"""Range proofs: verify that a sorted (key, value) slice is exactly the
trie's content between two boundary keys (parity target: the reference's
crates/common/trie/verify_range.rs — the snap-sync correctness core).

Algorithm (the geth/ethrex construction): load the boundary proofs into a
partial trie, prune every node strictly between the two boundary paths
(they will be recreated by the range insertions), insert the slice, and
require the recomputed root to equal the claimed root.  Soundness: any
omitted, added, or reordered key inside the range changes the root.
"""

from __future__ import annotations

from ..crypto.keccak import keccak256
from .trie import MissingNode, Trie, bytes_to_nibbles


class RangeProofError(Exception):
    pass


def verify_range(root_hash: bytes, keys: list[bytes], values: list[bytes],
                 proof_nodes: list[bytes]) -> bool:
    """Verify `keys`/`values` are the complete trie content in
    [keys[0], keys[-1]], using boundary proofs for the first and last key.

    GUARANTEE (read carefully): completeness is proven BETWEEN the two
    returned boundary keys only.  A server may truncate the tail of a
    requested range (returning a valid shorter range) — that is a liveness
    issue, not a soundness one: the snap client continues requesting from
    keys[-1], so omitted tails are simply re-requested.  Proving "nothing
    exists up to the requested limit" needs the origin/limit proof variant
    (later round, like absence proofs for empty ranges).

    Returns True on success; raises RangeProofError (or returns False for
    plain mismatches) on invalid input.
    """
    if not keys or len(keys) != len(values):
        raise RangeProofError("empty or mismatched range")
    for a, b in zip(keys, keys[1:]):
        if a >= b:
            raise RangeProofError("keys not sorted/unique")
    if any(not v for v in values):
        raise RangeProofError("empty value in range")

    store = {keccak256(n): bytes(n) for n in proof_nodes}
    trie = Trie.from_nodes(root_hash, store)
    left = bytes_to_nibbles(keys[0])
    right = bytes_to_nibbles(keys[-1])
    try:
        # boundary keys must be provable paths
        trie.get(keys[0])
        trie.get(keys[-1])
        trie._root = _prune(trie, trie._root, left, right)
        for k, v in zip(keys, values):
            trie.insert(k, bytes(v))
        return trie.root_hash() == root_hash
    except MissingNode as e:
        raise RangeProofError(f"incomplete proof: missing node {e}")


def _prune(t: Trie, node, l, r):
    """Remove everything strictly between paths l and r (exclusive of the
    boundary paths themselves)."""
    node = t._resolve(node)
    if node is None:
        return None
    kind = node[0]
    if kind == "branch":
        children = list(node[1])
        if l and r:
            li, ri = l[0], r[0]
            if li == ri:
                children[li] = _prune(t, children[li], l[1:], r[1:]) \
                    if children[li] is not None else None
            else:
                for i in range(li + 1, ri):
                    children[i] = None
                if children[li] is not None:
                    children[li] = _prune_side(t, children[li], l[1:],
                                               keep="left")
                if children[ri] is not None:
                    children[ri] = _prune_side(t, children[ri], r[1:],
                                               keep="right")
        return ("branch", children, node[2])
    if kind == "ext":
        p = node[1]
        cl = _cmp_path(p, l)
        cr = _cmp_path(p, r)
        if cl == 0 and cr == 0:
            child = _prune(t, node[2], l[len(p):], r[len(p):])
            return ("ext", p, child) if child is not None else None
        if cl > 0 and cr < 0:
            return None  # entirely inside the open interval
        if cl == 0:
            child = _prune_side(t, node[2], l[len(p):], keep="left")
            return ("ext", p, child) if child is not None else None
        if cr == 0:
            child = _prune_side(t, node[2], r[len(p):], keep="right")
            return ("ext", p, child) if child is not None else None
        return node  # outside the range on one side
    if kind == "leaf":
        full_cl = _cmp_path(node[1], l)
        full_cr = _cmp_path(node[1], r)
        # delete leaves strictly inside; boundary leaves are re-inserted
        # anyway, so deleting them too is harmless and simpler
        if full_cl >= 0 and full_cr <= 0:
            return None
        return node
    return node


def _prune_side(t: Trie, node, path, keep: str):
    """Along the kept boundary path, drop the siblings on the range side."""
    node = t._resolve(node)
    if node is None:
        return None
    kind = node[0]
    if kind == "branch":
        children = list(node[1])
        if path:
            idx = path[0]
            rng = range(idx + 1, 16) if keep == "left" else range(0, idx)
            for i in rng:
                children[i] = None
            if children[idx] is not None:
                children[idx] = _prune_side(t, children[idx], path[1:], keep)
        # snap-sync keys are fixed-length (keccak-hashed), so no key is a
        # prefix of another and branch values are always empty
        return ("branch", children, node[2])
    if kind == "ext":
        p = node[1]
        c = _cmp_path(p, path)
        if c == 0:
            child = _prune_side(t, node[2], path[len(p):], keep)
            return ("ext", p, child) if child is not None else None
        inside = (c > 0) if keep == "left" else (c < 0)
        return None if inside else node
    if kind == "leaf":
        c = _cmp_path(node[1], path)
        if c == 0:
            return None  # the boundary leaf itself: re-inserted later
        inside = (c > 0) if keep == "left" else (c < 0)
        return None if inside else node
    return node


def _cmp_path(p, q) -> int:
    """Compare path p against q: 0 if p is a prefix of q (or equal),
    else lexicographic -1/+1."""
    for a, b in zip(p, q):
        if a < b:
            return -1
        if a > b:
            return 1
    if len(p) <= len(q):
        return 0
    return 1  # p extends past q: p > q in trie order? (q prefix of p)