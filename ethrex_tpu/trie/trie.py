"""Merkle Patricia Trie (behavioral parity with the reference's
crates/common/trie — Trie::{get, insert, remove, hash, get_proof,
from_nodes}; re-implemented from the MPT specification).

In-memory node objects with lazy resolution from a node store, so the same
type serves three roles:
  * mutable state/storage tries (node store = dict, backed by Storage later)
  * witness tries for stateless execution (`from_nodes`: partial node sets;
    touching a missing node raises MissingNode — mirrors the guest program's
    pruned-trie behavior, reference crates/common/types/block_execution_witness.rs)
  * proof verification (a proof is just a small node set)

Nodes: None (empty), ("leaf", nibbles, value), ("ext", nibbles, child),
("branch", [16 children], value), ("ref", hash_or_inline) unresolved.
Child references: inline RLP if < 32 bytes else keccak256(rlp).
"""

from __future__ import annotations

from ..crypto.keccak import keccak256
from ..primitives import rlp
from ..primitives.account import EMPTY_TRIE_ROOT


class MissingNode(Exception):
    """A referenced node is absent from the node store (pruned witness)."""


def bytes_to_nibbles(key: bytes) -> tuple:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0xF)
    return tuple(out)


def hp_encode(nibbles: tuple, is_leaf: bool) -> bytes:
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2:
        first = bytes([(flag + 1) << 4 | nibbles[0]])
        rest = nibbles[1:]
    else:
        first = bytes([flag << 4])
        rest = nibbles
    return first + bytes(
        (rest[i] << 4) | rest[i + 1] for i in range(0, len(rest), 2)
    )


def hp_decode(data: bytes) -> tuple[tuple, bool]:
    if not data:
        raise ValueError("empty hex-prefix payload")
    flag = data[0] >> 4
    is_leaf = bool(flag & 2)
    nibbles = []
    if flag & 1:
        nibbles.append(data[0] & 0xF)
    for b in data[1:]:
        nibbles.append(b >> 4)
        nibbles.append(b & 0xF)
    return tuple(nibbles), is_leaf


class Trie:
    def __init__(self, nodes: dict | None = None):
        """nodes: hash -> encoded node (the backing store for refs)."""
        self._store = nodes if nodes is not None else {}
        self._root = None

    # ------------------------------------------------------------------
    # construction from a node set (witness / proof)
    # ------------------------------------------------------------------
    @classmethod
    def from_nodes(cls, root_hash: bytes, nodes: list[bytes] | dict,
                   share: bool = False) -> "Trie":
        """share=True uses the given dict as the live backing store (the
        node database of a Store) instead of copying it."""
        if isinstance(nodes, (list, tuple)):
            store = {keccak256(n): bytes(n) for n in nodes}
        else:  # dict-like (incl. recording wrappers)
            store = nodes if share else dict(nodes)
        t = cls(store)
        if root_hash == EMPTY_TRIE_ROOT:
            t._root = None
        else:
            t._root = ("ref", root_hash)
        return t

    # ------------------------------------------------------------------
    # node resolution / encoding
    # ------------------------------------------------------------------
    def _resolve(self, node):
        while node is not None and node[0] == "ref":
            ref = node[1]
            if isinstance(ref, list):
                node = self._decode_node(ref)          # inline embedded node
                continue
            enc = self._store.get(ref)
            if enc is None:
                raise MissingNode(ref.hex() if isinstance(ref, bytes) else str(ref))
            node = self._decode_node(rlp.decode(enc))
        return node

    @staticmethod
    def _decode_node(item):
        if isinstance(item, (bytes, bytearray)):
            if len(item) == 0:
                return None
            return ("ref", bytes(item))
        if len(item) == 17:
            children = []
            for c in item[:16]:
                if isinstance(c, (bytes, bytearray)) and len(c) == 0:
                    children.append(None)
                elif isinstance(c, list):
                    children.append(("ref", c))        # inline node
                else:
                    children.append(("ref", bytes(c)))
            value = bytes(item[16])
            return ("branch", children, value)
        if len(item) == 2:
            nibbles, is_leaf = hp_decode(bytes(item[0]))
            if is_leaf:
                return ("leaf", nibbles, bytes(item[1]))
            child = item[1]
            child = ("ref", child if isinstance(child, list) else bytes(child))
            return ("ext", nibbles, child)
        raise ValueError("malformed trie node")

    def _encode_node(self, node) -> bytes:
        return rlp.encode(self._node_fields(node))

    def _node_fields(self, node):
        kind = node[0]
        if kind == "leaf":
            return [hp_encode(node[1], True), node[2]]
        if kind == "ext":
            return [hp_encode(node[1], False), self._child_ref(node[2])]
        if kind == "branch":
            fields = [self._child_ref(c) if c is not None else b""
                      for c in node[1]]
            fields.append(node[2])
            return fields
        raise ValueError(f"cannot encode {kind}")

    def _child_ref(self, node):
        if node[0] == "ref":
            ref = node[1]
            return ref  # already hash bytes or inline field list
        enc = self._encode_node(node)
        if len(enc) < 32:
            return self._node_fields(node)  # embed inline
        h = keccak256(enc)
        self._store[h] = enc
        return h

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def get(self, key: bytes):
        return self._get(self._root, bytes_to_nibbles(key))

    def _get(self, node, path):
        node = self._resolve(node)
        if node is None:
            return None
        kind = node[0]
        if kind == "leaf":
            return node[2] if node[1] == path else None
        if kind == "ext":
            plen = len(node[1])
            if path[:plen] == node[1]:
                return self._get(node[2], path[plen:])
            return None
        # branch
        if not path:
            return node[2] or None
        child = node[1][path[0]]
        return self._get(child, path[1:]) if child is not None else None

    def insert(self, key: bytes, value: bytes):
        if not value:
            return self.remove(key)
        self._root = self._insert(self._root, bytes_to_nibbles(key),
                                  bytes(value))

    def _insert(self, node, path, value):
        node = self._resolve(node)
        if node is None:
            return ("leaf", path, value)
        kind = node[0]
        if kind == "leaf":
            if node[1] == path:
                return ("leaf", path, value)
            return self._split(node[1], node[2], path, value)
        if kind == "ext":
            epath = node[1]
            common = _common_prefix(epath, path)
            if common == len(epath):
                child = self._insert(node[2], path[len(epath):], value)
                return ("ext", epath, child)
            # split the extension
            children = [None] * 16
            ext_rest = epath[common + 1:]
            sub = node[2] if not ext_rest else ("ext", ext_rest, node[2])
            children[epath[common]] = sub
            if common < len(path):
                children[path[common]] = ("leaf", path[common + 1:], value)
                branch = ("branch", children, b"")
            else:
                branch = ("branch", children, value)
            if common:
                return ("ext", path[:common], branch)
            return branch
        # branch
        children, bval = list(node[1]), node[2]
        if not path:
            return ("branch", children, value)
        idx = path[0]
        child = children[idx]
        children[idx] = self._insert(child, path[1:], value)
        return ("branch", children, bval)

    def _split(self, lpath, lvalue, path, value):
        common = _common_prefix(lpath, path)
        children = [None] * 16
        bval = b""
        for p, v in ((lpath, lvalue), (path, value)):
            rest = p[common:]
            if not rest:
                bval = v
            else:
                children[rest[0]] = ("leaf", rest[1:], v)
        branch = ("branch", children, bval)
        if common:
            return ("ext", lpath[:common], branch)
        return branch

    def remove(self, key: bytes):
        self._root = self._remove(self._root, bytes_to_nibbles(key))

    def _remove(self, node, path):
        node = self._resolve(node)
        if node is None:
            return None
        kind = node[0]
        if kind == "leaf":
            return None if node[1] == path else node
        if kind == "ext":
            plen = len(node[1])
            if path[:plen] != node[1]:
                return node
            child = self._remove(node[2], path[plen:])
            if child is None:
                return None
            return self._merge_ext(node[1], child)
        # branch
        children, bval = list(node[1]), node[2]
        if not path:
            bval = b""
        else:
            idx = path[0]
            if children[idx] is None:
                return node
            children[idx] = self._remove(children[idx], path[1:])
        return self._collapse_branch(children, bval)

    def _merge_ext(self, prefix, child):
        child = self._resolve(child)
        kind = child[0]
        if kind == "leaf":
            return ("leaf", prefix + child[1], child[2])
        if kind == "ext":
            return ("ext", prefix + child[1], child[2])
        return ("ext", prefix, child)

    def _collapse_branch(self, children, bval):
        live = [(i, c) for i, c in enumerate(children) if c is not None]
        if len(live) == 0:
            return ("leaf", (), bval) if bval else None
        if len(live) == 1 and not bval:
            idx, child = live[0]
            return self._merge_ext((idx,), child)
        return ("branch", children, bval)

    # ------------------------------------------------------------------
    # hashing / commitment
    # ------------------------------------------------------------------
    def root_hash(self) -> bytes:
        if self._root is None:
            return EMPTY_TRIE_ROOT
        node = self._root
        if node[0] == "ref" and isinstance(node[1], bytes):
            return node[1]
        enc = self._encode_node(self._resolve(node))
        h = keccak256(enc)
        self._store[h] = enc
        return h

    def commit(self) -> bytes:
        """Encode all in-memory nodes into the store; return the root hash."""
        root = self.root_hash()
        if self._root is not None:
            self._commit_node(self._root)
        return root

    def _commit_node(self, node):
        if node is None or node[0] == "ref":
            return
        if node[0] in ("ext",):
            self._commit_node(node[2])
        elif node[0] == "branch":
            for c in node[1]:
                if c is not None:
                    self._commit_node(c)
        enc = self._encode_node(node)
        if len(enc) >= 32:
            self._store[keccak256(enc)] = enc

    # ------------------------------------------------------------------
    # proofs
    # ------------------------------------------------------------------
    def get_proof(self, key: bytes) -> list[bytes]:
        """Encoded nodes on the path from root to key (inclusive)."""
        proof = []
        node = self._root
        path = bytes_to_nibbles(key)
        while node is not None:
            node = self._resolve(node)
            if node is None:
                break
            proof.append(self._encode_node(node))
            kind = node[0]
            if kind == "leaf":
                break
            if kind == "ext":
                plen = len(node[1])
                if path[:plen] != node[1]:
                    break
                path = path[plen:]
                node = node[2]
            else:
                if not path:
                    break
                node = node[1][path[0]]
                path = path[1:]
        return proof

    def items(self):
        """Iterate (nibble_path, value) pairs (debug / range helpers)."""
        return list(self.iter_from(b""))

    def iter_from(self, start_key: bytes, max_items: int | None = None):
        """Ordered (nibble_path, value) list starting at start_key —
        O(window + depth), no full-trie materialization (snap serving).

        `bound` below is the remaining lower-bound nibble path relative to
        the current node; () means "emit everything in this subtree".
        """
        out = []

        def walk(node, prefix, bound):
            if max_items is not None and len(out) >= max_items:
                return
            node = self._resolve(node)
            if node is None:
                return
            kind = node[0]
            if kind == "leaf":
                if not bound or tuple(node[1]) >= tuple(bound):
                    out.append((prefix + node[1], node[2]))
                return
            if kind == "ext":
                p = node[1]
                if bound:
                    m = min(len(p), len(bound))
                    if tuple(p[:m]) < tuple(bound[:m]):
                        return          # subtree entirely before the bound
                    if tuple(p[:m]) > tuple(bound[:m]):
                        sub = ()        # entirely after: emit everything
                    else:
                        sub = tuple(bound[len(p):])
                else:
                    sub = ()
                walk(node[2], prefix + p, sub)
                return
            # branch: the branch value's key is a strict prefix of any
            # bounded start key, so it is only emitted when unbounded
            if node[2] and not bound:
                out.append((prefix, node[2]))
            lo = bound[0] if bound else 0
            for i in range(lo, 16):
                child = node[1][i]
                if child is None:
                    continue
                walk(child, prefix + (i,),
                     tuple(bound[1:]) if (bound and i == lo) else ())
                if max_items is not None and len(out) >= max_items:
                    return

        walk(self._root, (),
             bytes_to_nibbles(start_key) if start_key else ())
        return out


def _common_prefix(a, b) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


def trie_root_from_items(items: list[tuple[bytes, bytes]]) -> bytes:
    """Root of a fresh trie over (key, value) pairs — tx/receipt/withdrawal
    roots (key = rlp(index))."""
    t = Trie()
    for k, v in items:
        t.insert(k, v)
    return t.root_hash()


def verify_proof(root_hash: bytes, key: bytes, proof: list[bytes]):
    """Verify a Merkle proof; returns (verified: bool, value|None)."""
    store = {keccak256(n): bytes(n) for n in proof}
    t = Trie.from_nodes(root_hash, store)
    try:
        value = t.get(key)
    except MissingNode:
        return False, None
    return True, value
