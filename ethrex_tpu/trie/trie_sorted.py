"""Sorted bulk MPT construction: build a trie bottom-up from an ordered
(key, value) stream in one pass — no per-insert path walks.

The seat of the reference's `trie_sorted.rs` (crates/common/trie/
trie_sorted.rs, used by snap-sync finalize): range downloads arrive
key-sorted, so the trie's shape can be derived divide-and-conquer — the
common nibble prefix of a sorted slice becomes an extension, the first
divergent nibble splits it into branch children, and single items become
leaves.  Every node is constructed exactly once (O(n) constructions vs
O(n·depth) re-walks for repeated insert()), and the result is
byte-identical to incremental insertion (tested against Trie.insert over
randomized sets).
"""

from __future__ import annotations

import time as _time

from .trie import EMPTY_TRIE_ROOT, Trie, bytes_to_nibbles


def _note_trie_commit(seconds: float) -> None:
    try:
        from ..perf.profiler import record_stage
        record_stage("trie", "sorted_commit", seconds)
    except Exception:
        pass


def _build(items: list, lo: int, hi: int, depth: int):
    """Node for the sorted slice items[lo:hi] below `depth` nibbles.
    items = [(nibbles_tuple, value_bytes)]."""
    if hi - lo == 1:
        nibs, value = items[lo]
        return ("leaf", nibs[depth:], value)
    first = items[lo][0]
    last = items[hi - 1][0]
    # common prefix beyond depth (sorted slice: first/last bound all keys)
    cp = 0
    maxcp = min(len(first), len(last)) - depth
    while cp < maxcp and first[depth + cp] == last[depth + cp]:
        cp += 1
    if cp > 0:
        child = _build(items, lo, hi, depth + cp)
        return ("ext", first[depth:depth + cp], child)
    # branch at this depth: group by nibble; a key that ends exactly here
    # supplies the branch value
    children: list = [None] * 16
    bval = b""
    i = lo
    if len(first) == depth:
        bval = items[lo][1]
        i += 1
    while i < hi:
        nib = items[i][0][depth]
        j = i + 1
        while j < hi and items[j][0][depth] == nib:
            j += 1
        children[nib] = _build(items, i, j, depth + 1)
        i = j
    return ("branch", children, bval)


def build_from_sorted(pairs, nodes: dict | None = None,
                      use_native: bool = True):
    """Build an MPT from sorted, de-duplicated (key, value) pairs.

    Returns (root_hash, trie) with every node encoded into `nodes` (a
    shared node table when given).  Pairs must be strictly increasing by
    key and carry non-empty values; violations raise ValueError.

    When the C++ MPT engine is available the batch goes through it (the
    same engine the importer's merkleize step uses — ~an order of
    magnitude faster than Python node construction); the Python
    bottom-up builder is the fallback and the differential reference.
    """
    store = nodes if nodes is not None else {}
    items = []
    prev = None
    for key, value in pairs:
        if prev is not None and key <= prev:
            raise ValueError("keys must be strictly increasing")
        if not value:
            raise ValueError("empty value in sorted build")
        prev = key
        items.append((bytes(key), bytes(value)))
    if not items:
        return EMPTY_TRIE_ROOT, Trie(store)
    t0 = _time.perf_counter()
    if use_native:
        from . import native_mpt

        if native_mpt.available():
            eng = native_mpt.NativeMpt()
            root = eng.apply(store, EMPTY_TRIE_ROOT, items)
            _note_trie_commit(_time.perf_counter() - t0)
            return root, Trie.from_nodes(root, store, share=True)
    trie = Trie(store)
    trie._root = _build([(bytes_to_nibbles(k), v) for k, v in items],
                        0, len(items), 0)
    root = trie.commit()
    _note_trie_commit(_time.perf_counter() - t0)
    return root, trie
