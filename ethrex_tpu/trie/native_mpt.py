"""ctypes wrapper for the native MPT engine (native/mpt.cpp) — the
merkleize hot path of block import.

The engine owns a persistent node map mirroring the Python node table and
pulls nodes it lacks through a resolver upcall — one callback per unique
node over the engine's lifetime, so repeated applies touch Python only
for genuinely new paths.  Differentially tested against trie/trie.py
(tests/test_native_mpt.py), which stays the behavioral reference.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading

from ..crypto.keccak import keccak256
from .trie import MissingNode

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native"))
_SO_PATH = os.path.join(_NATIVE_DIR, "libmpt.so")
_SRC = [os.path.join(_NATIVE_DIR, "mpt.cpp"),
        os.path.join(_NATIVE_DIR, "keccak.c")]

_lib = None
_lock = threading.Lock()
_RESOLVER_TYPE = ctypes.CFUNCTYPE(ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_ubyte))


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib

        def build():
            # -x c: keccak.c must compile as C (unmangled keccak256)
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 "-o", _SO_PATH, _SRC[0], "-x", "c", _SRC[1]],
                check=True, capture_output=True)

        def bind():
            lib = ctypes.CDLL(_SO_PATH)
            lib.mpt_new.restype = ctypes.c_void_p
            lib.mpt_free.argtypes = [ctypes.c_void_p]
            lib.mpt_set_resolver.argtypes = [ctypes.c_void_p,
                                             _RESOLVER_TYPE]
            lib.mpt_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_size_t]
            lib.mpt_load.restype = ctypes.c_int
            lib.mpt_apply.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_char_p]
            lib.mpt_apply.restype = ctypes.c_int
            lib.mpt_missing.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_size_t]
            lib.mpt_missing.restype = ctypes.c_int
            lib.mpt_fresh_size.argtypes = [ctypes.c_void_p]
            lib.mpt_fresh_size.restype = ctypes.c_size_t
            lib.mpt_take_fresh.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_size_t]
            lib.mpt_take_fresh.restype = ctypes.c_int
            lib.mpt_node_count.argtypes = [ctypes.c_void_p]
            lib.mpt_node_count.restype = ctypes.c_size_t
            return lib

        try:
            newest_src = max(os.path.getmtime(p) for p in _SRC)
            if not os.path.exists(_SO_PATH) or \
                    os.path.getmtime(_SO_PATH) < newest_src:
                build()
            try:
                _lib = bind()
            except OSError:
                build()
                _lib = bind()
        except (OSError, subprocess.CalledProcessError):
            _lib = False
        return _lib


def available() -> bool:
    return bool(_load())


class NativeMpt:
    """One engine instance per node table (Store or witness)."""

    def __init__(self):
        lib = _load()
        if not lib:
            raise RuntimeError("native mpt unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.mpt_new())
        self._known: set[bytes] = set()
        self._table = None  # active node table during apply

        def _resolve(hash_ptr):
            h = bytes(hash_ptr[0:32])
            raw = self._table.get(h) if self._table is not None else None
            if raw is None:
                return 0
            raw = bytes(raw)
            buf = struct.pack("<I", len(raw)) + raw
            self._lib.mpt_load(self._h, buf, len(buf))
            self._known.add(h)
            return 1

        # keep a reference: ctypes callbacks die with their wrapper object
        self._resolver_cb = _RESOLVER_TYPE(_resolve)
        lib.mpt_set_resolver(self._h, self._resolver_cb)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.mpt_free(h)
            self._h = None

    def _feed(self, raws: list[bytes]) -> None:
        raws = [r for r in raws
                if keccak256(r) not in self._known]
        if not raws:
            return
        buf = b"".join(struct.pack("<I", len(r)) + r for r in raws)
        rc = self._lib.mpt_load(self._h, buf, len(buf))
        if rc < 0:
            raise RuntimeError("mpt_load rejected input")
        for r in raws:
            self._known.add(keccak256(r))

    def apply(self, table, root: bytes, ops: list[tuple[bytes, bytes]]
              ) -> bytes:
        """Apply ordered (key, value) ops (empty value = delete) against
        `root`; commit; persist new nodes back into `table`; return the
        new root.  Raises MissingNode exactly like the Python trie when
        the table lacks a required node."""
        lib = self._lib
        buf = b"".join(
            struct.pack("<I", len(k)) + k + struct.pack("<I", len(v)) + v
            for k, v in ops)
        out = ctypes.create_string_buffer(32)
        self._table = table
        try:
            rc = lib.mpt_apply(self._h, root, buf, len(buf), out)
        finally:
            self._table = None
        if rc == 1:
            miss_buf = ctypes.create_string_buffer(32 * 64)
            n = lib.mpt_missing(self._h, miss_buf, len(miss_buf))
            h = miss_buf.raw[:32] if n else b""
            raise MissingNode(h.hex())
        if rc != 0:
            raise RuntimeError(f"mpt_apply failed rc={rc}")
        size = lib.mpt_fresh_size(self._h)
        if size:
            fresh = ctypes.create_string_buffer(size)
            n = lib.mpt_take_fresh(self._h, fresh, size)
            if n < 0:
                raise RuntimeError("mpt_take_fresh overflow")
            pos = 0
            raw = fresh.raw
            for _ in range(n):
                (ln,) = struct.unpack_from("<I", raw, pos)
                pos += 4
                node = raw[pos:pos + ln]
                pos += ln
                h = keccak256(node)
                table[h] = node
                self._known.add(h)
        return bytes(out.raw)
