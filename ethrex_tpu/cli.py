"""ethrex-tpu CLI (parity target: cmd/ethrex/cli.rs — ~90 clap flags with
ETHREX_* env-var mirrors, plus the removedb / import / export /
compute-state-root subcommands, cli.rs:562-676).

Every flag reads its default from the matching ETHREX_* environment
variable (the reference's clap `env` mirrors); explicit CLI arguments win.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from .node import Node
from .primitives.genesis import Genesis
from .rpc.server import RpcServer

DEV_GENESIS = {
    "config": {
        "chainId": 1337,
        "homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
        "byzantiumBlock": 0, "constantinopleBlock": 0, "petersburgBlock": 0,
        "istanbulBlock": 0, "berlinBlock": 0, "londonBlock": 0,
        "mergeNetsplitBlock": 0, "terminalTotalDifficulty": 0,
        "shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0,
    },
    "alloc": {
        # dev account (well-known test key
        # 0x45a915e4d060149eb4365960e6a7a45f334393093061116b197e3240065ff2d8)
        "0xa94f5374fce5edbc8e2a8697c15331677e6ebf0b": {
            "balance": "0xd3c21bcecceda1000000"},
    },
    "gasLimit": "0x1c9c380",
    "baseFeePerGas": "0x7",
    "timestamp": "0x0",
}


def _env(name: str, default=None):
    return os.environ.get(f"ETHREX_{name}", default)


def _env_int(name: str, default: int) -> int:
    v = _env(name)
    return int(v) if v is not None else default


def _env_float(name: str, default: float) -> float:
    v = _env(name)
    return float(v) if v is not None else default


def _add_node_flags(parser: argparse.ArgumentParser):
    parser.add_argument("--dev", action="store_true",
                        default=_env("DEV") == "1",
                        help="dev mode: auto-produce blocks from the mempool")
    parser.add_argument("--datadir", default=_env("DATADIR"),
                        help="persist the chain in <datadir>/chain.db "
                             "(native C++ KV store); default: in-memory")
    parser.add_argument("--network", "--genesis", dest="genesis",
                        default=_env("NETWORK"),
                        help="network preset (mainnet|sepolia|hoodi, with "
                             "embedded genesis + bootnodes) or a genesis "
                             "JSON path")
    parser.add_argument("--http.addr", dest="http_addr",
                        default=_env("HTTP_ADDR", "127.0.0.1"))
    parser.add_argument("--http.port", dest="http_port", type=int,
                        default=_env_int("HTTP_PORT", 8545))
    parser.add_argument("--ws.port", dest="ws_port", type=int,
                        default=_env_int("WS_PORT", 0),
                        help="WebSocket JSON-RPC + subscriptions (0 = off)")
    parser.add_argument("--rpc-backlog", dest="rpc_backlog", type=int,
                        default=_env_int("RPC_BACKLOG", 128),
                        help="TCP listen backlog for the RPC listeners "
                             "(HTTP, Engine API, WebSocket); saturation "
                             "shows up as rpc_connections_reset_total")
    parser.add_argument("--rpc-executor-workers",
                        dest="rpc_executor_workers", type=int,
                        default=_env_int("RPC_EXECUTOR_WORKERS", 0),
                        help="handler threads behind the asyncio RPC "
                             "front door (0 = ETHREX_RPC_EXECUTOR_WORKERS "
                             "env or built-in default); the event loop "
                             "never blocks, handlers run here")
    parser.add_argument("--rpc-max-batch", dest="rpc_max_batch", type=int,
                        default=_env_int("RPC_MAX_BATCH", 0),
                        help="largest JSON-RPC batch array accepted "
                             "(0 = ETHREX_RPC_MAX_BATCH env or built-in "
                             "default); larger arrays get a typed -32600 "
                             "error, never a dropped connection")
    parser.add_argument("--block-time", dest="block_time", type=float,
                        default=_env_float("BLOCK_TIME", 1.0),
                        help="dev block production interval (s)")
    parser.add_argument("--coinbase",
                        default=_env("COINBASE", "0x" + "00" * 20))
    parser.add_argument("--metrics.port", dest="metrics_port", type=int,
                        default=_env_int("METRICS_PORT", 0),
                        help="Prometheus /metrics port (0 = off)")
    parser.add_argument("--log-level", dest="log_level",
                        choices=("debug", "info", "warning", "error"),
                        default=_env("LOG_LEVEL", "info"),
                        help="structured logger threshold")
    parser.add_argument("--log-json", dest="log_json",
                        action="store_true",
                        default=_env("LOG_JSON") == "1",
                        help="emit logs as one JSON object per line "
                             "(with trace/span IDs when in context)")
    parser.add_argument("--authrpc.addr", dest="authrpc_addr",
                        default=_env("AUTHRPC_ADDR", "127.0.0.1"))
    parser.add_argument("--authrpc.port", dest="authrpc_port", type=int,
                        default=_env_int("AUTHRPC_PORT", 0),
                        help="Engine API port (0 = off)")
    parser.add_argument("--authrpc.jwtsecret", dest="jwt_path",
                        default=_env("AUTHRPC_JWTSECRET"),
                        help="path to a hex-encoded 32-byte JWT secret")
    parser.add_argument("--p2p.enabled", dest="p2p_enabled",
                        action="store_true",
                        default=_env("P2P_ENABLED") == "1")
    parser.add_argument("--p2p.addr", dest="p2p_addr",
                        default=_env("P2P_ADDR", "0.0.0.0"))
    parser.add_argument("--p2p.port", dest="p2p_port", type=int,
                        default=_env_int("P2P_PORT", 30303))
    parser.add_argument("--discovery.port", dest="discovery_port", type=int,
                        default=_env_int("DISCOVERY_PORT", 30303),
                        help="discv4 UDP port")
    parser.add_argument("--p2p-timeout", dest="p2p_timeout", type=float,
                        default=_env_float("P2P_TIMEOUT", 10.0),
                        help="per-request p2p timeout CEILING (s): the "
                        "adaptive phi-accrual estimator tightens below "
                        "this per peer, never above it; also bounds the "
                        "dial/handshake (docs/P2P_RESILIENCE.md)")
    parser.add_argument("--p2p-retries", dest="p2p_retries", type=int,
                        default=_env_int("P2P_RETRIES", 2),
                        help="retries per p2p request after the first "
                        "attempt, with jittered exponential backoff; "
                        "0 disables retry (docs/P2P_RESILIENCE.md)")
    parser.add_argument("--bootnodes", default=_env("BOOTNODES", ""),
                        help="comma-separated enode URLs")
    parser.add_argument("--syncmode", choices=("full", "snap"),
                        default=_env("SYNCMODE", "full"))
    parser.add_argument("--kzg-setup", dest="kzg_setup",
                        default=_env("KZG_SETUP"),
                        help="path to the ceremony trusted_setup.json for "
                        "the 0x0a precompile; CONSENSUS-CRITICAL: every "
                        "node of a chain must use the same setup (default: "
                        "the deterministic dev setup, crypto/kzg.py)")
    parser.add_argument("--node-config", dest="node_config",
                        default=_env("NODE_CONFIG"),
                        help="JSON file persisting known peers across "
                        "restarts (reference: node_config.json)")
    parser.add_argument("--shutdown-deadline", dest="shutdown_deadline",
                        type=float,
                        default=_env_float("SHUTDOWN_DEADLINE", 30.0),
                        help="bounded SIGTERM/SIGINT drain deadline (s): "
                        "RPC stops, writers join, in-flight proof submits "
                        "land, every backend flushes and closes")
    parser.add_argument("--debug-snapshot-dir", dest="debug_snapshot_dir",
                        default=_env("DEBUG_SNAPSHOT_DIR"),
                        help="flight-recorder destination: debug snapshot "
                        "bundles (metrics, windows, alerts, traces, TPU "
                        "telemetry) written here on fatal actor errors, "
                        "shutdown, and ethrex_debug_snapshot calls")
    parser.add_argument("--profile-dir", dest="profile_dir",
                        default=_env("PROFILE_DIR"),
                        help="opt-in continuous profiler destination: "
                        "jax.profiler device traces (TensorBoard/XProf "
                        "format) captured around each prove land here; "
                        "unset keeps device tracing off (zero overhead)")
    parser.add_argument("--sender-workers", dest="sender_workers", type=int,
                        default=_env_int("SENDER_WORKERS", 0),
                        help="thread-pool size for batched sender "
                        "recovery (native secp256k1 engine); 0 = "
                        "min(8, cpu_count)")
    parser.add_argument("--executable-cache-dir",
                        dest="executable_cache_dir",
                        default=_env("EXEC_CACHE_DIR"),
                        help="on-disk serialized-executable cache for AOT "
                        "prover kernels (utils/exec_cache): a restarted "
                        "prover hydrates compiled programs from here in "
                        "deserialize time instead of recompiling — ship "
                        "it in a deploy image to kill cold-start "
                        "(docs/PERFORMANCE.md); default: a "
                        "host-fingerprinted /tmp directory")


def _enable_compile_caches(args):
    """Production startup wiring for the two compile caches: the XLA
    persistent compilation cache (utils/jax_cache, HLO-level) and the
    serialized-executable store (utils/exec_cache, whole-program level —
    the prover cold-start killer).  Never fatal: a node that cannot set
    up caching still serves."""
    try:
        from .utils import exec_cache, jax_cache

        if getattr(args, "executable_cache_dir", None):
            exec_cache.set_cache_dir(args.executable_cache_dir)
        jax_cache.enable_persistent_cache()
    except Exception as e:  # noqa: BLE001 — caching is an optimization
        print(f"compile-cache setup skipped: {e}", file=sys.stderr)


def _load_genesis(args) -> Genesis | None:
    if args.genesis:
        from .config import is_preset, load_network

        if is_preset(args.genesis):
            genesis, bootnodes = load_network(args.genesis)
            # preset bootnodes seed the dial list unless overridden
            if hasattr(args, "bootnodes") and not args.bootnodes:
                args.bootnodes = ",".join(bootnodes)
            return genesis
        with open(args.genesis) as f:
            return Genesis.from_json(json.load(f))
    if args.dev:
        return Genesis.from_json(DEV_GENESIS)
    return None


def _open_store(datadir: str | None):
    if not datadir:
        return None
    from .storage.persistent import PersistentBackend
    from .storage.store import Store

    os.makedirs(datadir, exist_ok=True)
    store = Store(PersistentBackend(os.path.join(datadir, "chain.db")))
    # diff layering: trie nodes reach the durable log only once finalized
    # (stale branches stay RAM-only; storage/layering.py)
    store.enable_layering()
    return store


def _decode_chain_file(path: str):
    from .primitives import rlp
    from .primitives.block import Block, BlockBody, BlockHeader

    with open(path, "rb") as f:
        rest = f.read()
    blocks = []
    while rest:
        item, rest = rlp.decode_prefix(rest)
        blocks.append(Block(BlockHeader.decode_fields(item[0]),
                            BlockBody.from_fields(item[1:])))
    return blocks


def cmd_import(args) -> int:
    """`ethrex import <chain.rlp>` — bulk-import an RLP chain file and
    report throughput (cli.rs `import` + tooling/import_benchmark)."""
    import time

    genesis = _load_genesis(args)
    if genesis is None:
        print("import requires --network <genesis.json> (or --dev)",
              file=sys.stderr)
        return 1
    node = Node(genesis, store=_open_store(args.datadir))
    blocks = _decode_chain_file(args.file)
    t0 = time.perf_counter()
    node.chain.add_blocks_in_batch(blocks)
    # make the imported tip canonical (the reference's import subcommand
    # ends with a fork-choice update to the last imported block)
    from .blockchain.fork_choice import apply_fork_choice

    tip = blocks[-1].hash
    apply_fork_choice(node.store, tip, tip, tip)
    dt = time.perf_counter() - t0
    gas = sum(b.header.gas_used for b in blocks)
    print(f"imported {len(blocks)} blocks, {gas / 1e6:.1f} Mgas "
          f"in {dt:.2f}s = {gas / dt / 1e6:.1f} Mgas/s")
    node.store.flush()
    return 0


def cmd_export(args) -> int:
    """`ethrex export <out.rlp>` — canonical chain to an RLP file."""
    from .primitives import rlp

    genesis = _load_genesis(args)
    if genesis is None:
        print("export requires --network/--dev", file=sys.stderr)
        return 1
    node = Node(genesis, store=_open_store(args.datadir))
    last = args.last if args.last is not None else \
        node.store.latest_number()
    with open(args.file, "wb") as f:
        for n in range(args.first, last + 1):
            block = node.store.get_canonical_block(n)
            if block is None:
                print(f"missing canonical block {n}", file=sys.stderr)
                return 1
            f.write(block.encode())
    print(f"exported blocks {args.first}..{last} to {args.file}")
    return 0


def cmd_removedb(args) -> int:
    """`ethrex removedb` — delete the datadir (cli.rs removedb)."""
    import shutil

    if not args.datadir:
        print("removedb requires --datadir", file=sys.stderr)
        return 1
    if not os.path.isdir(args.datadir):
        print(f"no database at {args.datadir}")
        return 0
    if not args.force:
        resp = input(f"delete {args.datadir}? [y/N] ")
        if resp.strip().lower() not in ("y", "yes"):
            print("aborted")
            return 1
    shutil.rmtree(args.datadir)
    print(f"removed {args.datadir}")
    return 0


def cmd_compute_state_root(args) -> int:
    """`ethrex compute-state-root --network genesis.json`."""
    genesis = _load_genesis(args)
    if genesis is None:
        print("compute-state-root requires --network", file=sys.stderr)
        return 1
    from .storage.store import Store

    header = Store().init_genesis(genesis)
    print(f"state root: 0x{header.state_root.hex()}")
    print(f"genesis hash: 0x{header.hash.hex()}")
    return 0


def _parse_enode(url: str):
    # enode://<128-hex pubkey>@host:port
    if not url.startswith("enode://"):
        raise ValueError(f"not an enode URL: {url}")
    rest = url[len("enode://"):]
    pub_hex, _, addr = rest.partition("@")
    host, _, port = addr.partition(":")
    from .p2p.rlpx import _pub_from_bytes

    return _pub_from_bytes(bytes.fromhex(pub_hex)), host, int(port or 30303)


def run_node(args) -> int:
    _enable_compile_caches(args)
    if args.kzg_setup:
        from .crypto import kzg

        kzg.set_setup(kzg.TrustedSetup.from_ceremony_json(args.kzg_setup))

    genesis = _load_genesis(args)
    if genesis is None:
        print("either --dev or --network <genesis.json> is required",
              file=sys.stderr)
        return 1

    coinbase = bytes.fromhex(args.coinbase.removeprefix("0x"))
    store = _open_store(args.datadir)
    node = Node(genesis, coinbase=coinbase, store=store)
    rpc_tuning = {
        "executor_workers": args.rpc_executor_workers or None,
        "max_batch": args.rpc_max_batch or None,
    }
    server = RpcServer(node, args.http_addr, args.http_port,
                       backlog=args.rpc_backlog, **rpc_tuning).start()
    print(f"genesis hash: 0x{node.genesis_header.hash.hex()}")
    print(f"JSON-RPC listening on http://{args.http_addr}:{server.port}")
    authrpc = None
    if args.authrpc_port:
        if args.jwt_path:
            with open(args.jwt_path) as f:
                jwt_secret = bytes.fromhex(
                    f.read().strip().removeprefix("0x"))
        else:
            # never expose an unauthenticated consensus-control endpoint:
            # generate a secret like the reference does and tell the user
            import secrets as _secrets

            jwt_secret = _secrets.token_bytes(32)
            print(f"generated JWT secret (pass to your CL): "
                  f"{jwt_secret.hex()}")
        authrpc = RpcServer(node, args.authrpc_addr, args.authrpc_port,
                            jwt_secret=jwt_secret, engine=True,
                            backlog=args.rpc_backlog, **rpc_tuning).start()
        print(f"Engine API listening on http://{args.authrpc_addr}:"
              f"{authrpc.port}")
    ws = None
    if args.ws_port:
        from .rpc.websocket import WsServer

        ws = WsServer(server, args.http_addr, args.ws_port,
                      backlog=args.rpc_backlog).start()
        print(f"WebSocket JSON-RPC on ws://{args.http_addr}:{ws.port}")
    metrics = None
    if args.metrics_port:
        from .utils.metrics import MetricsServer

        metrics = MetricsServer(args.http_addr, args.metrics_port).start()
        print(f"metrics on http://{args.http_addr}:{metrics.port}/metrics")

    p2p = None
    if args.p2p_enabled:
        from .p2p.connection import P2PServer

        p2p = P2PServer(node, host=args.p2p_addr, port=args.p2p_port,
                        timeout=args.p2p_timeout,
                        retries=args.p2p_retries)
        p2p.start()
        from .p2p.rlpx import _pub_bytes

        print(f"p2p listening on {p2p.host}:{p2p.port} "
              f"(enode pubkey {_pub_bytes(p2p.pub).hex()})")
        peers = []
        if args.node_config and os.path.exists(args.node_config):
            with open(args.node_config) as f:
                peers = json.load(f).get("known_peers", [])
        for url in filter(None, args.bootnodes.split(",")):
            peers.append(url.strip())
        for url in peers:
            try:
                pub, host, port = _parse_enode(url)
                p2p.dial(host, port, pub)
            except (ValueError, OSError) as e:
                print(f"bootnode {url}: {e}", file=sys.stderr)

    if args.dev:
        node.start_dev_producer(args.block_time)
        print(f"dev producer running (block time {args.block_time}s)")

    # observability: sampler + SLO alerts + optional flight recorder
    from .utils import snapshot
    from .utils.alerts import build_default_engine

    if args.debug_snapshot_dir:
        snapshot.configure(args.debug_snapshot_dir)
    if getattr(args, "profile_dir", None):
        from .perf import profiler as perf_profiler

        perf_profiler.configure(args.profile_dir)
    if getattr(args, "sender_workers", 0):
        from .blockchain import sender_recovery

        sender_recovery.configure(args.sender_workers)
    node.start_telemetry(alerts=build_default_engine(node))

    # coordinated drain (utils/shutdown.py): rpc -> producer -> flush+close
    from .utils.shutdown import build_node_shutdown

    manager = build_node_shutdown(
        node=node, servers=[server, authrpc, ws, metrics],
        stores=[node.store],
        deadline=args.shutdown_deadline)
    stop_event = _install_signal_handlers(stop_event=threading.Event())
    try:
        while not stop_event.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        # persist known peers (reference: node_config.json on shutdown)
        if p2p is not None and args.node_config:
            known = []
            for peer in p2p.peers:
                try:
                    host, port = peer.sock.getpeername()[:2]
                    known.append(
                        f"enode://{bytes(peer.remote_pub).hex()}"
                        f"@{host}:{port}")
                except (OSError, AttributeError, TypeError):
                    continue
            with open(args.node_config, "w") as f:
                json.dump({"known_peers": known}, f)
        report = manager.run()
        print(f"shutdown complete in {report['durationSeconds']:.2f}s "
              f"({len(report['steps'])} steps)")
    return 0


def _install_signal_handlers(stop_event: threading.Event):
    """SIGTERM/SIGINT set the stop event; the main loop then runs the
    coordinated drain.  Falls back silently off the main thread (tests
    drive the manager directly)."""
    def _on_signal(signum, frame):
        print(f"received {signal.Signals(signum).name}; draining...")
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
    except ValueError:
        pass
    return stop_event


def run_l2(args) -> int:
    """`ethrex-tpu l2`: launch the sequencer stack — L2 node + block
    producer + committer + proof coordinator + proof sender (+ optional
    in-process prover) against a datadir with durable checkpoints
    (reference: cmd/ethrex/cli.rs:562-676 `l2` subcommand tree +
    crates/l2/sequencer/mod.rs start_l2)."""
    from .l2.l1_client import InMemoryL1
    from .l2.rollup_store import PersistentRollupStore, RollupStore
    from .l2.sequencer import Sequencer, SequencerConfig

    _enable_compile_caches(args)
    genesis = _load_genesis(args)
    if genesis is None:
        print("either --dev or --network <genesis.json> is required",
              file=sys.stderr)
        return 1
    coinbase = bytes.fromhex(args.coinbase.removeprefix("0x"))
    store = _open_store(args.datadir)
    node = Node(genesis, coinbase=coinbase, store=store)

    if args.datadir:
        rollup = PersistentRollupStore(
            os.path.join(args.datadir, "rollup.db"))
    else:
        rollup = RollupStore()

    prover_types = tuple(t for t in args.l2_provers.split(",") if t)
    if args.l1_url:
        from .l2.eth_client import EthClient
        from .l2.l1_contract import RpcL1Client

        if not (args.l1_contract and args.l1_secret):
            print("--l1.contract and --l1.secret are required with "
                  "--l1.url", file=sys.stderr)
            return 1
        l1 = RpcL1Client(
            EthClient(args.l1_url),
            bytes.fromhex(args.l1_contract.removeprefix("0x")),
            int(args.l1_secret.removeprefix("0x"), 16),
            needed_prover_types=list(prover_types))
    elif args.datadir:
        from .l2.l1_client import PersistentInMemoryL1

        l1 = PersistentInMemoryL1(
            os.path.join(args.datadir, "l1_dev.json"),
            needed_prover_types=list(prover_types))
        print("l2: using datadir-persisted dev L1 "
              "(pass --l1.url for a real one)")
    else:
        l1 = InMemoryL1(needed_prover_types=list(prover_types))
        print("l2: using in-process dev L1 (pass --l1.url for a real one)")

    ha_role = getattr(args, "ha_role", None)
    if ha_role and not l1.supports_leases():
        # refusing beats running unfenced: without the lease cell a
        # second sequencer could double-commit (docs/SEQUENCER_HA.md)
        print("--ha-role requires an L1 client with leader-lease support "
              "(the RPC L1 client has no lease cell yet)", file=sys.stderr)
        return 1
    cfg = SequencerConfig(
        block_time=args.block_time or 1.0,
        commit_interval=args.commit_interval,
        needed_prover_types=prover_types,
        ha_role=ha_role,
        leader_lease=getattr(args, "leader_lease", 3.0),
        ha_node_id=getattr(args, "ha_node_id", None))
    seq = Sequencer(node, l1, cfg, rollup=rollup)
    node.sequencer = seq

    server = RpcServer(
        node, args.http_addr, args.http_port,
        backlog=getattr(args, "rpc_backlog", None),
        executor_workers=getattr(args, "rpc_executor_workers", 0) or None,
        max_batch=getattr(args, "rpc_max_batch", 0) or None).start()
    print(f"genesis hash: 0x{node.genesis_header.hash.hex()}")
    print(f"L2 JSON-RPC listening on http://{args.http_addr}:{server.port}")
    latest = rollup.latest_batch_number()
    if latest:
        print(f"resuming from checkpoint: batch {latest} "
              f"(blocks up to {seq.last_batched_block})")
    seq.start()
    if seq.leadership is not None:
        print(f"sequencer in HA mode as {cfg.ha_role} "
              f"(lease ttl {cfg.leader_lease}s, node id "
              f"{seq.leadership.node_id}); actors parked until the "
              f"leader lease is won — watch ethrex_ready")
    else:
        print(f"sequencer running (block time {cfg.block_time}s, commit "
              f"interval {cfg.commit_interval}s, proof coordinator on port "
              f"{seq.coordinator.port})")

    clients = []
    if args.l2_run_prover:
        from .prover.client import ProverClient

        for ptype in prover_types:
            client = ProverClient(
                ptype, [("127.0.0.1", seq.coordinator.port)])
            client.start()
            clients.append(client)
            print(f"in-process {ptype} prover polling the coordinator")

    # observability: sampler + SLO alerts + optional flight recorder
    # (fatal actor errors auto-snapshot via Sequencer's on_fatal hook)
    from .utils import snapshot
    from .utils.alerts import build_default_engine

    if args.debug_snapshot_dir:
        snapshot.configure(args.debug_snapshot_dir)
    if getattr(args, "profile_dir", None):
        from .perf import profiler as perf_profiler

        perf_profiler.configure(args.profile_dir)
    if getattr(args, "sender_workers", 0):
        from .blockchain import sender_recovery

        sender_recovery.configure(args.sender_workers)
    node.start_telemetry(alerts=build_default_engine(node))

    # coordinated drain: rpc -> prover clients -> sequencer (in-flight
    # proof submits land) -> producer -> flush+close both stores
    from .utils.shutdown import build_node_shutdown

    manager = build_node_shutdown(
        node=node, servers=[server], sequencer=seq,
        prover_clients=clients, stores=[node.store, rollup],
        deadline=args.shutdown_deadline)
    stop_event = _install_signal_handlers(stop_event=threading.Event())

    code = 0
    try:
        while seq.fatal is None and not stop_event.wait(0.5):
            pass
        if seq.fatal is not None:
            actor, err = seq.fatal
            print(f"fatal sequencer actor {actor}: {err}", file=sys.stderr)
            code = 1
    except KeyboardInterrupt:
        pass
    finally:
        report = manager.run()
        print(f"shutdown complete in {report['durationSeconds']:.2f}s "
              f"({len(report['steps'])} steps)")
    return code


def main(argv=None):
    flags = argparse.ArgumentParser(add_help=False)
    _add_node_flags(flags)
    parser = argparse.ArgumentParser(
        prog="ethrex-tpu", description="TPU-native Ethereum L1/L2 node",
        parents=[flags])
    # shared flags are accepted before OR after the subcommand (clap-style)
    sub = parser.add_subparsers(dest="command")

    p_import = sub.add_parser("import", parents=[flags],
                              help="import an RLP chain file")
    p_import.add_argument("file")
    p_export = sub.add_parser("export", parents=[flags],
                              help="export the canonical chain")
    p_export.add_argument("file")
    p_export.add_argument("--first", type=int, default=1)
    p_export.add_argument("--last", type=int, default=None)
    p_rm = sub.add_parser("removedb", parents=[flags],
                          help="delete the database directory")
    p_rm.add_argument("--force", action="store_true")
    sub.add_parser("compute-state-root", parents=[flags],
                   help="print the genesis state root")
    p_l2 = sub.add_parser("l2", parents=[flags],
                          help="run the L2 sequencer stack")
    p_l2.add_argument("--commit-interval", type=float,
                      default=float(_env("COMMIT_INTERVAL", "2.0")),
                      help="seconds between batch commits")
    p_l2.add_argument("--l1.url", dest="l1_url",
                      default=_env("L1_URL"),
                      help="L1 JSON-RPC endpoint (omit for dev L1)")
    p_l2.add_argument("--l1.contract", dest="l1_contract",
                      default=_env("L1_CONTRACT"),
                      help="OnChainProposer contract address on L1")
    p_l2.add_argument("--l1.secret", dest="l1_secret",
                      default=_env("L1_SECRET"),
                      help="hex secret key for L1 commitment txs")
    p_l2.add_argument("--provers", dest="l2_provers",
                      default=_env("L2_PROVERS", "tpu"),
                      help="comma-separated required prover types")
    p_l2.add_argument("--run-prover", dest="l2_run_prover",
                      action="store_true",
                      help="also run in-process prover client(s)")
    p_l2.add_argument("--ha-role", dest="ha_role",
                      choices=("leader", "follower"),
                      default=_env("HA_ROLE"),
                      help="run HA leader election against the L1 lease "
                           "cell: 'leader' bids immediately, 'follower' "
                           "starts as a hot standby (docs/SEQUENCER_HA.md)")
    p_l2.add_argument("--leader-lease", dest="leader_lease", type=float,
                      default=float(_env("HA_LEASE", "3.0")),
                      help="leader lease TTL in seconds (renewal runs at "
                           "ttl/3; failover completes within one TTL)")
    p_l2.add_argument("--ha-node-id", dest="ha_node_id",
                      default=_env("HA_NODE_ID"),
                      help="stable node identity for the lease cell "
                           "(default: derived from role + process)")
    p_repl = sub.add_parser(
        "repl", help="interactive JSON-RPC shell against a running node")
    p_repl.add_argument("--url", default=_env("RPC_URL",
                                              "http://127.0.0.1:8545"))
    p_mon = sub.add_parser(
        "monitor", help="terminal dashboard for a running node")
    p_mon.add_argument("--url", default=_env("RPC_URL",
                                             "http://127.0.0.1:8545"))
    p_mon.add_argument("--interval", type=float, default=2.0)

    args = parser.parse_args(argv)

    # repl/monitor subcommands don't take the shared node flags
    from .utils.tracing import setup_logging

    setup_logging(getattr(args, "log_level", "info") or "info",
                  json_mode=bool(getattr(args, "log_json", False)))

    def cmd_repl(a):
        from .utils.repl import run as repl_run

        return repl_run(a.url)

    def cmd_monitor(a):
        from .utils.monitor import run as monitor_run

        return monitor_run(a.url, a.interval)

    handlers = {
        "import": cmd_import,
        "export": cmd_export,
        "removedb": cmd_removedb,
        "compute-state-root": cmd_compute_state_root,
        "l2": run_l2,
        "repl": cmd_repl,
        "monitor": cmd_monitor,
        None: run_node,
    }
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
