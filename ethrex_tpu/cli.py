"""ethrex-tpu CLI (parity target: cmd/ethrex/cli.rs — ~90 clap flags with
ETHREX_* env-var mirrors, plus the removedb / import / export /
compute-state-root subcommands, cli.rs:562-676).

Every flag reads its default from the matching ETHREX_* environment
variable (the reference's clap `env` mirrors); explicit CLI arguments win.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

from .node import Node
from .primitives.genesis import Genesis
from .rpc.server import RpcServer

DEV_GENESIS = {
    "config": {
        "chainId": 1337,
        "homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
        "byzantiumBlock": 0, "constantinopleBlock": 0, "petersburgBlock": 0,
        "istanbulBlock": 0, "berlinBlock": 0, "londonBlock": 0,
        "mergeNetsplitBlock": 0, "terminalTotalDifficulty": 0,
        "shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0,
    },
    "alloc": {
        # dev account (well-known test key
        # 0x45a915e4d060149eb4365960e6a7a45f334393093061116b197e3240065ff2d8)
        "0xa94f5374fce5edbc8e2a8697c15331677e6ebf0b": {
            "balance": "0xd3c21bcecceda1000000"},
    },
    "gasLimit": "0x1c9c380",
    "baseFeePerGas": "0x7",
    "timestamp": "0x0",
}


def _env(name: str, default=None):
    return os.environ.get(f"ETHREX_{name}", default)


def _env_int(name: str, default: int) -> int:
    v = _env(name)
    return int(v) if v is not None else default


def _env_float(name: str, default: float) -> float:
    v = _env(name)
    return float(v) if v is not None else default


def _add_node_flags(parser: argparse.ArgumentParser):
    parser.add_argument("--dev", action="store_true",
                        default=_env("DEV") == "1",
                        help="dev mode: auto-produce blocks from the mempool")
    parser.add_argument("--datadir", default=_env("DATADIR"),
                        help="persist the chain in <datadir>/chain.db "
                             "(native C++ KV store); default: in-memory")
    parser.add_argument("--network", "--genesis", dest="genesis",
                        default=_env("NETWORK"),
                        help="path to a genesis JSON file")
    parser.add_argument("--http.addr", dest="http_addr",
                        default=_env("HTTP_ADDR", "127.0.0.1"))
    parser.add_argument("--http.port", dest="http_port", type=int,
                        default=_env_int("HTTP_PORT", 8545))
    parser.add_argument("--ws.port", dest="ws_port", type=int,
                        default=_env_int("WS_PORT", 0),
                        help="WebSocket JSON-RPC + subscriptions (0 = off)")
    parser.add_argument("--block-time", dest="block_time", type=float,
                        default=_env_float("BLOCK_TIME", 1.0),
                        help="dev block production interval (s)")
    parser.add_argument("--coinbase",
                        default=_env("COINBASE", "0x" + "00" * 20))
    parser.add_argument("--metrics.port", dest="metrics_port", type=int,
                        default=_env_int("METRICS_PORT", 0),
                        help="Prometheus /metrics port (0 = off)")
    parser.add_argument("--authrpc.addr", dest="authrpc_addr",
                        default=_env("AUTHRPC_ADDR", "127.0.0.1"))
    parser.add_argument("--authrpc.port", dest="authrpc_port", type=int,
                        default=_env_int("AUTHRPC_PORT", 0),
                        help="Engine API port (0 = off)")
    parser.add_argument("--authrpc.jwtsecret", dest="jwt_path",
                        default=_env("AUTHRPC_JWTSECRET"),
                        help="path to a hex-encoded 32-byte JWT secret")
    parser.add_argument("--p2p.enabled", dest="p2p_enabled",
                        action="store_true",
                        default=_env("P2P_ENABLED") == "1")
    parser.add_argument("--p2p.addr", dest="p2p_addr",
                        default=_env("P2P_ADDR", "0.0.0.0"))
    parser.add_argument("--p2p.port", dest="p2p_port", type=int,
                        default=_env_int("P2P_PORT", 30303))
    parser.add_argument("--discovery.port", dest="discovery_port", type=int,
                        default=_env_int("DISCOVERY_PORT", 30303),
                        help="discv4 UDP port")
    parser.add_argument("--bootnodes", default=_env("BOOTNODES", ""),
                        help="comma-separated enode URLs")
    parser.add_argument("--syncmode", choices=("full", "snap"),
                        default=_env("SYNCMODE", "full"))
    parser.add_argument("--kzg-setup", dest="kzg_setup",
                        default=_env("KZG_SETUP"),
                        help="path to the ceremony trusted_setup.json for "
                        "the 0x0a precompile; CONSENSUS-CRITICAL: every "
                        "node of a chain must use the same setup (default: "
                        "the deterministic dev setup, crypto/kzg.py)")
    parser.add_argument("--node-config", dest="node_config",
                        default=_env("NODE_CONFIG"),
                        help="JSON file persisting known peers across "
                        "restarts (reference: node_config.json)")


def _load_genesis(args) -> Genesis | None:
    if args.genesis:
        with open(args.genesis) as f:
            return Genesis.from_json(json.load(f))
    if args.dev:
        return Genesis.from_json(DEV_GENESIS)
    return None


def _open_store(datadir: str | None):
    if not datadir:
        return None
    from .storage.persistent import PersistentBackend
    from .storage.store import Store

    os.makedirs(datadir, exist_ok=True)
    return Store(PersistentBackend(os.path.join(datadir, "chain.db")))


def _decode_chain_file(path: str):
    from .primitives import rlp
    from .primitives.block import Block, BlockBody, BlockHeader

    with open(path, "rb") as f:
        rest = f.read()
    blocks = []
    while rest:
        item, rest = rlp.decode_prefix(rest)
        blocks.append(Block(BlockHeader.decode_fields(item[0]),
                            BlockBody.from_fields(item[1:])))
    return blocks


def cmd_import(args) -> int:
    """`ethrex import <chain.rlp>` — bulk-import an RLP chain file and
    report throughput (cli.rs `import` + tooling/import_benchmark)."""
    import time

    genesis = _load_genesis(args)
    if genesis is None:
        print("import requires --network <genesis.json> (or --dev)",
              file=sys.stderr)
        return 1
    node = Node(genesis, store=_open_store(args.datadir))
    blocks = _decode_chain_file(args.file)
    t0 = time.perf_counter()
    node.chain.add_blocks_in_batch(blocks)
    # make the imported tip canonical (the reference's import subcommand
    # ends with a fork-choice update to the last imported block)
    from .blockchain.fork_choice import apply_fork_choice

    tip = blocks[-1].hash
    apply_fork_choice(node.store, tip, tip, tip)
    dt = time.perf_counter() - t0
    gas = sum(b.header.gas_used for b in blocks)
    print(f"imported {len(blocks)} blocks, {gas / 1e6:.1f} Mgas "
          f"in {dt:.2f}s = {gas / dt / 1e6:.1f} Mgas/s")
    node.store.flush()
    return 0


def cmd_export(args) -> int:
    """`ethrex export <out.rlp>` — canonical chain to an RLP file."""
    from .primitives import rlp

    genesis = _load_genesis(args)
    if genesis is None:
        print("export requires --network/--dev", file=sys.stderr)
        return 1
    node = Node(genesis, store=_open_store(args.datadir))
    last = args.last if args.last is not None else \
        node.store.latest_number()
    with open(args.file, "wb") as f:
        for n in range(args.first, last + 1):
            block = node.store.get_canonical_block(n)
            if block is None:
                print(f"missing canonical block {n}", file=sys.stderr)
                return 1
            f.write(block.encode())
    print(f"exported blocks {args.first}..{last} to {args.file}")
    return 0


def cmd_removedb(args) -> int:
    """`ethrex removedb` — delete the datadir (cli.rs removedb)."""
    import shutil

    if not args.datadir:
        print("removedb requires --datadir", file=sys.stderr)
        return 1
    if not os.path.isdir(args.datadir):
        print(f"no database at {args.datadir}")
        return 0
    if not args.force:
        resp = input(f"delete {args.datadir}? [y/N] ")
        if resp.strip().lower() not in ("y", "yes"):
            print("aborted")
            return 1
    shutil.rmtree(args.datadir)
    print(f"removed {args.datadir}")
    return 0


def cmd_compute_state_root(args) -> int:
    """`ethrex compute-state-root --network genesis.json`."""
    genesis = _load_genesis(args)
    if genesis is None:
        print("compute-state-root requires --network", file=sys.stderr)
        return 1
    from .storage.store import Store

    header = Store().init_genesis(genesis)
    print(f"state root: 0x{header.state_root.hex()}")
    print(f"genesis hash: 0x{header.hash.hex()}")
    return 0


def _parse_enode(url: str):
    # enode://<128-hex pubkey>@host:port
    if not url.startswith("enode://"):
        raise ValueError(f"not an enode URL: {url}")
    rest = url[len("enode://"):]
    pub_hex, _, addr = rest.partition("@")
    host, _, port = addr.partition(":")
    from .p2p.rlpx import _pub_from_bytes

    return _pub_from_bytes(bytes.fromhex(pub_hex)), host, int(port or 30303)


def run_node(args) -> int:
    if args.kzg_setup:
        from .crypto import kzg

        kzg.set_setup(kzg.TrustedSetup.from_ceremony_json(args.kzg_setup))

    genesis = _load_genesis(args)
    if genesis is None:
        print("either --dev or --network <genesis.json> is required",
              file=sys.stderr)
        return 1

    coinbase = bytes.fromhex(args.coinbase.removeprefix("0x"))
    store = _open_store(args.datadir)
    node = Node(genesis, coinbase=coinbase, store=store)
    server = RpcServer(node, args.http_addr, args.http_port).start()
    print(f"genesis hash: 0x{node.genesis_header.hash.hex()}")
    print(f"JSON-RPC listening on http://{args.http_addr}:{server.port}")
    authrpc = None
    if args.authrpc_port:
        if args.jwt_path:
            with open(args.jwt_path) as f:
                jwt_secret = bytes.fromhex(
                    f.read().strip().removeprefix("0x"))
        else:
            # never expose an unauthenticated consensus-control endpoint:
            # generate a secret like the reference does and tell the user
            import secrets as _secrets

            jwt_secret = _secrets.token_bytes(32)
            print(f"generated JWT secret (pass to your CL): "
                  f"{jwt_secret.hex()}")
        authrpc = RpcServer(node, args.authrpc_addr, args.authrpc_port,
                            jwt_secret=jwt_secret, engine=True).start()
        print(f"Engine API listening on http://{args.authrpc_addr}:"
              f"{authrpc.port}")
    ws = None
    if args.ws_port:
        from .rpc.websocket import WsServer

        ws = WsServer(server, args.http_addr, args.ws_port).start()
        print(f"WebSocket JSON-RPC on ws://{args.http_addr}:{ws.port}")
    metrics = None
    if args.metrics_port:
        from .utils.metrics import MetricsServer

        metrics = MetricsServer(args.http_addr, args.metrics_port).start()
        print(f"metrics on http://{args.http_addr}:{metrics.port}/metrics")

    p2p = None
    if args.p2p_enabled:
        from .p2p.connection import P2PServer

        p2p = P2PServer(node, host=args.p2p_addr, port=args.p2p_port)
        p2p.start()
        from .p2p.rlpx import _pub_bytes

        print(f"p2p listening on {p2p.host}:{p2p.port} "
              f"(enode pubkey {_pub_bytes(p2p.pub).hex()})")
        peers = []
        if args.node_config and os.path.exists(args.node_config):
            with open(args.node_config) as f:
                peers = json.load(f).get("known_peers", [])
        for url in filter(None, args.bootnodes.split(",")):
            peers.append(url.strip())
        for url in peers:
            try:
                pub, host, port = _parse_enode(url)
                p2p.dial(host, port, pub)
            except (ValueError, OSError) as e:
                print(f"bootnode {url}: {e}", file=sys.stderr)

    if args.dev:
        node.start_dev_producer(args.block_time)
        print(f"dev producer running (block time {args.block_time}s)")

    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        # persist known peers (reference: node_config.json on shutdown)
        if p2p is not None and args.node_config:
            known = []
            for peer in p2p.peers:
                try:
                    host, port = peer.sock.getpeername()[:2]
                    known.append(
                        f"enode://{bytes(peer.remote_pub).hex()}"
                        f"@{host}:{port}")
                except (OSError, AttributeError, TypeError):
                    continue
            with open(args.node_config, "w") as f:
                json.dump({"known_peers": known}, f)
        # order matters: stop writers (join producer), THEN fsync, THEN
        # close the backend; servers last-but-harmless
        writers_stopped = node.stop()
        node.store.flush()
        try:
            server.stop()
        except OSError:
            pass
        if store is not None and writers_stopped:
            # never close the native handle under a live writer
            store.backend.close()
    return 0


def main(argv=None):
    flags = argparse.ArgumentParser(add_help=False)
    _add_node_flags(flags)
    parser = argparse.ArgumentParser(
        prog="ethrex-tpu", description="TPU-native Ethereum L1/L2 node",
        parents=[flags])
    # shared flags are accepted before OR after the subcommand (clap-style)
    sub = parser.add_subparsers(dest="command")

    p_import = sub.add_parser("import", parents=[flags],
                              help="import an RLP chain file")
    p_import.add_argument("file")
    p_export = sub.add_parser("export", parents=[flags],
                              help="export the canonical chain")
    p_export.add_argument("file")
    p_export.add_argument("--first", type=int, default=1)
    p_export.add_argument("--last", type=int, default=None)
    p_rm = sub.add_parser("removedb", parents=[flags],
                          help="delete the database directory")
    p_rm.add_argument("--force", action="store_true")
    sub.add_parser("compute-state-root", parents=[flags],
                   help="print the genesis state root")

    args = parser.parse_args(argv)
    handlers = {
        "import": cmd_import,
        "export": cmd_export,
        "removedb": cmd_removedb,
        "compute-state-root": cmd_compute_state_root,
        None: run_node,
    }
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
