"""ethrex-tpu CLI (parity target: cmd/ethrex/cli.rs — the L1 node entry
point; L2 subcommands arrive with the sequencer)."""

from __future__ import annotations

import argparse
import json
import signal
import sys

from .node import Node
from .primitives.genesis import Genesis
from .rpc.server import RpcServer

DEV_GENESIS = {
    "config": {
        "chainId": 1337,
        "homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
        "byzantiumBlock": 0, "constantinopleBlock": 0, "petersburgBlock": 0,
        "istanbulBlock": 0, "berlinBlock": 0, "londonBlock": 0,
        "mergeNetsplitBlock": 0, "terminalTotalDifficulty": 0,
        "shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0,
    },
    "alloc": {
        # dev account (well-known test key
        # 0x45a915e4d060149eb4365960e6a7a45f334393093061116b197e3240065ff2d8)
        "0xa94f5374fce5edbc8e2a8697c15331677e6ebf0b": {
            "balance": "0xd3c21bcecceda1000000"},
    },
    "gasLimit": "0x1c9c380",
    "baseFeePerGas": "0x7",
    "timestamp": "0x0",
}


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ethrex-tpu", description="TPU-native Ethereum L1/L2 node")
    parser.add_argument("--dev", action="store_true",
                        help="dev mode: auto-produce blocks from the mempool")
    parser.add_argument("--datadir",
                        help="persist the chain in <datadir>/chain.db "
                             "(native C++ KV store); default: in-memory")
    parser.add_argument("--network", "--genesis", dest="genesis",
                        help="path to a genesis JSON file")
    parser.add_argument("--http.addr", dest="http_addr", default="127.0.0.1")
    parser.add_argument("--http.port", dest="http_port", type=int,
                        default=8545)
    parser.add_argument("--block-time", dest="block_time", type=float,
                        default=1.0, help="dev block production interval (s)")
    parser.add_argument("--coinbase", default="0x" + "00" * 20)
    parser.add_argument("--metrics.port", dest="metrics_port", type=int,
                        default=0, help="Prometheus /metrics port (0 = off)")
    parser.add_argument("--authrpc.port", dest="authrpc_port", type=int,
                        default=0, help="Engine API port (0 = off)")
    parser.add_argument("--authrpc.jwtsecret", dest="jwt_path",
                        help="path to a hex-encoded 32-byte JWT secret")
    parser.add_argument("--kzg-setup", dest="kzg_setup",
                        help="path to the ceremony trusted_setup.json for "
                        "the 0x0a precompile; CONSENSUS-CRITICAL: every "
                        "node of a chain must use the same setup (default: "
                        "the deterministic dev setup, crypto/kzg.py)")
    args = parser.parse_args(argv)
    if args.kzg_setup:
        from .crypto import kzg

        kzg.set_setup(kzg.TrustedSetup.from_ceremony_json(args.kzg_setup))

    if args.genesis:
        with open(args.genesis) as f:
            genesis = Genesis.from_json(json.load(f))
    elif args.dev:
        genesis = Genesis.from_json(DEV_GENESIS)
    else:
        print("either --dev or --network <genesis.json> is required",
              file=sys.stderr)
        return 1

    coinbase = bytes.fromhex(args.coinbase.removeprefix("0x"))
    store = None
    if args.datadir:
        import os

        from .storage.persistent import PersistentBackend
        from .storage.store import Store

        os.makedirs(args.datadir, exist_ok=True)
        store = Store(PersistentBackend(
            os.path.join(args.datadir, "chain.db")))
    node = Node(genesis, coinbase=coinbase, store=store)
    server = RpcServer(node, args.http_addr, args.http_port).start()
    print(f"genesis hash: 0x{node.genesis_header.hash.hex()}")
    print(f"JSON-RPC listening on http://{args.http_addr}:{server.port}")
    authrpc = None
    if args.authrpc_port:
        if args.jwt_path:
            with open(args.jwt_path) as f:
                jwt_secret = bytes.fromhex(
                    f.read().strip().removeprefix("0x"))
        else:
            # never expose an unauthenticated consensus-control endpoint:
            # generate a secret like the reference does and tell the user
            import secrets as _secrets

            jwt_secret = _secrets.token_bytes(32)
            print(f"generated JWT secret (pass to your CL): "
                  f"{jwt_secret.hex()}")
        authrpc = RpcServer(node, args.http_addr, args.authrpc_port,
                            jwt_secret=jwt_secret, engine=True).start()
        print(f"Engine API listening on http://{args.http_addr}:"
              f"{authrpc.port}")
    metrics = None
    if args.metrics_port:
        from .utils.metrics import MetricsServer

        metrics = MetricsServer(args.http_addr, args.metrics_port).start()
        print(f"metrics on http://{args.http_addr}:{metrics.port}/metrics")
    if args.dev:
        node.start_dev_producer(args.block_time)
        print(f"dev producer running (block time {args.block_time}s)")

    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        # order matters: stop writers (join producer), THEN fsync, THEN
        # close the backend; servers last-but-harmless
        writers_stopped = node.stop()
        node.store.flush()
        try:
            server.stop()
        except OSError:
            pass
        if store is not None and writers_stopped:
            # never close the native handle under a live writer
            store.backend.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
