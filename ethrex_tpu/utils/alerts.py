"""Declarative SLO/alert engine over the time-series windows.

Rules are plain data: a signal callable (engine, node) -> float | None,
a threshold, and hysteresis counts.  The state machine per rule is

    ok -> pending -> firing -> ok

with two flap guards: a rule must breach `for_count` consecutive
evaluations before it fires (a single bad sample never pages), and must
clear `resolve_count` consecutive evaluations before it resolves (a
boundary-hugging series cannot strobe).  A signal returning None (cold
start, no samples, no data in window) is always treated as not-breached.

Burn-rate severities follow the multi-window convention: each SLO
yields a "page" rule (short window, high threshold — fast burn) and a
"warn" rule (long window, lower threshold — slow burn).  Transitions
are logged, counted (alert_transitions_total / alerts_firing), kept in
a bounded history ring, and surfaced through the ethrex_alerts RPC, the
ethrex_health alerts section, and the monitor panel.

evaluate() never raises — a broken rule records its error on the rule
state and evaluation moves on.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Callable

from . import timeseries
from .metrics import record_alert_transition, record_alerts_firing

log = logging.getLogger("ethrex_tpu.alerts")

HISTORY = 64


@dataclasses.dataclass
class AlertRule:
    name: str
    severity: str                      # "page" | "warn"
    signal: Callable                   # (engine, node) -> float | None
    threshold: float
    window: float = 60.0               # informational: the signal's window
    for_count: int = 2                 # consecutive breaches before firing
    resolve_count: int = 2             # consecutive clears before resolving
    description: str = ""
    runbook: str = ""
    below: bool = False                # breach when value <= threshold
                                       # (throughput floors, not ceilings)


class _RuleState:
    __slots__ = ("state", "breach_streak", "ok_streak", "since",
                 "last_value", "last_error")

    def __init__(self):
        self.state = "ok"
        self.breach_streak = 0
        self.ok_streak = 0
        self.since = None
        self.last_value = None
        self.last_error = None


class AlertEngine:
    """Evaluates a rule set against a TimeSeriesEngine; never raises."""

    def __init__(self, engine=None, rules=(), node=None,
                 history: int = HISTORY):
        self.engine = engine if engine is not None else timeseries.ENGINE
        self.node = node
        self.rules = list(rules)
        self.states = {r.name: _RuleState() for r in self.rules}
        self.history: collections.deque = collections.deque(maxlen=history)
        self.transitions_total = 0
        self.eval_errors = 0
        self.lock = threading.Lock()

    # ------------------------------------------------------------------
    def evaluate(self, now: float | None = None):
        try:
            self._evaluate(time.time() if now is None else now)
        except Exception:
            self.eval_errors += 1

    def _evaluate(self, now: float):
        with self.lock:
            for rule in self.rules:
                st = self.states[rule.name]
                try:
                    value = rule.signal(self.engine, self.node)
                    st.last_error = None
                except Exception as exc:
                    value = None
                    st.last_error = f"{type(exc).__name__}: {exc}"
                    self.eval_errors += 1
                st.last_value = value
                if rule.below:
                    breached = value is not None and value <= rule.threshold
                else:
                    breached = value is not None and value >= rule.threshold
                if breached:
                    st.breach_streak += 1
                    st.ok_streak = 0
                    if st.state != "firing":
                        if st.breach_streak >= rule.for_count:
                            self._transition(rule, st, "firing", now, value)
                        else:
                            st.state = "pending"
                else:
                    st.ok_streak += 1
                    st.breach_streak = 0
                    if st.state == "firing":
                        if st.ok_streak >= rule.resolve_count:
                            self._transition(rule, st, "resolved", now, value)
                    elif st.state == "pending":
                        st.state = "ok"
            firing = sum(1 for s in self.states.values()
                         if s.state == "firing")
        record_alerts_firing(firing)

    def _transition(self, rule, st, event, now, value):
        st.state = "firing" if event == "firing" else "ok"
        st.since = now
        self.transitions_total += 1
        self.history.append({
            "rule": rule.name, "severity": rule.severity, "event": event,
            "ts": now, "value": value})
        record_alert_transition(rule.name, event)
        log.log(logging.WARNING if event == "firing" else logging.INFO,
                "alert %s: %s [%s] value=%s threshold=%s",
                event, rule.name, rule.severity, value, rule.threshold)

    # ------------------------------------------------------------------
    def _alert_json(self, rule, st):
        return {"name": rule.name, "severity": rule.severity,
                "state": st.state, "value": st.last_value,
                "threshold": rule.threshold, "window": rule.window,
                "below": rule.below,
                "since": st.since, "description": rule.description,
                "runbook": rule.runbook, "error": st.last_error}

    def active(self) -> list:
        with self.lock:
            return [self._alert_json(r, self.states[r.name])
                    for r in self.rules
                    if self.states[r.name].state == "firing"]

    def to_json(self) -> dict:
        with self.lock:
            rules = [self._alert_json(r, self.states[r.name])
                     for r in self.rules]
            recent = list(self.history)
        return {"rules": rules,
                "active": [r for r in rules if r["state"] == "firing"],
                "recent": recent,
                "transitions": self.transitions_total,
                "evalErrors": self.eval_errors}


# ---------------------------------------------------------------------------
# signal helpers (each returns (engine, node) -> float | None)

def rate_signal(counter: str, window: float = 60.0):
    return lambda eng, node: eng.rate(counter, window=window)


def p95_signal(histogram: str, window: float = 300.0):
    def sig(eng, node):
        p = eng.percentiles(histogram, qs=(0.95,), window=window)
        return None if p is None else p.get("p95")
    return sig


def p99_signal(histogram: str, window: float = 300.0):
    def sig(eng, node):
        p = eng.percentiles(histogram, qs=(0.99,), window=window)
        return None if p is None else p.get("p99")
    return sig


def gauge_signal(gauge: str):
    """Latest sampled value of a plain gauge (None before the first
    sample, so a node that never touched the subsystem never alerts)."""
    return lambda eng, node: eng.gauge(gauge)


def component_p95_signal(histogram: str, component: str,
                         window: float = 300.0):
    """p95 of ONE component series of a labelled histogram — e.g. the
    queue-wait leg of batch_critical_path_seconds.  None until that
    component has samples in the window, so nodes that never settle a
    batch (or predate critical-path attribution) never alert."""
    def sig(eng, node):
        p = eng.percentiles(histogram, qs=(0.95,), window=window,
                            labels={"component": component})
        return None if p is None else p.get("p95")
    return sig


def settlement_lag_signal(eng, node):
    """Batches committed but not yet verified on the L1."""
    latest = eng.gauge("ethrex_l2_latest_batch")
    if latest is None:
        return None
    verified = eng.gauge("ethrex_l2_last_verified_batch") or 0.0
    return latest - verified


def aggregation_lag_signal(eng, node):
    """Batches past the last aggregated settlement.  None until the first
    aggregation lands (`ethrex_l2_last_aggregated_batch` is only sampled
    by the aggregation path), so nodes running per-batch settlement —
    or no L2 at all — never alert."""
    aggregated = eng.gauge("ethrex_l2_last_aggregated_batch")
    if aggregated is None:
        return None
    latest = eng.gauge("ethrex_l2_latest_batch")
    if latest is None:
        return None
    return latest - aggregated


def snap_stall_signal(window: float = 60.0):
    """Snap-sync progress rate, armed only while a sync is actually
    running (`snap_sync_phase` gauge is 1=accounts or 2=healing).  Idle
    nodes and completed syncs return None so they never alert; a running
    sync whose range throughput collapses to ~0 is stalled — usually a
    partition (see snap_sync_paused) or every peer refusing the pivot."""
    def sig(eng, node):
        phase = eng.gauge("snap_sync_phase")
        if phase is None or phase not in (1.0, 2.0):
            return None
        return eng.rate("snap_ranges_synced_total", window=window)
    return sig


def actor_stall_signal(eng, node):
    """Seconds since the least-recently-successful sequencer actor made
    progress (no-progress watchdog; every healthy actor iteration —
    including an idle no-op — counts as a success)."""
    seq = getattr(node, "sequencer", None)
    if seq is None or not getattr(seq, "health", None):
        return None
    now = time.time()
    started = getattr(seq, "started_at", None)
    worst = None
    for st in seq.health.values():
        last = getattr(st, "last_success", None)
        if last is None:
            if (not getattr(st, "runs", 0)
                    and not getattr(st, "consecutive_failures", 0)):
                continue            # actor never scheduled yet
            last = started
        if last is None:
            continue
        stall = now - last
        if worst is None or stall > worst:
            worst = stall
    return worst


def inclusion_backlog_signal(eng, node):
    """Estimated seconds to drain the mempool admission backlog at the
    chain path's current inclusion rate (perf/chain_path.py).  None
    while the backlog is empty or on nodes that never produce blocks
    (L1-only followers) — armed but silent, never false-paging."""
    try:
        from ..perf.chain_path import CHAIN_PATH

        return CHAIN_PATH.backlog_seconds()
    except Exception:  # noqa: BLE001 — a signal must never raise
        return None


def producer_stall_signal(eng, node):
    """Seconds since the last sealed block while admitted transactions
    wait in the pool.  None when the pool is empty or before this node's
    first block (an idle or L1-only node is not a stalled producer)."""
    try:
        from ..perf.chain_path import CHAIN_PATH

        return CHAIN_PATH.producer_stall_seconds()
    except Exception:  # noqa: BLE001 — a signal must never raise
        return None


def sequencer_leaderless_signal(eng, node):
    """1.0 when, from this node's view, NO sequencer holds a live leader
    lease; 0.0 while somebody (us included) does.  None unless this node
    runs HA leader election (`--ha-role`), so single-sequencer deploys
    never arm the rule (docs/SEQUENCER_HA.md)."""
    seq = getattr(node, "sequencer", None)
    leadership = getattr(seq, "leadership", None)
    if leadership is None:
        return None
    return 1.0 if leadership.leaderless() else 0.0


def default_rules(node=None) -> list:
    """The stock SLO set (documented in docs/OBSERVABILITY.md)."""
    mk = AlertRule
    return [
        # batch proving latency (tail) — fast/slow burn over p95
        mk("batch_proving_p95:page", "page",
           p95_signal("batch_proving_seconds", window=120.0), 480.0,
           window=120.0, for_count=2, resolve_count=3,
           description="Batch proof p95 over 2m exceeds 480s",
           runbook="Check prover fleet health (ethrex_health l2.prover) "
                   "and TPU compile churn (prover_kernel_retraces_total)."),
        mk("batch_proving_p95:warn", "warn",
           p95_signal("batch_proving_seconds", window=600.0), 120.0,
           window=600.0, for_count=3, resolve_count=3,
           description="Batch proof p95 over 10m exceeds 120s",
           runbook="Inspect prover_stage_seconds for the regressing stage."),
        # prover runtime degradation — the mesh ladder demoting provers
        # (OOM / device loss) trades throughput for liveness; any
        # sustained rate means the fleet is running under capacity
        mk("prover_runtime_degraded:page", "page",
           rate_signal("prover_mesh_degradations_count", window=60.0),
           0.1, window=60.0, for_count=2, resolve_count=3,
           description="Mesh degradations above 0.1/s over 1m",
           runbook="Provers are repeatedly OOMing or losing devices and "
                   "falling down the ladder; see docs/PROVER_RESILIENCE.md "
                   "'Runtime failures' and ethrex_health l2.prover.runtime."),
        mk("prover_runtime_degraded:warn", "warn",
           rate_signal("prover_mesh_degradations_count", window=600.0),
           0.002, window=600.0, for_count=2, resolve_count=3,
           description="Any mesh degradation in the last 10m",
           runbook="A prover demoted its mesh; check "
                   "prover_oom_retries_total vs the memory gate headroom "
                   "(ETHREX_MEM_GATE_HEADROOM, docs/PROVER_RESILIENCE.md)."),
        # prover lease-loss / reassignment rate
        mk("prover_reassignment_rate:page", "page",
           rate_signal("proof_reassignments_total", window=60.0), 0.2,
           window=60.0, for_count=2, resolve_count=3,
           description="Lease losses/rejections above 0.2/s over 1m",
           runbook="Provers are dying or submitting bad proofs; check "
                   "quarantined_batches and the coordinator log."),
        mk("prover_reassignment_rate:warn", "warn",
           rate_signal("proof_reassignments_total", window=600.0), 0.02,
           window=600.0, for_count=3, resolve_count=3,
           description="Lease losses/rejections above 0.02/s over 10m",
           runbook="A prover endpoint is flapping; check breaker metrics."),
        # store corruption — any corruption warrants a look
        mk("store_corruption_rate:page", "page",
           rate_signal("store_corruption_total", window=60.0), 0.1,
           window=60.0, for_count=2, resolve_count=3,
           description="Checksum failures above 0.1/s over 1m",
           runbook="Disk is actively corrupting records; stop writes and "
                   "inspect backend.quarantined."),
        mk("store_corruption_rate:warn", "warn",
           rate_signal("store_corruption_total", window=600.0), 0.001,
           window=600.0, for_count=2, resolve_count=3,
           description="Any checksum failure in the last 10m",
           runbook="See docs/STORAGE_RESILIENCE.md quarantine flow."),
        # execution-chain reorg depth — a deep reorg orphans many
        # blocks at once (consensus trouble or a hostile fork); a
        # sustained multi-block reorg rate means the chain is churning
        mk("deep_reorg:page", "page",
           p95_signal("chain_reorg_depth", window=120.0), 5.0,
           window=120.0, for_count=2, resolve_count=3,
           description="Reorg depth p95 over 2m at or above 5 blocks",
           runbook="A deep reorg just orphaned 5+ blocks; check the "
                   "chain section of ethrex_health (reinjected/"
                   "evictions) and docs/CHAIN_RESILIENCE.md."),
        mk("deep_reorg:warn", "warn",
           p95_signal("chain_reorg_depth", window=600.0), 2.0,
           window=600.0, for_count=2, resolve_count=3,
           description="Reorg depth p95 over 10m at or above 2 blocks",
           runbook="Multi-block reorgs are recurring; check peer "
                   "health and mempool_reinjections_total churn "
                   "(docs/CHAIN_RESILIENCE.md)."),
        # L1 settlement lag (gauge-derived; windows are evaluation-paced)
        mk("l1_settlement_lag:page", "page",
           settlement_lag_signal, 20.0,
           window=60.0, for_count=3, resolve_count=3,
           description="20+ committed batches await L1 verification",
           runbook="Verifier is stalled or L1 is rejecting proofs; check "
                   "l2.l1 in ethrex_health."),
        mk("l1_settlement_lag:warn", "warn",
           settlement_lag_signal, 5.0,
           window=600.0, for_count=5, resolve_count=3,
           description="5+ committed batches await L1 verification",
           runbook="Settlement is falling behind proving; check "
                   "send_proofs actor latency."),
        # aggregation lag (gauge-derived like settlement lag, but
        # anchored to the last AGGREGATED settlement: only armed once an
        # aggregation has landed, so per-batch-settling nodes stay quiet)
        mk("aggregation_lag:page", "page",
           aggregation_lag_signal, 48.0,
           window=60.0, for_count=3, resolve_count=3,
           description="48+ batches produced past the last aggregated "
                       "settlement",
           runbook="The aggregator stalled or its proofs are being "
                   "rejected; check l2.aggregation.lastError in "
                   "ethrex_health and docs/AGGREGATION.md."),
        mk("aggregation_lag:warn", "warn",
           aggregation_lag_signal, 16.0,
           window=600.0, for_count=5, resolve_count=3,
           description="16+ batches produced past the last aggregated "
                       "settlement",
           runbook="Aggregation is falling behind proving; check the "
                   "aggregate_proofs actor latency and whether the run "
                   "keeps failing its pre-settlement audit."),
        # critical-path queue-wait — batches spending their lifecycle
        # WAITING for a prover while the fleet reports idle capacity is
        # a scheduler bug, not a capacity problem: cross-check
        # scheduler_queue_depth and liveAssignments in ethrex_health
        # (docs/OBSERVABILITY.md "Distributed tracing")
        mk("batch_queue_wait_p95:page", "page",
           component_p95_signal("batch_critical_path_seconds",
                                "queue-wait", window=120.0), 240.0,
           window=120.0, for_count=2, resolve_count=3,
           description="Queue-wait leg of the batch critical path p95 "
                       "over 2m exceeds 240s",
           runbook="Batches sit unassigned while provers poll: check "
                   "scheduler_queue_depth vs l2.prover.liveAssignments "
                   "in ethrex_health, the hedging deadline "
                   "(docs/AGGREGATION.md), and "
                   "ethrex_trace_criticalPath for the dominated trace."),
        mk("batch_queue_wait_p95:warn", "warn",
           component_p95_signal("batch_critical_path_seconds",
                                "queue-wait", window=600.0), 60.0,
           window=600.0, for_count=3, resolve_count=3,
           description="Queue-wait leg of the batch critical path p95 "
                       "over 10m exceeds 60s",
           runbook="Queue time dominating proving time usually means "
                   "too few provers for the batch rate or a cold fleet "
                   "being deferred; see prover_cold_deferrals_total."),
        # chain-path inclusion backlog — the admission stage queue is
        # deeper than the producer can drain (perf/chain_path.py);
        # None on empty pools and L1-only nodes keeps them silent
        mk("inclusion_backlog:page", "page",
           inclusion_backlog_signal, 120.0,
           window=60.0, for_count=2, resolve_count=3,
           description="Mempool backlog needs 120s+ to drain at the "
                       "current inclusion rate",
           runbook="Offered load exceeds chain-path capacity: check "
                   "ethrex_chainPath (explain.bottleneck) and "
                   "block_inclusion_tps vs the admission arrivalRate; "
                   "docs/OBSERVABILITY.md 'Chain-path telemetry'."),
        mk("inclusion_backlog:warn", "warn",
           inclusion_backlog_signal, 20.0,
           window=60.0, for_count=3, resolve_count=3,
           description="Mempool backlog needs 20s+ to drain at the "
                       "current inclusion rate",
           runbook="Sustained arrival/service imbalance; compare the "
                   "payload stage spans (ethrex_perf) against the "
                   "inclusion bench baseline (docs/PERFORMANCE.md "
                   "'Reading the inclusion bench')."),
        # chain-path producer stall — txs wait but no block seals;
        # distinct from sequencer_stall (which watches actor loops):
        # this watches the block producer itself
        mk("producer_stall:page", "page",
           producer_stall_signal, 30.0,
           window=60.0, for_count=2, resolve_count=3,
           description="No block sealed for 30s while transactions "
                       "wait in the mempool",
           runbook="The producer loop is stuck or crashing: check the "
                   "node log for 'block production failed', the "
                   "producer stage in ethrex_chainPath, and the "
                   "payload stage spans in ethrex_perf."),
        mk("producer_stall:warn", "warn",
           producer_stall_signal, 10.0,
           window=60.0, for_count=2, resolve_count=3,
           description="No block sealed for 10s while transactions "
                       "wait in the mempool",
           runbook="Block time is stretching under load; check "
                   "build_payload execute/merkleize spans and prewarm "
                   "effectiveness (docs/OBSERVABILITY.md)."),
        # sequencer actor stall — no-progress watchdog
        mk("sequencer_stall:page", "page",
           actor_stall_signal, 120.0,
           window=60.0, for_count=2, resolve_count=3,
           description="A sequencer actor made no progress for 120s",
           runbook="Check l2.actors in ethrex_health for the stalled "
                   "actor and its lastError."),
        mk("sequencer_stall:warn", "warn",
           actor_stall_signal, 30.0,
           window=60.0, for_count=3, resolve_count=3,
           description="A sequencer actor made no progress for 30s",
           runbook="Often an L1 outage burning the transient budget; see "
                   "sequencer_transient_errors_total."),
        # sequencer loop latency (tail) — slow-burn warn only
        mk("sequencer_loop_p95:warn", "warn",
           p95_signal("sequencer_actor_seconds", window=600.0), 5.0,
           window=600.0, for_count=3, resolve_count=3,
           description="Actor loop p95 over 10m exceeds 5s",
           runbook="An actor body is slow; sequencer_actor_seconds is "
                   "labelled per actor."),
        # throughput floors (below=True: a gauge COLLAPSING is the
        # breach; None before the first sample never alerts, so L1-only
        # or idle nodes stay quiet — docs/PERFORMANCE.md)
        mk("l1_import_throughput_floor:warn", "warn",
           gauge_signal("l1_import_mgas_per_sec"), 0.1,
           window=60.0, for_count=3, resolve_count=3, below=True,
           description="L1 import throughput below 0.1 Mgas/s",
           runbook="Check block_import_stage_seconds (execute vs "
                   "merkleize vs store_write) and ethrex_perf's l1_import "
                   "attribution for the collapsed stage."),
        mk("prover_throughput_floor:warn", "warn",
           gauge_signal("prover_trace_cells_per_sec"), 1e4,
           window=60.0, for_count=3, resolve_count=3, below=True,
           description="Prover throughput below 10k trace cells/s",
           runbook="Compare ethrex_perf roofline utilization against "
                   "the last bench_history.jsonl record; a collapsed "
                   "kernel usually means recompilation churn or a "
                   "fallen-back backend."),
        # RPC serving tail (the item-3 front-door SLO; thresholds match
        # the serving bench gate in docs/PERFORMANCE.md)
        mk("rpc_request_p99:page", "page",
           p99_signal("rpc_request_seconds", window=120.0), 2.0,
           window=120.0, for_count=2, resolve_count=3,
           description="JSON-RPC p99 over 2m exceeds 2s",
           runbook="Check rpc_queue_wait_seconds (thread-pool backlog) "
                   "vs rpc_request_seconds per method, and "
                   "rpc_inflight_requests for a concurrency pile-up."),
        mk("rpc_request_p99:warn", "warn",
           p99_signal("rpc_request_seconds", window=600.0), 0.5,
           window=600.0, for_count=3, resolve_count=3,
           description="JSON-RPC p99 over 10m exceeds 0.5s",
           runbook="Compare against the serving record in "
                   "bench_history.jsonl; see ethrex_health rpc section "
                   "for resets/EOFs under load."),
        # mempool saturation — sustained occupancy near capacity means
        # admissions are evicting (pool churn, dropped txs)
        mk("mempool_saturation:page", "page",
           gauge_signal("mempool_utilization"), 0.98,
           window=60.0, for_count=3, resolve_count=3,
           description="Mempool at 98%+ of capacity for 3 evals",
           runbook="Check ethrex_health mempoolFlow topSenders for a "
                   "spammer and mempool_evictions_by_reason for churn."),
        mk("mempool_saturation:warn", "warn",
           gauge_signal("mempool_utilization"), 0.8,
           window=300.0, for_count=3, resolve_count=3,
           description="Mempool above 80% of capacity",
           runbook="Inclusion is falling behind admission; compare "
                   "mempool_time_in_pool_seconds against the block "
                   "interval."),
        # RPC load shedding — admission control actively rejecting;
        # some shedding under a spike is the design working, sustained
        # shedding means capacity or a stuck shed level
        mk("rpc_shed_rate:page", "page",
           rate_signal("rpc_requests_shed_total", window=60.0), 5.0,
           window=60.0, for_count=2, resolve_count=3,
           description="RPC shedding above 5 req/s over 1m",
           runbook="Check ethrex_health rpc.overload for the shed level "
                   "and byReason split; see docs/OVERLOAD.md for the "
                   "level ladder and tuning knobs."),
        mk("rpc_shed_rate:warn", "warn",
           rate_signal("rpc_requests_shed_total", window=600.0), 0.5,
           window=600.0, for_count=3, resolve_count=3,
           description="RPC shedding above 0.5 req/s over 10m",
           runbook="Sustained low-grade shedding: compare "
                   "rpc_queue_wait_seconds against ETHREX_SHED_QUEUE_HIGH "
                   "and check mempool utilization (level>=2 couples "
                   "to it — docs/OVERLOAD.md)."),
        # snap-sync stall — armed only while a sync runs (phase gauge);
        # below=True: zero range throughput during an active sync is the
        # breach (docs/P2P_RESILIENCE.md)
        mk("snap_sync_stall:page", "page",
           snap_stall_signal(window=120.0), 0.01,
           window=120.0, for_count=3, resolve_count=3, below=True,
           description="Snap sync made no range progress for 3 evals",
           runbook="Check snap_sync_paused (partition: zero live peers) "
                   "and p2p_request_timeouts_total in ethrex_health p2p; "
                   "see docs/P2P_RESILIENCE.md."),
        mk("snap_sync_stall:warn", "warn",
           snap_stall_signal(window=300.0), 0.05,
           window=300.0, for_count=3, resolve_count=3, below=True,
           description="Snap sync range throughput below 0.05/s over 5m",
           runbook="Peers are slow or flapping; compare "
                   "p2p_peer_rtt_seconds per peer and "
                   "p2p_request_retries_total (docs/P2P_RESILIENCE.md)."),
        # sequencer leaderless — HA deploys only (signal is None without
        # --ha-role, so the pair never arms elsewhere).  The lease cell
        # on the L1 says nobody leads: nothing is producing blocks
        mk("sequencer_leaderless:page", "page",
           sequencer_leaderless_signal, 1.0,
           window=60.0, for_count=3, resolve_count=2,
           description="No sequencer holds the leader lease for 3 evals",
           runbook="Every candidate is failing acquire_lease or dying "
                   "during promotion; check leadership.lastError in "
                   "ethrex_ready on each standby and the L1 lease cell "
                   "(docs/SEQUENCER_HA.md runbook)."),
        mk("sequencer_leaderless:warn", "warn",
           sequencer_leaderless_signal, 1.0,
           window=60.0, for_count=2, resolve_count=2,
           description="Leader lease momentarily unheld (failover window)",
           runbook="Expected for up to one lease TTL during a failover; "
                   "sustained flapping means renewal starvation — check "
                   "leadership_transitions_total and the lease TTL vs L1 "
                   "latency (docs/SEQUENCER_HA.md)."),
        # mempool replacement churn — high replacement-by-fee rates are
        # a fee-bidding war or a deliberate repricing spam pattern
        mk("mempool_replacement_churn:page", "page",
           rate_signal("mempool_replacements_total", window=60.0), 10.0,
           window=60.0, for_count=2, resolve_count=3,
           description="Tx replacements above 10/s over 1m",
           runbook="Check mempoolFlow topSenders for a single sender "
                   "repricing in a loop; the >=10% bump rule makes this "
                   "expensive for them (docs/OVERLOAD.md)."),
        mk("mempool_replacement_churn:warn", "warn",
           rate_signal("mempool_replacements_total", window=600.0), 1.0,
           window=600.0, for_count=3, resolve_count=3,
           description="Tx replacements above 1/s over 10m",
           runbook="Persistent repricing churn; compare against base-fee "
                   "movement and the dynamic fee floor in "
                   "ethrex_health mempool stats."),
        # scaling autopsy (PR 18): the two regressor classes the sweep
        # names — idle devices and collective-dominated kernel walls.
        # Both gauges only exist after a prove (gauge_signal answers
        # None before the first sample), so L1-only nodes never fire.
        mk("prover_occupancy_floor:warn", "warn",
           gauge_signal("prover_device_occupancy"), 0.5,
           window=60.0, for_count=3, resolve_count=3, below=True,
           description="Device occupancy of the last proves below 50%",
           runbook="Read ethrex_perf's occupancy section (per-lane busy "
                   "vs idle) and the Perfetto device-lane view; a low "
                   "fraction with large idleGapSeconds means the mesh "
                   "slices are starved between jobs — the cross-batch "
                   "pipelining signal (docs/PERFORMANCE.md \"Reading "
                   "the scaling autopsy\")."),
        mk("prover_collective_share:warn", "warn",
           gauge_signal("prover_collective_wall_share"), 0.4,
           window=60.0, for_count=3, resolve_count=3,
           description="Estimated collective share of a kernel wall "
                       "above 40%",
           runbook="ethrex_perf's collectives section names the kernel "
                   "and op mix (all-gather vs all-reduce bytes); "
                   "re-check _MeshPlan's phase-boundary shardings and "
                   "the explain_scaling autopsy in the latest "
                   "bench_history.jsonl scaling record."),
    ]


def build_default_engine(node=None, engine=None) -> AlertEngine:
    return AlertEngine(engine=engine, rules=default_rules(node), node=node)
