"""Coordinated node shutdown: drain every subsystem in dependency order
under one bounded deadline (the seat of the reference's cancellation-token
teardown in cmd/ethrex — RPC stops accepting, writers stop, in-flight work
lands, backends flush and close).

The CLI builds a `ShutdownManager` with `build_node_shutdown` and runs it
from its SIGTERM/SIGINT handler; `ethrex_health` reports the live phase
while the drain runs, and the total wall-clock lands in the
`shutdown_duration_seconds` gauge.
"""

from __future__ import annotations

import logging
import threading
import time

from .metrics import record_shutdown_duration

log = logging.getLogger("ethrex_tpu.utils.shutdown")

# wall-clock of the last completed drain in this process (health-readable
# even after the manager object is gone)
LAST_DURATION: float | None = None


class ShutdownManager:
    """Ordered drain steps under one deadline.

    Each step is `fn(remaining_seconds)`; exceptions are recorded, never
    propagated — a failing step must not keep later steps (flush, close)
    from running.  Steps registered with `critical=True` (durability:
    flush + close) run even after the deadline is exhausted, with a small
    grace budget; ordinary steps are skipped at that point."""

    CRITICAL_GRACE = 2.0

    def __init__(self, deadline: float = 30.0):
        self.deadline = deadline
        self.steps: list[tuple[str, object, bool]] = []
        self.phase = "running"
        self.report: list[dict] = []
        self.duration: float | None = None
        self._lock = threading.Lock()
        self._ran = False

    def register(self, phase: str, fn, critical: bool = False) -> None:
        self.steps.append((phase, fn, critical))

    def summary(self) -> dict:
        return {"phase": self.phase, "durationSeconds": self.duration,
                "deadlineSeconds": self.deadline, "steps": self.report}

    def run(self) -> dict:
        with self._lock:
            if self._ran:
                return self.summary()
            self._ran = True
        global LAST_DURATION
        t0 = time.monotonic()
        for phase, fn, critical in self.steps:
            self.phase = phase
            remaining = self.deadline - (time.monotonic() - t0)
            entry = {"phase": phase, "ok": True}
            if remaining <= 0:
                if critical:
                    remaining = self.CRITICAL_GRACE
                else:
                    entry.update(ok=False, error="deadline exhausted")
                    self.report.append(entry)
                    log.warning("shutdown step %s skipped: deadline "
                                "exhausted", phase)
                    continue
            t1 = time.monotonic()
            try:
                result = fn(remaining)
                if result is False:
                    entry["ok"] = False
                    entry["error"] = "did not finish within its budget"
            except Exception as e:  # noqa: BLE001 — drain must continue
                entry["ok"] = False
                entry["error"] = f"{type(e).__name__}: {e}"
                log.warning("shutdown step %s failed: %s", phase,
                            entry["error"])
            entry["seconds"] = round(time.monotonic() - t1, 4)
            self.report.append(entry)
        self.duration = time.monotonic() - t0
        self.phase = "done"
        LAST_DURATION = self.duration
        record_shutdown_duration(self.duration)
        failed = [s["phase"] for s in self.report if not s["ok"]]
        log.info("shutdown drain complete in %.2fs (%d steps%s)",
                 self.duration, len(self.report),
                 f"; degraded: {failed}" if failed else "")
        return self.summary()


def build_node_shutdown(node=None, servers=(), sequencer=None,
                        prover_clients=(), stores=(),
                        deadline: float = 30.0) -> ShutdownManager:
    """Wire the standard drain order for a node stack:

    1. rpc — stop accepting requests (HTTP/WS/metrics servers);
    2. prover-clients — no new proofs enter the pipe;
    3. sequencer — in HA mode the leader lease is released first (a hot
       standby starts promoting while we drain); then actors finish
       their in-flight iteration, the coordinator waits for in-flight
       submits to land (or their leases expire and reassign on restart);
    4. producer — the dev block producer joins;
    5. flush+close — every store settles pending layers, flushes and
       releases its KV handle (critical: runs even past the deadline).

    Any component may be None/empty — an L1-only node registers only the
    steps it has.  The manager is attached to `node.shutdown` so
    `ethrex_health` can report the live phase.

    Two telemetry steps bracket the drain: a flight-recorder snapshot
    runs FIRST (capturing the live pre-drain state; a no-op unless
    --debug-snapshot-dir configured a destination), and the time-series
    sampler is stopped (with one final drain sample) after the
    sequencer/producer land but before stores close."""
    manager = ShutdownManager(deadline=deadline)
    manager.register("snapshot", lambda t: _write_shutdown_snapshot(node))
    for server in servers:
        if server is None:
            continue

        def _stop_server(t, s=server):
            # the asyncio front door accepts a drain budget: in-flight
            # responses get a slice of the remaining deadline to land
            # before connections are aborted.  Servers without a drain
            # parameter (metrics, ws) just stop.
            try:
                s.stop(drain=min(max(t, 0.0), 5.0))
            except TypeError:
                s.stop()

        manager.register("rpc", _stop_server)
    for client in prover_clients:
        if client is None:
            continue
        manager.register("prover-clients", lambda t, c=client: c.stop())
    if sequencer is not None:
        # release the leader lease FIRST so a hot standby can begin its
        # promotion while this node drains (planned failover takes one
        # candidacy poll, not a whole lease TTL — docs/SEQUENCER_HA.md)
        if getattr(sequencer, "leadership", None) is not None:
            manager.register(
                "leadership",
                lambda t, s=sequencer: s.leadership.stop(timeout=t))
        manager.register(
            "sequencer", lambda t, s=sequencer: s.stop(timeout=t))
    if node is not None:
        manager.register(
            "producer", lambda t, n=node: n.stop(timeout=max(t, 1.0)))
    manager.register("telemetry", lambda t: _stop_telemetry())
    for store in stores:
        if store is None:
            continue
        manager.register("flush-close",
                         lambda t, s=store: s.close(), critical=True)
    if node is not None:
        node.shutdown = manager
    return manager


def _write_shutdown_snapshot(node):
    from . import snapshot

    if snapshot.configured_dir() is None:
        return True
    snapshot.write(node, reason="shutdown")
    return True


def _stop_telemetry():
    from . import timeseries

    timeseries.ENGINE.stop()
    return True
