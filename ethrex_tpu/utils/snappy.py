"""Raw snappy block format (compress/decompress), dependency-free.

RLPx compresses every post-Hello message body with snappy (devp2p spec;
reference: crates/networking/p2p/rlpx/connection/codec.rs uses the snap
crate).  The image has no python-snappy, so this implements the block
format directly:

    preamble: uncompressed length as little-endian varint
    elements: 2-bit tag in the low bits of the first byte
        00 literal  (len-1 in tag bits 2..7; 60..63 mean 1..4 extra
                     little-endian length bytes)
        01 copy     (len-4 in tag bits 2..4, offset 11 bits: high 3 in
                     tag bits 5..7, low 8 in the next byte)
        10 copy     (len-1 in tag bits 2..7, offset 2 LE bytes)
        11 copy     (len-1 in tag bits 2..7, offset 4 LE bytes)

The compressor is a greedy 4-byte-hash matcher (snappy's own strategy,
simplified); any literal/copy mix is a valid stream, so correctness never
depends on match quality.  The decompressor validates lengths and offsets
and enforces a caller-supplied output cap (RLPx rejects messages that
inflate beyond the protocol limit).
"""

from __future__ import annotations


class SnappyError(Exception):
    pass


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data) or shift > 35:
            raise SnappyError("bad varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _emit_literal(out: bytearray, lit: bytes):
    n = len(lit) - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += n.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += n.to_bytes(4, "little")
    out += lit


def _emit_copy(out: bytearray, offset: int, length: int):
    while length > 0:
        if length < 4:  # too short for any copy element: shouldn't happen
            raise SnappyError("internal: copy too short")
        step = min(length, 64)
        if length - step in (1, 2, 3):
            step = length - 4  # keep the tail >= 4
        if 4 <= step <= 11 and offset < (1 << 11):
            out.append(0x01 | ((step - 4) << 2) | ((offset >> 8) << 5))
            out.append(offset & 0xFF)
        elif offset < (1 << 16):
            out.append(0x02 | ((step - 1) << 2))
            out += offset.to_bytes(2, "little")
        else:
            out.append(0x03 | ((step - 1) << 2))
            out += offset.to_bytes(4, "little")
        length -= step


def compress(data: bytes) -> bytes:
    out = bytearray(_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    i = 0
    lit_start = 0
    while i + 4 <= n:
        key = data[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and data[cand:cand + 4] == key \
                and i - cand < (1 << 32):
            # extend the match
            length = 4
            while i + length < n and length < 1 << 16 and \
                    data[cand + length] == data[i + length]:
                length += 1
            if i > lit_start:
                _emit_literal(out, data[lit_start:i])
            _emit_copy(out, i - cand, length)
            i += length
            lit_start = i
        else:
            i += 1
    if lit_start < n:
        _emit_literal(out, data[lit_start:])
    return bytes(out)


def decompress(data: bytes, max_len: int = 16 * 1024 * 1024) -> bytes:
    want, pos = _read_varint(data, 0)
    if want > max_len:
        raise SnappyError(f"decoded length {want} over cap")
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise SnappyError("truncated literal")
            out += data[pos:pos + ln]
            pos += ln
        else:
            if kind == 1:
                ln = ((tag >> 2) & 0x07) + 4
                if pos >= n:
                    raise SnappyError("truncated copy")
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                ln = (tag >> 2) + 1
                if pos + 2 > n:
                    raise SnappyError("truncated copy")
                offset = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:
                ln = (tag >> 2) + 1
                if pos + 4 > n:
                    raise SnappyError("truncated copy")
                offset = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise SnappyError("bad copy offset")
            for _ in range(ln):  # overlapping copies are legal
                out.append(out[-offset])
        if len(out) > max_len:
            raise SnappyError("output over cap")
    if len(out) != want:
        raise SnappyError(f"length mismatch: {len(out)} != {want}")
    return bytes(out)
