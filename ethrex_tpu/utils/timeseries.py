"""Rolling-window time-series engine over the Metrics registry.

Raw counters and cumulative histogram buckets are not operator signals;
rates and windowed percentiles are.  A lightweight sampler thread (owned
by the node, drained on shutdown) snapshots the registry on a fixed
interval into a bounded ring of samples.  Derived queries take deltas
between the newest sample and the oldest sample inside the requested
window:

  * counter delta / elapsed  -> rate (counter resets clamp to the new
    value, never a negative rate);
  * histogram bucket deltas  -> windowed p50/p95/p99 by linear
    interpolation inside the bucket ladder (Prometheus
    histogram_quantile semantics, +Inf capped at the last finite
    boundary).

Everything here sits on the telemetry side of the never-raise contract:
`tick()` (the sampler body) and the registered evaluators are
exception-guarded, so a broken metric can never take the node down.
"""

from __future__ import annotations

import collections
import threading

from .metrics import METRICS, record_telemetry_sample

DEFAULT_INTERVAL = 1.0
DEFAULT_WINDOW = 60.0
MAX_SAMPLES = 4096

# Histogram families summarised by windows_json (bounded output; ad-hoc
# families remain queryable through percentiles()).
_SUMMARY_QS = (0.5, 0.95, 0.99)


class TimeSeriesEngine:
    """Ring of registry samples + windowed rate/percentile queries."""

    def __init__(self, registry=None, max_samples: int = MAX_SAMPLES):
        self.registry = registry if registry is not None else METRICS
        self.samples: collections.deque = collections.deque(
            maxlen=max_samples)
        self.lock = threading.Lock()
        self.interval = DEFAULT_INTERVAL
        self.sampler_errors = 0
        self._evaluators: list = []
        self._thread = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    # sampling
    def sample_now(self, now: float | None = None) -> dict:
        """Take one registry sample (tests pass explicit timestamps)."""
        snap = self.registry.snapshot()
        if now is not None:
            snap["ts"] = float(now)
        with self.lock:
            self.samples.append(snap)
        record_telemetry_sample()
        return snap

    def clear(self):
        with self.lock:
            self.samples.clear()
        self._evaluators = []
        self.sampler_errors = 0

    def add_evaluator(self, fn):
        """Register a callable run after every sampler tick (the alert
        engine registers its evaluate here)."""
        if fn not in self._evaluators:
            self._evaluators.append(fn)

    def tick(self, now: float | None = None):
        """One sampler beat: sample + run evaluators.  Never raises."""
        try:
            self.sample_now(now)
        except Exception:
            self.sampler_errors += 1
        for fn in list(self._evaluators):
            try:
                fn()
            except Exception:
                self.sampler_errors += 1

    # ------------------------------------------------------------------
    # windowed queries
    def _bounds(self, window: float, now: float | None):
        """(oldest-in-window, newest) sample pair, or None if fewer than
        two samples land inside the window."""
        with self.lock:
            if len(self.samples) < 2:
                return None
            newest = self.samples[-1]
            cutoff = (now if now is not None else newest["ts"]) - window
            oldest = None
            for s in self.samples:
                if s["ts"] >= cutoff:
                    oldest = s
                    break
            if oldest is None or oldest is newest:
                return None
            return oldest, newest

    def rate(self, name: str, window: float = DEFAULT_WINDOW,
             now: float | None = None) -> float | None:
        """Windowed per-second rate of a counter; None without data."""
        bounds = self._bounds(window, now)
        if bounds is None:
            return None
        old, new = bounds
        a = old["counters"].get(name)
        b = new["counters"].get(name)
        if a is None and b is None:
            return None
        a, b = a or 0.0, b or 0.0
        dt = new["ts"] - old["ts"]
        if dt <= 0:
            return None
        # Counter reset (process restart / Metrics.reset): the new value
        # IS the increase since the reset — never a negative rate.
        inc = b - a if b >= a else b
        return inc / dt

    def gauge(self, name: str) -> float | None:
        """Latest sampled gauge value."""
        with self.lock:
            if not self.samples:
                return None
            return self.samples[-1]["gauges"].get(name)

    def counter(self, name: str) -> float | None:
        """Latest sampled cumulative counter value."""
        with self.lock:
            if not self.samples:
                return None
            return self.samples[-1]["counters"].get(name)

    @staticmethod
    def _series_delta(old_hist, new_hist, labels):
        """Summed per-bucket cumulative deltas across matching series."""
        nb = len(new_hist["buckets"])
        old_by_labels = {}
        if old_hist and old_hist.get("buckets") == new_hist["buckets"]:
            for s in old_hist["series"]:
                old_by_labels[tuple(sorted(s["labels"].items()))] = s
        deltas = [0] * (nb + 1)
        seen = False
        for s in new_hist["series"]:
            if labels is not None and any(
                    s["labels"].get(k) != v for k, v in labels.items()):
                continue
            seen = True
            prev = old_by_labels.get(tuple(sorted(s["labels"].items())))
            pc = prev["counts"] if prev else [0] * (nb + 1)
            # Per-series reset clamp: counts moving backwards means the
            # registry restarted; treat the new counts as the delta.
            if s["counts"][nb] < pc[nb]:
                pc = [0] * (nb + 1)
            for i in range(nb + 1):
                deltas[i] += s["counts"][i] - pc[i]
        return (deltas, new_hist["buckets"]) if seen else (None, None)

    def percentiles(self, name: str, qs=_SUMMARY_QS,
                    window: float = DEFAULT_WINDOW,
                    labels: dict | None = None,
                    now: float | None = None) -> dict | None:
        """Windowed percentile estimates from histogram-bucket deltas.

        Returns {"p50": ..., ...} or None when no observation landed in
        the window (cold start must read as no-data, not zero)."""
        bounds = self._bounds(window, now)
        if bounds is None:
            return None
        old, new = bounds
        new_hist = new["histograms"].get(name)
        if new_hist is None:
            return None
        deltas, buckets = self._series_delta(
            old["histograms"].get(name), new_hist, labels)
        if deltas is None:
            return None
        total = deltas[len(buckets)]
        if total <= 0:
            return None
        out = {}
        for q in qs:
            rank = q * total
            value = buckets[-1]          # +Inf cap: last finite boundary
            lower, prev_count = 0.0, 0
            for i, le in enumerate(buckets):
                if deltas[i] >= rank:
                    span = deltas[i] - prev_count
                    frac = (rank - prev_count) / span if span else 1.0
                    value = lower + frac * (le - lower)
                    break
                lower, prev_count = le, deltas[i]
            out[f"p{int(q * 100)}"] = value
        return out

    def windows_json(self, window: float = DEFAULT_WINDOW,
                     now: float | None = None) -> dict:
        """Serializable summary of the current windows (snapshot/RPC)."""
        with self.lock:
            if not self.samples:
                return {"window": window, "samples": 0}
            newest = self.samples[-1]
            n = len(self.samples)
        if now is None:
            now = newest["ts"]
        rates = {}
        for name in sorted(newest["counters"]):
            r = self.rate(name, window, now)
            if r is not None:
                rates[name] = r
        pcts = {}
        for name in sorted(newest["histograms"]):
            p = self.percentiles(name, window=window, now=now)
            if p is not None:
                pcts[name] = p
        return {"window": window, "samples": n, "ts": newest["ts"],
                "rates": rates, "percentiles": pcts,
                "gauges": dict(newest["gauges"]),
                "samplerErrors": self.sampler_errors}

    # ------------------------------------------------------------------
    # sampler thread
    def start(self, interval: float = DEFAULT_INTERVAL):
        """Start the background sampler (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self.interval = interval
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-sampler", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.tick()

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, timeout: float = 2.0):
        """Stop the sampler and drain: one final sample so the last
        window reflects the state at shutdown.  Never raises."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout)
        try:
            self.sample_now()
        except Exception:
            self.sampler_errors += 1
        self._evaluators = []


ENGINE = TimeSeriesEngine()  # process-global, like METRICS / TRACER
