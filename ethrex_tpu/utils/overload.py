"""Admission control / overload protection for the JSON-RPC serving path.

A front door that accepts everything the listen backlog lets through
and runs every request to completion melts p99 for *everyone* past the
knee of the load curve (the Tail at Scale argument, and DAGOR-style
overload control — Zhou et al., "Overload Control for Scaling WeChat
Microservices").  This module is the shared admission stage the server
consults BEFORE executing a request.  The asyncio front door
(rpc/server.py) runs it as on-loop middleware: ``admit()`` is cheap and
non-blocking, so the event loop decides inline — per batch entry — and
only admitted requests ever cross to the handler executor
(docs/OVERLOAD.md "Async admission middleware"):

- **Cost classes.**  Every method maps to one of four classes:
  ``control`` (health/alerts/admin/engine — never shed: the authenticated
  consensus path and the operator's eyes must survive overload),
  ``read`` (cheap state reads, the default), ``submit``
  (eth_sendRawTransaction — work that grows the mempool), and ``heavy``
  (debug/trace, eth_getLogs, eth_call, eth_estimateGas, eth_getProof).
  Each class carries a concurrency limit and a queue-age deadline
  budget.

- **Shed decisions.**  ``admit()`` refuses a request when (a) it
  already waited past its class's deadline budget (executing it would
  spend server time on an answer the client gave up on), (b) the
  class's concurrency limit is full, or (c) the adaptive shed level
  says the class is switched off.  A refused request is answered with a
  typed JSON-RPC ``server busy`` error (code ``SERVER_BUSY_CODE``)
  carrying a machine-readable ``retryAfter`` — it is NEVER executed,
  which is what makes shedding cheap (<10ms) while accepted work keeps
  its latency budget.

- **Adaptive shed level.**  Level 0 sheds nothing; level 1 sheds
  ``heavy``; level 2 adds ``submit``; level 3 sheds everything but
  ``control``.  The level is driven by the accept-to-handler queue-wait
  signal (the existing rpc_queue_wait_seconds histogram's source),
  mempool utilization (so tx submission sheds BEFORE the pool starts
  thrashing its eviction queues), and sustained structural shedding
  (deadline/concurrency refusals), with ok→shedding→recovered
  hysteresis mirroring the alert engine's: a breach must persist
  ``raise_hold`` seconds before the level rises, and the signal must
  stay clear ``recover_hold`` seconds (one hysteresis window) before it
  falls back.

Tuning knobs (env, read at import): ETHREX_SHED_QUEUE_HIGH,
ETHREX_SHED_RAISE_HOLD, ETHREX_SHED_RECOVER_HOLD,
ETHREX_SHED_MEMPOOL_HIGH, ETHREX_SHED_RETRY_AFTER, and
ETHREX_OVERLOAD_DISABLED=1 to turn admission control off entirely.
See docs/OVERLOAD.md for the full contract.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time

from .metrics import record_rpc_shed, record_shed_level

LOG = logging.getLogger("ethrex.overload")

# JSON-RPC application error code for "server busy" (the de-facto
# rate-limit code used by major providers); data.retryAfter is the
# machine-readable backoff hint.
SERVER_BUSY_CODE = -32005

QUEUE_HIGH = float(os.environ.get("ETHREX_SHED_QUEUE_HIGH", "0.25"))
RAISE_HOLD = float(os.environ.get("ETHREX_SHED_RAISE_HOLD", "1.0"))
RECOVER_HOLD = float(os.environ.get("ETHREX_SHED_RECOVER_HOLD", "5.0"))
MEMPOOL_HIGH = float(os.environ.get("ETHREX_SHED_MEMPOOL_HIGH", "0.95"))
RETRY_AFTER = float(os.environ.get("ETHREX_SHED_RETRY_AFTER", "1.0"))
DISABLED = os.environ.get("ETHREX_OVERLOAD_DISABLED", "") == "1"

# default per-class knobs: generous enough that a healthy node under
# test-suite concurrency never sheds, tight enough that a melting node
# stays answerable (docs/OVERLOAD.md "Defaults")
READ_LIMIT = 128
READ_DEADLINE = 5.0
SUBMIT_LIMIT = 64
SUBMIT_DEADLINE = 2.5
HEAVY_LIMIT = 16
HEAVY_DEADLINE = 10.0

_SUBMIT_METHODS = frozenset({"eth_sendRawTransaction"})
_HEAVY_METHODS = frozenset({
    "eth_getLogs", "eth_call", "eth_estimateGas", "eth_getProof",
})
_HEAVY_PREFIXES = ("debug_",)
_CONTROL_PREFIXES = ("engine_", "net_", "admin_", "ethrex_admin")
_CONTROL_METHODS = frozenset({
    "ethrex_health", "ethrex_alerts", "ethrex_debug_snapshot",
    "web3_clientVersion",
})


class CostClass:
    """One admission class: a concurrency limit (0 = unlimited), a
    queue-age deadline budget, and the shed level at which the whole
    class is switched off (0 = never shed)."""

    __slots__ = ("name", "limit", "deadline", "shed_at")

    def __init__(self, name: str, limit: int, deadline: float,
                 shed_at: int):
        self.name = name
        self.limit = limit
        self.deadline = deadline
        self.shed_at = shed_at


def classify(method: str) -> str:
    """Map a JSON-RPC method name to its cost-class name."""
    if method in _CONTROL_METHODS or \
            method.startswith(_CONTROL_PREFIXES):
        return "control"
    if method in _SUBMIT_METHODS:
        return "submit"
    if method in _HEAVY_METHODS or method.startswith(_HEAVY_PREFIXES):
        return "heavy"
    return "read"


class Decision:
    """Outcome of one admit() call.  ``admitted`` decisions must be
    handed back via release(); shed decisions carry the typed error
    payload for the `server busy` response."""

    __slots__ = ("admitted", "cost_class", "reason", "retry_after",
                 "level")

    def __init__(self, admitted: bool, cost_class: str,
                 reason: str | None = None, retry_after: float = 0.0,
                 level: int = 0):
        self.admitted = admitted
        self.cost_class = cost_class
        self.reason = reason
        self.retry_after = retry_after
        self.level = level

    def error_data(self) -> dict:
        """The machine-readable `data` of the server-busy error
        (docs/OVERLOAD.md "retryAfter contract")."""
        return {
            "reason": self.reason,
            "class": self.cost_class,
            "retryAfter": round(self.retry_after, 3),
            "shedLevel": self.level,
        }


def is_busy_error(err) -> bool:
    """True when a JSON-RPC error object is the typed server-busy
    (shed) response — the classifier loadgen uses to keep graceful
    shedding out of the generic error count."""
    return (isinstance(err, dict)
            and err.get("code") == SERVER_BUSY_CODE
            and isinstance(err.get("data"), dict)
            and "retryAfter" in err["data"])


class OverloadController:
    """Shared admission stage for one RPC server (thread-safe)."""

    def __init__(self, *,
                 read_limit: int = READ_LIMIT,
                 read_deadline: float = READ_DEADLINE,
                 submit_limit: int = SUBMIT_LIMIT,
                 submit_deadline: float = SUBMIT_DEADLINE,
                 heavy_limit: int = HEAVY_LIMIT,
                 heavy_deadline: float = HEAVY_DEADLINE,
                 queue_high: float = QUEUE_HIGH,
                 raise_hold: float = RAISE_HOLD,
                 recover_hold: float = RECOVER_HOLD,
                 tick_interval: float = 0.25,
                 signal_window: float = 5.0,
                 shed_pressure_min: int = 3,
                 mempool_high: float = MEMPOOL_HIGH,
                 retry_after: float = RETRY_AFTER,
                 mempool_probe=None,
                 enabled: bool | None = None):
        self.classes = {
            "control": CostClass("control", 0, math.inf, 0),
            "read": CostClass("read", read_limit, read_deadline, 3),
            "submit": CostClass("submit", submit_limit,
                                submit_deadline, 2),
            "heavy": CostClass("heavy", heavy_limit, heavy_deadline, 1),
        }
        self.queue_high = queue_high
        self.raise_hold = raise_hold
        self.recover_hold = recover_hold
        self.tick_interval = tick_interval
        self.signal_window = signal_window
        self.shed_pressure_min = shed_pressure_min
        self.mempool_high = mempool_high
        self.retry_after = retry_after
        self.mempool_probe = mempool_probe
        self.enabled = (not DISABLED) if enabled is None else enabled
        self.level = 0
        self.state = "ok"           # ok -> shedding -> recovered -> ok
        self.lock = threading.Lock()
        self._inflight = {name: 0 for name in self.classes}
        # controller-local tallies (survive metric-registry resets, the
        # same convention as the mempool's flow ledger)
        self.shed_total = 0
        self.shed_by_reason: dict[str, int] = {}
        self.level_changes = 0
        self._waits: list[tuple[float, float]] = []
        self._sheds: list[float] = []      # structural-shed timestamps
        self._last_tick = 0.0
        self._breach_since: float | None = None
        self._clear_since: float | None = None
        self._level0_at: float | None = None

    # -- signals -----------------------------------------------------------
    def note_queue_wait(self, seconds: float) -> None:
        """Feed one accept-to-handler queue wait into the shed-level
        signal (the same measurement rpc_queue_wait_seconds records)."""
        now = time.monotonic()
        with self.lock:
            self._waits.append((now, seconds))
            self._trim_locked(now)

    def _trim_locked(self, now: float) -> None:
        horizon = now - self.signal_window
        self._waits = [(t, w) for t, w in self._waits if t >= horizon]
        self._sheds = [t for t in self._sheds if t >= horizon]

    def _desired_level_locked(self, now: float) -> int:
        self._trim_locked(now)
        lvl = 0
        waits = sorted(w for _, w in self._waits)
        if waits:
            # p99-ish of the recent queue waits; a single stalled accept
            # must not flip the ladder, sustained backlog must
            q = waits[min(len(waits) - 1,
                          max(0, int(0.99 * len(waits))))]
            if q >= self.queue_high:
                lvl = 1
            if q >= 2 * self.queue_high:
                lvl = 2
            if q >= 4 * self.queue_high:
                lvl = 3
        if self.mempool_probe is not None:
            try:
                util = self.mempool_probe()
            except Exception:   # noqa: BLE001 — a probe must never shed
                util = None
            if util is not None and util >= self.mempool_high:
                # the pool is about to thrash: shed submissions (level
                # >= 2) before eviction churn eats the node
                lvl = max(lvl, 2)
        if len(self._sheds) >= self.shed_pressure_min:
            # sustained structural shedding (deadline/concurrency) is
            # itself an overload signal: switch off the heavy class
            lvl = max(lvl, 1)
        return lvl

    def _tick_locked(self, now: float) -> None:
        if self.tick_interval > 0 and \
                now - self._last_tick < self.tick_interval:
            return
        self._last_tick = now
        desired = self._desired_level_locked(now)
        if desired > self.level:
            self._clear_since = None
            if self._breach_since is None:
                self._breach_since = now
            if now - self._breach_since >= self.raise_hold:
                self._set_level_locked(desired, now)
        elif desired < self.level:
            self._breach_since = None
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since >= self.recover_hold:
                self._set_level_locked(desired, now)
        else:
            self._breach_since = self._clear_since = None
            if (self.state == "recovered" and self.level == 0
                    and self._level0_at is not None
                    and now - self._level0_at >= self.recover_hold):
                self.state = "ok"

    def _set_level_locked(self, level: int, now: float) -> None:
        prev = self.level
        self.level = level
        self.level_changes += 1
        self._breach_since = self._clear_since = None
        if level > 0:
            self.state = "shedding"
            self._level0_at = None
        else:
            self.state = "recovered"
            self._level0_at = now
        record_shed_level(level)
        LOG.warning("shed level %d -> %d (state=%s)", prev, level,
                    self.state)

    # -- admission ---------------------------------------------------------
    def admit(self, method: str, queue_age: float | None = None):
        """Admission check for one request.  Returns a Decision; a
        non-admitted decision means: answer the typed busy error NOW,
        never execute the handler."""
        cls = self.classes[classify(method)]
        now = time.monotonic()
        with self.lock:
            self._tick_locked(now)
            if not self.enabled or cls.name == "control":
                self._inflight[cls.name] += 1
                return Decision(True, cls.name)
            if queue_age is not None and queue_age > cls.deadline:
                # past its deadline budget: the caller has likely timed
                # out already; executing it is pure waste
                return self._shed_locked(cls, "deadline", now)
            if self.level >= cls.shed_at > 0:
                return self._shed_locked(cls, "level", now,
                                         structural=False)
            if cls.limit and self._inflight[cls.name] >= cls.limit:
                return self._shed_locked(cls, "concurrency", now)
            self._inflight[cls.name] += 1
            return Decision(True, cls.name)

    def _shed_locked(self, cls: CostClass, reason: str, now: float,
                     structural: bool = True) -> Decision:
        if structural:
            # level sheds are excluded so the ladder cannot latch
            # itself up on its own output
            self._sheds.append(now)
        self.shed_total += 1
        self.shed_by_reason[reason] = \
            self.shed_by_reason.get(reason, 0) + 1
        retry = self.retry_after * max(1, self.level) \
            if reason == "level" else self.retry_after
        record_rpc_shed(reason, cls.name)
        return Decision(False, cls.name, reason, retry, self.level)

    def release(self, decision: Decision) -> None:
        if not decision.admitted:
            return
        with self.lock:
            self._inflight[decision.cost_class] -= 1

    # -- introspection -----------------------------------------------------
    def to_json(self) -> dict:
        with self.lock:
            return {
                "enabled": self.enabled,
                "level": self.level,
                "state": self.state,
                "levelChanges": self.level_changes,
                "shedTotal": self.shed_total,
                "shedByReason": dict(sorted(
                    self.shed_by_reason.items())),
                "classes": {
                    name: {
                        "limit": cls.limit,
                        "deadlineSeconds": None
                        if math.isinf(cls.deadline) else cls.deadline,
                        "shedAtLevel": cls.shed_at,
                        "inflight": self._inflight[name],
                    } for name, cls in sorted(self.classes.items())
                },
                "queueHighSeconds": self.queue_high,
                "raiseHoldSeconds": self.raise_hold,
                "recoverHoldSeconds": self.recover_hold,
                "mempoolHigh": self.mempool_high,
                "retryAfterSeconds": self.retry_after,
            }
