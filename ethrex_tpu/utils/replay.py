"""ethrex-replay equivalent: execute (and later prove) real-network blocks
from a cached witness (reference: tooling's replay flow + the
fixtures/cache/rpc_prover format — {"blocks": [json], "witness": {state,
keys, codes, headers}, "network"}).

Usage:
    python -m ethrex_tpu.utils.replay <cache.json> --genesis <genesis.json>
"""

from __future__ import annotations

import json

from ..guest.execution import ProgramInput, execution_program
from ..guest.witness import ExecutionWitness
from ..primitives.block import (Block, BlockBody, BlockHeader, Withdrawal)
from ..primitives.genesis import ChainConfig
from ..primitives.transaction import Transaction


from ..rpc.serializers import parse_bytes, parse_quantity


def _hx(v) -> int:
    """parse_quantity tolerating None (absent optional RPC fields)."""
    return 0 if v is None else parse_quantity(v)


def _hb(v) -> bytes:
    """parse_bytes tolerating None / '0x'."""
    return b"" if not v or v == "0x" else parse_bytes(v)


def header_from_rpc_json(h: dict) -> BlockHeader:
    hdr = BlockHeader(
        parent_hash=_hb(h["parentHash"]),
        uncles_hash=_hb(h["sha3Uncles"]),
        coinbase=_hb(h["miner"]),
        state_root=_hb(h["stateRoot"]),
        tx_root=_hb(h["transactionsRoot"]),
        receipts_root=_hb(h["receiptsRoot"]),
        bloom=_hb(h["logsBloom"]),
        difficulty=_hx(h["difficulty"]),
        number=_hx(h["number"]),
        gas_limit=_hx(h["gasLimit"]),
        gas_used=_hx(h["gasUsed"]),
        timestamp=_hx(h["timestamp"]),
        extra_data=_hb(h["extraData"]),
        prev_randao=_hb(h["mixHash"]),
        nonce=_hb(h["nonce"]).rjust(8, b"\x00"),
    )
    if h.get("baseFeePerGas") is not None:
        hdr.base_fee_per_gas = _hx(h["baseFeePerGas"])
    if h.get("withdrawalsRoot") is not None:
        hdr.withdrawals_root = _hb(h["withdrawalsRoot"])
    if h.get("blobGasUsed") is not None:
        hdr.blob_gas_used = _hx(h["blobGasUsed"])
    if h.get("excessBlobGas") is not None:
        hdr.excess_blob_gas = _hx(h["excessBlobGas"])
    if h.get("parentBeaconBlockRoot") is not None:
        hdr.parent_beacon_block_root = _hb(h["parentBeaconBlockRoot"])
    if h.get("requestsHash") is not None:
        hdr.requests_hash = _hb(h["requestsHash"])
    return hdr


def tx_from_rpc_json(t: dict) -> Transaction:
    tx_type = _hx(t.get("type", "0x0"))
    tx = Transaction(
        tx_type=tx_type,
        nonce=_hx(t.get("nonce")),
        gas_limit=_hx(t.get("gas")),
        to=_hb(t.get("to") or ""),
        value=_hx(t.get("value")),
        data=_hb(t.get("input") or t.get("data") or ""),
        v=_hx(t.get("yParity", t.get("v")) if tx_type else t.get("v")),
        r=_hx(t.get("r")),
        s=_hx(t.get("s")),
    )
    if t.get("chainId") is not None:
        tx.chain_id = _hx(t["chainId"])
    elif tx_type == 0:
        v = _hx(t.get("v"))
        tx.chain_id = (v - 35) // 2 if v >= 35 else None
    if tx_type in (0, 1):
        tx.gas_price = _hx(t.get("gasPrice"))
    else:
        tx.max_priority_fee_per_gas = _hx(t.get("maxPriorityFeePerGas"))
        tx.max_fee_per_gas = _hx(t.get("maxFeePerGas"))
    if t.get("accessList"):
        tx.access_list = [
            (_hb(e["address"]),
             [int(k, 16) for k in e.get("storageKeys", [])])
            for e in t["accessList"]]
    if tx_type == 3:
        tx.max_fee_per_blob_gas = _hx(t.get("maxFeePerBlobGas"))
        tx.blob_versioned_hashes = [
            _hb(h) for h in t.get("blobVersionedHashes", [])]
    if tx_type == 4:
        tx.authorization_list = [{
            "chain_id": _hx(a.get("chainId")),
            "address": _hb(a.get("address")),
            "nonce": _hx(a.get("nonce")),
            "y_parity": _hx(a.get("yParity", a.get("v"))),
            "r": _hx(a.get("r")), "s": _hx(a.get("s")),
        } for a in t.get("authorizationList", [])]
    return tx


def block_from_rpc_json(b: dict) -> Block:
    header = header_from_rpc_json(b["header"])
    body = b["body"]
    txs = [tx_from_rpc_json(t) for t in body.get("transactions", [])]
    withdrawals = None
    if body.get("withdrawals") is not None:
        withdrawals = [Withdrawal(
            index=_hx(w["index"]), validator_index=_hx(w["validatorIndex"]),
            address=_hb(w["address"]), amount=_hx(w["amount"]))
            for w in body["withdrawals"]]
    return Block(header, BlockBody(transactions=txs, uncles=[],
                                   withdrawals=withdrawals))


def load_cache(path: str, config: ChainConfig) -> ProgramInput:
    with open(path) as f:
        cache = json.load(f)
    blocks = [block_from_rpc_json(b) for b in cache["blocks"]]
    w = cache["witness"]
    headers = sorted(
        (BlockHeader.decode(_hb(h)) for h in w["headers"]),
        key=lambda h: h.number)
    witness = ExecutionWitness(
        nodes=[_hb(n) for n in w["state"]],
        codes=[_hb(c) for c in w["codes"]],
        block_headers=headers,
        first_block_number=blocks[0].header.number,
    )
    return ProgramInput(blocks=blocks, witness=witness, config=config)


def replay(cache_path: str, genesis_config_path: str) -> dict:
    with open(genesis_config_path) as f:
        config = ChainConfig.from_json(json.load(f).get("config", {}))
    program_input = load_cache(cache_path, config)
    blk = program_input.blocks[-1].header
    import time
    t0 = time.time()
    output = execution_program(program_input)
    dt = time.time() - t0
    return {
        "block": blk.number,
        "gas_used": blk.gas_used,
        "wall_s": round(dt, 3),
        "mgas_per_s": round(blk.gas_used / dt / 1e6, 3),
        "final_state_root": "0x" + output.final_state_root.hex(),
    }


if __name__ == "__main__":
    import sys

    if len(sys.argv) < 2 or "--genesis" not in sys.argv:
        sys.stderr.write("usage: python -m ethrex_tpu.utils.replay "
                         "<cache.json> --genesis <genesis.json>\n")
        sys.exit(2)
    cache = sys.argv[1]
    genesis = sys.argv[sys.argv.index("--genesis") + 1]
    sys.stdout.write(json.dumps(replay(cache, genesis), indent=2) + "\n")
