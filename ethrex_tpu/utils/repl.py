"""Interactive JSON-RPC REPL (the seat of the reference's tooling/repl).

`ethrex-tpu repl [--url http://...]` opens a readline loop against a
running node.  Shorthand commands cover the common queries; anything
else is `raw <method> [json-args...]` or a bare `eth_*`-style method
name with arguments.

    bn                      block number
    head                    latest block (summary)
    block <n|hash>          block by number/hash
    bal <addr> [tag]        balance
    nonce <addr> [tag]      transaction count
    code <addr> [tag]       code size + prefix
    tx <hash>               transaction by hash
    receipt <hash>          transaction receipt
    peers                   admin_peers
    batch [n]               L2 batch (latest without n)
    health                  sequencer health
    raw <method> [args...]  arbitrary call; args parsed as JSON
"""

from __future__ import annotations

import json
import urllib.request


class RpcSession:
    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, params: list):
        self._id += 1
        payload = json.dumps({"jsonrpc": "2.0", "id": self._id,
                              "method": method, "params": params}).encode()
        req = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/json"})
        resp = json.loads(
            urllib.request.urlopen(req, timeout=self.timeout).read())
        if "error" in resp:
            raise RuntimeError(resp["error"].get("message", str(resp)))
        return resp.get("result")


def _arg(a: str):
    try:
        return json.loads(a)
    except ValueError:
        return a


def _fmt(v) -> str:
    return json.dumps(v, indent=2, sort_keys=True) \
        if isinstance(v, (dict, list)) else str(v)


def dispatch(rpc: RpcSession, line: str) -> str:
    """One REPL command -> printable output (separated from the loop so
    tests drive it directly)."""
    parts = line.strip().split()
    if not parts:
        return ""
    cmd, args = parts[0], parts[1:]
    if cmd == "bn":
        return str(int(rpc.call("eth_blockNumber", []), 16))
    if cmd == "head":
        b = rpc.call("eth_getBlockByNumber", ["latest", False])
        return (f"#{int(b['number'], 16)} {b['hash']} "
                f"txs={len(b['transactions'])} "
                f"gasUsed={int(b['gasUsed'], 16)}")
    if cmd == "block":
        ref = args[0] if args else "latest"
        if ref.startswith("0x") and len(ref) == 66:
            return _fmt(rpc.call("eth_getBlockByHash", [ref, False]))
        tag = ref if ref in ("latest", "earliest", "pending") \
            else hex(int(ref, 0))
        return _fmt(rpc.call("eth_getBlockByNumber", [tag, False]))
    if cmd == "bal":
        tag = args[1] if len(args) > 1 else "latest"
        return str(int(rpc.call("eth_getBalance", [args[0], tag]), 16))
    if cmd == "nonce":
        tag = args[1] if len(args) > 1 else "latest"
        return str(int(rpc.call("eth_getTransactionCount",
                                [args[0], tag]), 16))
    if cmd == "code":
        tag = args[1] if len(args) > 1 else "latest"
        code = rpc.call("eth_getCode", [args[0], tag])
        nbytes = (len(code) - 2) // 2
        return f"{nbytes} bytes: {code[:66]}{'...' if nbytes > 32 else ''}"
    if cmd == "tx":
        return _fmt(rpc.call("eth_getTransactionByHash", [args[0]]))
    if cmd == "receipt":
        return _fmt(rpc.call("eth_getTransactionReceipt", [args[0]]))
    if cmd == "peers":
        return _fmt(rpc.call("admin_peers", []))
    if cmd == "batch":
        if args:
            return _fmt(rpc.call("ethrex_getBatchByNumber",
                                 [int(args[0], 0)]))
        return _fmt(rpc.call("ethrex_latestBatch", []))
    if cmd == "health":
        return _fmt(rpc.call("ethrex_health", []))
    if cmd == "raw":
        return _fmt(rpc.call(args[0], [_arg(a) for a in args[1:]]))
    if cmd in ("help", "?"):
        return __doc__.split("\n\n", 1)[1]
    # bare method name fallthrough: `eth_chainId`, `net_version 1`, ...
    if "_" in cmd:
        return _fmt(rpc.call(cmd, [_arg(a) for a in args]))
    return f"unknown command {cmd!r} (try `help`)"


def run(url: str) -> int:
    try:
        import readline  # noqa: F401  (history/arrow keys)
    except ImportError:
        pass
    rpc = RpcSession(url)
    try:
        chain = rpc.call("eth_chainId", [])
        print(f"connected to {url} (chain {int(chain, 16)}) — "
              "`help` for commands, ^D to exit")
    except Exception as e:
        print(f"cannot reach {url}: {e}")
        return 1
    while True:
        try:
            line = input("ethrex> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        try:
            out = dispatch(rpc, line)
            if out:
                print(out)
        except Exception as e:
            print(f"error: {e}")
