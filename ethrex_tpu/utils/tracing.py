"""Hierarchical in-process tracing and structured logging (stdlib only).

Mirrors the shape of the reference stack's tracing setup (ethrex wires
`tracing_subscriber` + OTLP spans around the sequencer and prover): a
span is a named, timed region with attributes; spans nest via a
thread-local context stack; completed spans are folded into a bounded
ring buffer of traces keyed by trace ID.

Cross-process propagation is cooperative: the proof coordinator stamps
``trace_id``/``span_id`` into ``InputResponse``, the prover client
re-enters that context with :class:`trace_context`, and ``ProofSubmit``
echoes the IDs back, so one batch's life (assign -> prove -> submit ->
verify -> settle) is a single trace even across the TCP seam.

Everything here is best-effort by contract: tracing must NEVER raise
into the traced path.  Span entry/exit and recording are wrapped so a
tracing bug degrades to missing telemetry, not a failed prove.
"""

from __future__ import annotations

import collections
import json
import logging
import secrets
import sys
import threading
import time

# Completed traces kept in memory (oldest evicted first).
TRACE_CAPACITY = 256
# Spans kept per trace (runaway-loop protection).
SPANS_PER_TRACE = 512


def new_trace_id() -> str:
    return secrets.token_hex(8)


def new_span_id() -> str:
    return secrets.token_hex(4)


class Span:
    """A single timed region.  Fields are finalized on context exit."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "seconds", "attrs", "status", "error", "_t0")

    def __init__(self, trace_id, span_id, parent_id, name, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.seconds = 0.0
        self.status = "ok"
        self.error = None

    def set_attr(self, key, value):
        try:
            self.attrs[key] = value
        except Exception:
            pass

    def to_json(self) -> dict:
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "seconds": self.seconds,
            "status": self.status,
        }
        if self.attrs:
            out["attrs"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if self.error:
            out["error"] = self.error
        return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


_ctx = threading.local()


def _stack() -> list:
    st = getattr(_ctx, "stack", None)
    if st is None:
        st = []
        _ctx.stack = st
    return st


def current() -> "tuple[str, str | None] | None":
    """(trace_id, span_id) for the innermost active context, or None."""
    try:
        st = _stack()
        return st[-1] if st else None
    except Exception:
        return None


def current_trace_id() -> "str | None":
    cur = current()
    return cur[0] if cur else None


class Tracer:
    """Bounded ring buffer of completed traces, keyed by trace ID."""

    def __init__(self, capacity: int = TRACE_CAPACITY):
        self.lock = threading.Lock()
        self.capacity = capacity
        self._traces: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.dropped = 0

    def record(self, span: Span) -> None:
        with self.lock:
            rec = self._traces.get(span.trace_id)
            if rec is None:
                rec = {"traceId": span.trace_id, "spans": []}
                self._traces[span.trace_id] = rec
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
                    self.dropped += 1
            else:
                # A late span keeps its trace warm in the ring.
                self._traces.move_to_end(span.trace_id)
            rec["spans"].append(span.to_json())
            if len(rec["spans"]) > SPANS_PER_TRACE:
                del rec["spans"][:len(rec["spans"]) - SPANS_PER_TRACE]

    def __len__(self) -> int:
        with self.lock:
            return len(self._traces)

    def get_trace(self, trace_id: str) -> "dict | None":
        with self.lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            return {"traceId": rec["traceId"], "spans": list(rec["spans"])}

    def _summaries(self) -> list:
        with self.lock:
            recs = [(tid, list(rec["spans"]))
                    for tid, rec in self._traces.items()]
        out = []
        for tid, spans in recs:
            if not spans:
                continue
            start = min(s["start"] for s in spans)
            end = max(s["start"] + s["seconds"] for s in spans)
            root = next((s for s in spans if not s["parentId"]), spans[0])
            out.append({
                "traceId": tid,
                "name": root["name"],
                "start": start,
                "seconds": end - start,
                "spanCount": len(spans),
                "spans": spans,
            })
        return out

    def recent(self, limit: int = 20) -> list:
        """Most recently touched traces, newest first."""
        return list(reversed(self._summaries()))[:max(0, limit)]

    def slowest(self, limit: int = 20) -> list:
        """Traces ordered by wall-clock extent, slowest first."""
        out = self._summaries()
        out.sort(key=lambda t: t["seconds"], reverse=True)
        return out[:max(0, limit)]

    def stage_breakdown(self, trace_id: str) -> "dict[str, float]":
        """Sum span durations by their ``stage`` attribute for one trace."""
        rec = self.get_trace(trace_id)
        stages: "dict[str, float]" = {}
        if rec is None:
            return stages
        for s in rec["spans"]:
            stage = (s.get("attrs") or {}).get("stage")
            if stage:
                stages[stage] = stages.get(stage, 0.0) + s["seconds"]
        return stages

    def clear(self) -> None:
        with self.lock:
            self._traces.clear()
            self.dropped = 0


TRACER = Tracer()

# Stage observers: callables (span_name, stage, seconds) invoked on every
# stage-span exit, after the prover_stage_seconds observation.  The perf
# profiler (ethrex_tpu/perf/profiler.py) registers here to fold stage
# spans into its attribution tree.  Observers run under the same
# never-raise guard as the rest of span exit.
STAGE_OBSERVERS: list = []


class span:
    """Context manager opening a span under the current thread context.

    With no enclosing context a new trace is started.  ``stage=`` also
    feeds the ``prover_stage_seconds`` histogram on exit.  Never raises:
    on internal failure ``__enter__`` yields None and the body still runs.
    """

    __slots__ = ("_name", "_stage", "_attrs", "_span", "_pushed")

    def __init__(self, name: str, stage: "str | None" = None, **attrs):
        self._name = name
        self._stage = stage
        self._attrs = attrs
        self._span = None
        self._pushed = False

    def __enter__(self):
        try:
            attrs = dict(self._attrs)
            if self._stage:
                attrs["stage"] = self._stage
            st = _stack()
            if st:
                trace_id, parent_id = st[-1]
            else:
                trace_id, parent_id = new_trace_id(), None
            sp = Span(trace_id, new_span_id(), parent_id, self._name, attrs)
            st.append((trace_id, sp.span_id))
            self._pushed = True
            self._span = sp
        except Exception:
            self._span = None
        return self._span

    def __exit__(self, exc_type, exc, tb):
        try:
            if self._pushed:
                st = _stack()
                if st:
                    st.pop()
            sp = self._span
            if sp is not None:
                sp.seconds = time.perf_counter() - sp._t0
                if exc is not None:
                    sp.status = "error"
                    sp.error = f"{exc_type.__name__}: {exc}"
                TRACER.record(sp)
                if self._stage:
                    from . import metrics
                    metrics.observe_prover_stage(self._stage, sp.seconds)
                    for obs in STAGE_OBSERVERS:
                        try:
                            obs(self._name, self._stage, sp.seconds)
                        except Exception:
                            pass
        except Exception:
            pass
        return False


class trace_context:
    """Re-enter a trace received over the wire on this thread.

    Spans opened inside become children of ``parent_span_id`` (or roots
    of the trace when no parent is known).  A falsy ``trace_id`` starts
    a fresh trace so callers need not special-case old peers that do
    not send one.  Never raises.
    """

    __slots__ = ("_trace_id", "_parent_id", "_pushed")

    def __init__(self, trace_id: "str | None",
                 parent_span_id: "str | None" = None):
        self._trace_id = trace_id
        self._parent_id = parent_span_id
        self._pushed = False

    def __enter__(self):
        try:
            tid = self._trace_id
            if not isinstance(tid, str) or not tid:
                tid = new_trace_id()
            pid = self._parent_id if isinstance(self._parent_id, str) else None
            _stack().append((tid, pid))
            self._pushed = True
            self._trace_id = tid
        except Exception:
            pass
        return self._trace_id

    def __exit__(self, exc_type, exc, tb):
        try:
            if self._pushed:
                st = _stack()
                if st:
                    st.pop()
        except Exception:
            pass
        return False


# ---------------------------------------------------------------------------
# Structured logging


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line; carries trace context when present."""

    def format(self, record):
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        cur = current()
        if cur:
            out["traceId"] = cur[0]
            if cur[1]:
                out["spanId"] = cur[1]
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(level: str = "info", json_mode: bool = False,
                  stream=None) -> logging.Logger:
    """Configure the ``ethrex_tpu`` logger namespace.

    Idempotent: replaces any handler installed by a prior call.  Library
    modules log via ``logging.getLogger("ethrex_tpu.<mod>")`` and route
    through here; nothing is written until this is called (or the root
    logger is otherwise configured), which keeps library imports silent.
    """
    root = logging.getLogger("ethrex_tpu")
    handler = logging.StreamHandler(stream if stream is not None
                                    else sys.stderr)
    if json_mode:
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s: %(message)s"))
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    # propagation stays on: the root logger has no handlers in normal
    # CLI runs (no duplicate output), and pytest's caplog attaches there
    return root
