"""Hierarchical in-process tracing and structured logging (stdlib only).

Mirrors the shape of the reference stack's tracing setup (ethrex wires
`tracing_subscriber` + OTLP spans around the sequencer and prover): a
span is a named, timed region with attributes; spans nest via a
thread-local context stack; completed spans are folded into a bounded
ring buffer of traces keyed by trace ID.

Cross-process propagation is cooperative: the proof coordinator stamps
``trace_id``/``span_id`` into ``InputResponse``, the prover client
re-enters that context with :class:`trace_context`, and ``ProofSubmit``
echoes the IDs back, so one batch's life (assign -> prove -> submit ->
verify -> settle) is a single trace even across the TCP seam.

Everything here is best-effort by contract: tracing must NEVER raise
into the traced path.  Span entry/exit and recording are wrapped so a
tracing bug degrades to missing telemetry, not a failed prove.
"""

from __future__ import annotations

import collections
import json
import logging
import secrets
import sys
import threading
import time

# Completed traces kept in memory (oldest evicted first).
TRACE_CAPACITY = 256
# Spans kept per trace (runaway-loop protection).
SPANS_PER_TRACE = 512

# -- span-shipping wire format (docs/OBSERVABILITY.md "Distributed
# tracing").  A prover attaches ``export_wire(trace_id)`` to ProofSubmit
# (and piggybacks it on Heartbeat mid-proof); the coordinator merges it
# with ``TRACER.ingest``.  The field is advisory like ``prover_id``:
# old peers ignore it, new coordinators accept only this version tag.
WIRE_VERSION = 1
# Spans shipped per payload; over the cap the LONGEST spans win, because
# they are the ones critical-path analysis needs.
WIRE_MAX_SPANS = 256
# Serialized payload budget; halve the span list until it fits.
WIRE_MAX_BYTES = 256 * 1024
# Spans one source may contribute to one merged trace, so a chatty or
# hedged prover cannot evict the rest of the tree.
INGEST_SPANS_PER_SOURCE = 256


def new_trace_id() -> str:
    return secrets.token_hex(8)


def new_span_id() -> str:
    return secrets.token_hex(4)


class Span:
    """A single timed region.  Fields are finalized on context exit."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "seconds", "attrs", "status", "error", "_t0")

    def __init__(self, trace_id, span_id, parent_id, name, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.seconds = 0.0
        self.status = "ok"
        self.error = None

    def set_attr(self, key, value):
        try:
            self.attrs[key] = value
        except Exception:
            pass

    def to_json(self) -> dict:
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "seconds": self.seconds,
            "status": self.status,
        }
        if self.attrs:
            out["attrs"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if self.error:
            out["error"] = self.error
        return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


_ctx = threading.local()


def _stack() -> list:
    st = getattr(_ctx, "stack", None)
    if st is None:
        st = []
        _ctx.stack = st
    return st


def current() -> "tuple[str, str | None] | None":
    """(trace_id, span_id) for the innermost active context, or None."""
    try:
        st = _stack()
        return st[-1] if st else None
    except Exception:
        return None


def current_trace_id() -> "str | None":
    cur = current()
    return cur[0] if cur else None


class Tracer:
    """Bounded ring buffer of completed traces, keyed by trace ID."""

    def __init__(self, capacity: int = TRACE_CAPACITY):
        self.lock = threading.Lock()
        self.capacity = capacity
        self._traces: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.dropped = 0
        # spans merged from / dropped by remote payloads (``ingest``)
        self.ingested = 0
        self.ingest_dropped = 0

    def record(self, span: Span) -> None:
        with self.lock:
            rec = self._traces.get(span.trace_id)
            if rec is None:
                rec = {"traceId": span.trace_id, "spans": []}
                self._traces[span.trace_id] = rec
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
                    self.dropped += 1
            else:
                # A late span keeps its trace warm in the ring.
                self._traces.move_to_end(span.trace_id)
            rec["spans"].append(span.to_json())
            if len(rec["spans"]) > SPANS_PER_TRACE:
                del rec["spans"][:len(rec["spans"]) - SPANS_PER_TRACE]

    def ingest(self, payload, source: "str | None" = None) -> int:
        """Merge a shipped span payload (``export_wire``) into the ring.

        Spans land under their ORIGINAL trace and parent IDs, so the
        remote subtree reattaches to the local assign/verify spans and
        one batch renders as one cross-process tree.  The contract is
        the usual tracing one plus wire paranoia: never raises, accepts
        only ``WIRE_VERSION`` payloads, drops malformed spans,
        deduplicates by span ID within a trace (heartbeat payloads are
        cumulative, so re-shipping is idempotent), and caps each source
        at ``INGEST_SPANS_PER_SOURCE`` spans per trace.  Returns the
        number of spans actually added.
        """
        added = dropped = 0
        try:
            if not isinstance(payload, dict) \
                    or payload.get("v") != WIRE_VERSION:
                return 0
            spans = payload.get("spans")
            if not isinstance(spans, list):
                return 0
            src = source if isinstance(source, str) and source else "remote"
            with self.lock:
                # per-call cache: trace id -> (rec, seen span ids,
                # per-source counts) — payload spans overwhelmingly
                # share one trace, so resolve/ring-touch it once
                cache: "dict[str, tuple]" = {}
                # the loop body is hand-flattened (bound s.get, type()
                # over isinstance, branch-only-when-clamping): ingestion
                # sits on the coordinator's socket-serving path and the
                # whole ship+merge cycle carries a <2% tail budget
                per_src_cap = INGEST_SPANS_PER_SOURCE
                per_trace_cap = SPANS_PER_TRACE
                for s in spans:
                    if type(s) is not dict:
                        dropped += 1
                        continue
                    sget = s.get
                    tid = sget("traceId")
                    sid = sget("spanId")
                    start = sget("start")
                    secs = sget("seconds")
                    if not (type(tid) is str and type(sid) is str
                            and isinstance(start, (int, float))
                            and isinstance(secs, (int, float))):
                        dropped += 1
                        continue
                    hit = cache.get(tid)
                    if hit is None:
                        rec = self._traces.get(tid)
                        if rec is None:
                            rec = {"traceId": tid, "spans": []}
                            self._traces[tid] = rec
                            while len(self._traces) > self.capacity:
                                self._traces.popitem(last=False)
                                self.dropped += 1
                        else:
                            self._traces.move_to_end(tid)
                        hit = (rec["spans"],
                               {x.get("spanId") for x in rec["spans"]},
                               rec.setdefault("sources", {}))
                        cache[tid] = hit
                    out, ids, per_src = hit
                    if sid in ids:
                        continue  # duplicate (heartbeat then submit)
                    if per_src.get(src, 0) >= per_src_cap \
                            or len(out) >= per_trace_cap:
                        dropped += 1
                        continue
                    name = sget("name") or "remote"
                    if type(name) is not str:
                        name = str(name)
                    status = sget("status") or "ok"
                    if type(status) is not str:
                        status = str(status)
                    parent = sget("parentId")
                    clean = {
                        "traceId": tid,
                        "spanId": sid,
                        "parentId": parent if type(parent) is str else None,
                        "name": name if len(name) <= 120 else name[:120],
                        "start": float(start),
                        "seconds": float(secs) if secs >= 0 else 0.0,
                        "status": status if len(status) <= 16
                        else status[:16],
                        # which process shipped it; drives the Perfetto
                        # pid mapping and hedged-subtree rendering
                        "source": src,
                    }
                    attrs = sget("attrs")
                    if type(attrs) is dict and attrs:
                        if len(attrs) > 32:
                            attrs = dict(list(attrs.items())[:32])
                        clean["attrs"] = {
                            (k if type(k) is str else str(k)): (
                                v if v is None
                                or type(v) in (str, int, float, bool)
                                else str(v))
                            for k, v in attrs.items()}
                    err = sget("error")
                    if err:
                        clean["error"] = str(err)[:500]
                    out.append(clean)
                    ids.add(sid)
                    per_src[src] = per_src.get(src, 0) + 1
                    added += 1
                self.ingested += added
                self.ingest_dropped += dropped
        except Exception:
            pass
        if added or dropped:
            try:
                from . import metrics
                metrics.record_trace_ingest(added, dropped)
            except Exception:
                pass
        return added

    def __len__(self) -> int:
        with self.lock:
            return len(self._traces)

    def get_trace(self, trace_id: str) -> "dict | None":
        with self.lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            return {"traceId": rec["traceId"], "spans": list(rec["spans"])}

    def _summaries(self) -> list:
        with self.lock:
            recs = [(tid, list(rec["spans"]))
                    for tid, rec in self._traces.items()]
        out = []
        for tid, spans in recs:
            spans = [s for s in spans if isinstance(s, dict)]
            if not spans:
                continue
            start = min(s.get("start") or 0.0 for s in spans)
            root = next((s for s in spans if not s.get("parentId")), None)
            if root is not None:
                end = max((s.get("start") or 0.0) + (s.get("seconds") or 0.0)
                          for s in spans)
                seconds = max(0.0, end - start)
            else:
                # Rootless trace: late or shipped spans kept it warm in
                # the ring without a root, so the wall extent is
                # unknowable.  The longest single span stands in for the
                # duration — a partial trace must not skew the slowest
                # sort with a fabricated extent (or raise on render).
                seconds = max(s.get("seconds") or 0.0 for s in spans)
            entry = {
                "traceId": tid,
                "name": (root if root is not None else
                         min(spans, key=lambda s: s.get("start") or 0.0)
                         ).get("name") or "?",
                "start": start,
                "seconds": seconds,
                "spanCount": len(spans),
                "spans": spans,
            }
            if root is None:
                entry["partial"] = True
            out.append(entry)
        return out

    def recent(self, limit: int = 20) -> list:
        """Most recently touched traces, newest first."""
        return list(reversed(self._summaries()))[:max(0, limit)]

    def slowest(self, limit: int = 20) -> list:
        """Traces ordered by wall-clock extent, slowest first."""
        out = self._summaries()
        out.sort(key=lambda t: t["seconds"], reverse=True)
        return out[:max(0, limit)]

    def stage_breakdown(self, trace_id: str) -> "dict[str, float]":
        """Sum span durations by their ``stage`` attribute for one trace."""
        rec = self.get_trace(trace_id)
        stages: "dict[str, float]" = {}
        if rec is None:
            return stages
        for s in rec["spans"]:
            stage = (s.get("attrs") or {}).get("stage")
            if stage:
                stages[stage] = stages.get(stage, 0.0) + s["seconds"]
        return stages

    def clear(self) -> None:
        with self.lock:
            self._traces.clear()
            self.dropped = 0
            self.ingested = 0
            self.ingest_dropped = 0


TRACER = Tracer()

# Stage observers: callables (span_name, stage, seconds) invoked on every
# stage-span exit, after the prover_stage_seconds observation.  The perf
# profiler (ethrex_tpu/perf/profiler.py) registers here to fold stage
# spans into its attribution tree.  Observers run under the same
# never-raise guard as the rest of span exit.
STAGE_OBSERVERS: list = []


class span:
    """Context manager opening a span under the current thread context.

    With no enclosing context a new trace is started.  ``stage=`` also
    feeds the ``prover_stage_seconds`` histogram on exit.  Never raises:
    on internal failure ``__enter__`` yields None and the body still runs.
    """

    __slots__ = ("_name", "_stage", "_attrs", "_span", "_pushed")

    def __init__(self, name: str, stage: "str | None" = None, **attrs):
        self._name = name
        self._stage = stage
        self._attrs = attrs
        self._span = None
        self._pushed = False

    def __enter__(self):
        try:
            attrs = dict(self._attrs)
            if self._stage:
                attrs["stage"] = self._stage
            st = _stack()
            if st:
                trace_id, parent_id = st[-1]
            else:
                trace_id, parent_id = new_trace_id(), None
            sp = Span(trace_id, new_span_id(), parent_id, self._name, attrs)
            st.append((trace_id, sp.span_id))
            self._pushed = True
            self._span = sp
        except Exception:
            self._span = None
        return self._span

    def __exit__(self, exc_type, exc, tb):
        try:
            if self._pushed:
                st = _stack()
                if st:
                    st.pop()
            sp = self._span
            if sp is not None:
                sp.seconds = time.perf_counter() - sp._t0
                if exc is not None:
                    sp.status = "error"
                    sp.error = f"{exc_type.__name__}: {exc}"
                TRACER.record(sp)
                if self._stage:
                    from . import metrics
                    metrics.observe_prover_stage(self._stage, sp.seconds)
                    for obs in STAGE_OBSERVERS:
                        try:
                            obs(self._name, self._stage, sp.seconds)
                        except Exception:
                            pass
        except Exception:
            pass
        return False


class trace_context:
    """Re-enter a trace received over the wire on this thread.

    Spans opened inside become children of ``parent_span_id`` (or roots
    of the trace when no parent is known).  A falsy ``trace_id`` starts
    a fresh trace so callers need not special-case old peers that do
    not send one.  Never raises.
    """

    __slots__ = ("_trace_id", "_parent_id", "_pushed")

    def __init__(self, trace_id: "str | None",
                 parent_span_id: "str | None" = None):
        self._trace_id = trace_id
        self._parent_id = parent_span_id
        self._pushed = False

    def __enter__(self):
        try:
            tid = self._trace_id
            if not isinstance(tid, str) or not tid:
                tid = new_trace_id()
            pid = self._parent_id if isinstance(self._parent_id, str) else None
            _stack().append((tid, pid))
            self._pushed = True
            self._trace_id = tid
        except Exception:
            pass
        return self._trace_id

    def __exit__(self, exc_type, exc, tb):
        try:
            if self._pushed:
                st = _stack()
                if st:
                    st.pop()
        except Exception:
            pass
        return False


# ---------------------------------------------------------------------------
# Span shipping, critical-path analysis, Perfetto export
# (docs/OBSERVABILITY.md "Distributed tracing")


def export_wire(trace_id, max_spans: int = WIRE_MAX_SPANS,
                max_bytes: int = WIRE_MAX_BYTES,
                tracer: "Tracer | None" = None) -> "dict | None":
    """One trace's completed spans as a bounded wire payload.

    Returns ``{"v": WIRE_VERSION, "spans": [...], "truncated": bool}``
    sorted by span start, or None when the trace is unknown or empty.
    Over ``max_spans`` the longest spans are kept (they are what
    critical-path analysis needs); over ``max_bytes`` the list is halved
    until the serialized payload fits.  Never raises.
    """
    try:
        t = tracer if tracer is not None else TRACER
        if not isinstance(trace_id, str) or not trace_id:
            return None
        rec = t.get_trace(trace_id)
        if rec is None:
            return None
        spans = [s for s in rec["spans"] if isinstance(s, dict)]
        if not spans:
            return None
        truncated = False
        if len(spans) > max_spans:
            spans.sort(key=lambda s: s.get("seconds") or 0.0, reverse=True)
            spans = spans[:max(1, max_spans)]
            truncated = True
        # serialization is the expensive part of shipping (~100us for a
        # 64-span trace) — skip it when a pessimistic size estimate (x6
        # covers worst-case JSON string escaping) is still under budget
        if _approx_wire_bytes(spans) * 6 > max_bytes:
            while len(spans) > 1 and len(json.dumps(
                    {"v": WIRE_VERSION, "spans": spans},
                    default=str)) > max_bytes:
                spans.sort(key=lambda s: s.get("seconds") or 0.0,
                           reverse=True)
                spans = spans[:max(1, len(spans) // 2)]
                truncated = True
        spans.sort(key=lambda s: s.get("start") or 0.0)
        return {"v": WIRE_VERSION, "spans": spans, "truncated": truncated}
    except Exception:
        return None


def _approx_wire_bytes(spans) -> int:
    """Cheap lower bound on the serialized payload size (fixed keys +
    ids + numbers ~= 150 bytes/span, plus the variable strings)."""
    total = 32
    for s in spans:
        n = 150 + len(str(s.get("name") or ""))
        err = s.get("error")
        if err:
            n += len(str(err))
        attrs = s.get("attrs")
        if isinstance(attrs, dict):
            for k, v in attrs.items():
                n += len(str(k)) + len(str(v)) + 8
        total += n
    return total


def _component(s: dict) -> str:
    """Critical-path component of one span.

    The taxonomy the walker attributes wall time to: stage spans become
    ``compile`` / ``prove/<stage>``, transport and lifecycle spans map
    by name, anything unrecognized is ``other`` (uncovered top-level
    time is ``queue-wait``, added by the walker itself).
    """
    attrs = s.get("attrs")
    stage = attrs.get("stage") if isinstance(attrs, dict) else None
    if stage:
        stage = str(stage)
        return "compile" if "compile" in stage else f"prove/{stage}"
    name = str(s.get("name") or "")
    if name == "prover.assign":
        return "assign"
    if name in ("prover.submit", "prover.store_proof"):
        return "transport"
    if name in ("proof.verify", "proof.audit") or name.startswith("aggregate"):
        return "verify"
    if name == "proof.settle":
        return "settle"
    if name.startswith("prover.") or name.startswith("bench."):
        return "prove"
    return "other"


def critical_path(trace: "dict | None") -> dict:
    """Blocking chain + per-component attribution of one merged trace.

    Pure and defensive: walks the plain-dict trace shape
    (``Tracer.get_trace`` output), never raises on partial or malformed
    spans, and attributes every second of the trace's wall
    [earliest start, latest end] to exactly ONE component, so the
    components sum to ``wallSeconds`` by construction — including for a
    hedged batch whose two prover subtrees overlap in time.

    The sweep cuts the wall at every span boundary; each segment is
    attributed to the DEEPEST span covering it (ties to the latest
    starter), i.e. the most specific thing actually running then.  A
    child may outlive its parent — the shipped ``prover.prove`` span
    runs long after its milliseconds-long ``prover.assign`` parent
    closed — and still claims its segments.  Segments nothing covers
    are ``queue-wait``.
    """
    tid = trace.get("traceId") if isinstance(trace, dict) else None
    raw = trace.get("spans") if isinstance(trace, dict) else None
    spans = [s for s in (raw or [])
             if isinstance(s, dict)
             and isinstance(s.get("start"), (int, float))
             and isinstance(s.get("seconds"), (int, float))]
    out = {"traceId": tid, "start": None, "wallSeconds": 0.0,
           "spanCount": len(spans), "components": {}, "chain": [],
           "sources": [], "partial": False}
    if not spans:
        return out

    def _end(s):
        return s["start"] + max(0.0, s["seconds"])

    ids: "dict[str, dict]" = {}
    for s in spans:
        sid = s.get("spanId")
        if isinstance(sid, str) and sid not in ids:
            ids[sid] = s

    def _depth(s):
        # orphans whose parent never reached the ring count as roots
        d = 0
        seen: set = set()
        cur = s
        while d < 64:
            sid = cur.get("spanId")
            if isinstance(sid, str):
                if sid in seen:
                    break  # cycle in wire data
                seen.add(sid)
            pid = cur.get("parentId")
            parent = ids.get(pid) if isinstance(pid, str) else None
            if parent is None or parent is cur:
                break
            d += 1
            cur = parent
        return d

    ranked = [((_depth(s), s["start"]), s) for s in spans]
    wall_lo = min(s["start"] for s in spans)
    wall_hi = max(_end(s) for s in spans)
    cuts = sorted({s["start"] for s in spans} | {_end(s) for s in spans})
    comps: "dict[str, float]" = {}
    chain: list = []
    for a, b in zip(cuts, cuts[1:]):
        if b - a <= 1e-9:
            continue
        mid = (a + b) / 2.0
        best = None
        for rank, s in ranked:
            if s["start"] <= mid < _end(s) \
                    and (best is None or rank > best[0]):
                best = (rank, s)
        if best is None:
            # nothing ran at all: scheduler / queue time, not on any span
            comps["queue-wait"] = comps.get("queue-wait", 0.0) + (b - a)
            continue
        sp = best[1]
        comp = _component(sp)
        comps[comp] = comps.get(comp, 0.0) + (b - a)
        last = chain[-1] if chain else None
        if last is not None and last["spanId"] == sp.get("spanId") \
                and abs(last["end"] - a) <= 1e-9:
            last["end"] = b  # same blocker continues across the cut
        else:
            chain.append({"spanId": sp.get("spanId"),
                          "name": sp.get("name"),
                          "component": comp,
                          "source": sp.get("source"),
                          "start": a, "end": b})
    out.update({
        "start": wall_lo,
        "wallSeconds": wall_hi - wall_lo,
        "components": dict(sorted(comps.items(),
                                  key=lambda kv: kv[1], reverse=True)),
        "chain": chain[:128],
        "sources": sorted({str(s.get("source") or "local") for s in spans}),
        "partial": not any(not s.get("parentId") for s in spans),
    })
    return out


def to_trace_events(trace: "dict | None") -> dict:
    """One merged trace as Chrome trace-event JSON (Perfetto-loadable).

    pid 1 is the local process (coordinator/sequencer spans); each
    remote span ``source`` gets its own pid with process_name metadata,
    so a hedged batch renders as two prover tracks.  Spans carrying a
    ``deviceLane`` attr (the parallel prover's mesh-slice jobs,
    prover/tpu_backend.py) render on a per-lane thread track
    ("device-lane N (k dev)") instead of tid 1, so slice concurrency
    and the idle bubbles between jobs are visible in Perfetto.
    Parent->child links that cross a pid — the submit seam — are
    emitted as flow events ("s"/"f") so the viewer draws the arrow
    across processes.  Never raises; malformed spans are skipped.
    """
    tid = trace.get("traceId") if isinstance(trace, dict) else None
    raw = trace.get("spans") if isinstance(trace, dict) else None
    spans = [s for s in (raw or [])
             if isinstance(s, dict)
             and isinstance(s.get("start"), (int, float))
             and isinstance(s.get("seconds"), (int, float))]
    events: list = []
    try:
        sources = sorted({s["source"] for s in spans
                          if isinstance(s.get("source"), str)})
        pids = {None: 1}
        for i, src in enumerate(sources):
            pids[src] = 2 + i
        for src, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            name = "local" if src is None else f"prover:{src}"
            events.append({"ph": "M", "pid": pid, "tid": 1, "ts": 0,
                           "name": "process_name", "args": {"name": name}})
            events.append({"ph": "M", "pid": pid, "tid": 1, "ts": 0,
                           "name": "thread_name", "args": {"name": "spans"}})

        def _pid(s):
            return pids.get(s.get("source")
                            if isinstance(s.get("source"), str) else None, 1)

        def _lane(s):
            attrs = s.get("attrs")
            lane = attrs.get("deviceLane") if isinstance(attrs, dict) \
                else None
            if isinstance(lane, (int, float)) and not isinstance(lane, bool) \
                    and 0 <= int(lane) < 4096:
                return int(lane)
            return None

        lane_meta = set()
        for s in spans:
            lane = _lane(s)
            if lane is None:
                continue
            key = (_pid(s), lane)
            if key in lane_meta:
                continue
            lane_meta.add(key)
            attrs = s.get("attrs") or {}
            ndev = attrs.get("laneDevices")
            label = f"device-lane {lane}"
            if isinstance(ndev, (int, float)) and ndev:
                label += f" ({int(ndev)} dev)"
            events.append({"ph": "M", "pid": key[0], "tid": 2 + lane,
                           "ts": 0, "name": "thread_name",
                           "args": {"name": label}})

        ids: "dict[str, dict]" = {}
        for s in spans:
            sid = s.get("spanId")
            if isinstance(sid, str) and sid not in ids:
                ids[sid] = s
        for s in spans:
            args = {"spanId": s.get("spanId"), "parentId": s.get("parentId"),
                    "status": s.get("status")}
            attrs = s.get("attrs")
            if isinstance(attrs, dict):
                args.update({str(k): _jsonable(v) for k, v in attrs.items()})
            lane = _lane(s)
            events.append({
                "ph": "X", "cat": "span",
                "name": str(s.get("name") or "?"),
                "pid": _pid(s), "tid": 1 if lane is None else 2 + lane,
                "ts": round(s["start"] * 1e6, 3),
                "dur": max(1.0, round(max(0.0, s["seconds"]) * 1e6, 3)),
                "args": args,
            })
        flow = 0
        for s in spans:
            parent = ids.get(s.get("parentId"))
            if parent is None or _pid(parent) == _pid(s):
                continue
            flow += 1
            events.append({"ph": "s", "cat": "flow", "name": "submit-seam",
                           "id": flow, "pid": _pid(parent), "tid": 1,
                           "ts": round(parent["start"] * 1e6, 3)})
            events.append({"ph": "f", "bp": "e", "cat": "flow",
                           "name": "submit-seam",
                           "id": flow, "pid": _pid(s), "tid": 1,
                           "ts": round(s["start"] * 1e6, 3)})
    except Exception:
        pass
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"traceId": tid}}


# ---------------------------------------------------------------------------
# Structured logging


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line; carries trace context when present."""

    def format(self, record):
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        cur = current()
        if cur:
            out["traceId"] = cur[0]
            if cur[1]:
                out["spanId"] = cur[1]
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(level: str = "info", json_mode: bool = False,
                  stream=None) -> logging.Logger:
    """Configure the ``ethrex_tpu`` logger namespace.

    Idempotent: replaces any handler installed by a prior call.  Library
    modules log via ``logging.getLogger("ethrex_tpu.<mod>")`` and route
    through here; nothing is written until this is called (or the root
    logger is otherwise configured), which keeps library imports silent.
    """
    root = logging.getLogger("ethrex_tpu")
    handler = logging.StreamHandler(stream if stream is not None
                                    else sys.stderr)
    if json_mode:
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s: %(message)s"))
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    # propagation stays on: the root logger has no handlers in normal
    # CLI runs (no duplicate output), and pytest's caplog attaches there
    return root
