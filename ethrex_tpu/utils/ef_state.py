"""EF GeneralStateTest fixture runner.

The seat of the reference's `tooling/ef_tests/state_v2` (types.rs /
runner.rs): parse standard EF state-test JSON — one file holds named tests,
each with a shared `env`/`pre`/`transaction` and per-fork `post` cases
indexed into the data/gasLimit/value arrays — execute each case through the
real transaction executor, merkleize, and compare the post-state root and
the keccak(rlp(logs)) digest byte-exactly.

EF fixture archives are not shipped in this image; the runner executes any
fixtures dropped under `tests/fixtures/ef_state/` or a directory named by
the `EF_STATE_FIXTURES` env var, and a small vendored set written in the
exact EF format keeps it honest hermetically (tests/test_ef_state.py).
"""

from __future__ import annotations

import dataclasses
import json
import os

from ..crypto.keccak import keccak256
from ..evm.db import StateDB
from ..evm.executor import InvalidTransaction, execute_tx
from ..evm.vm import BlockEnv
from ..primitives import rlp
from ..primitives.account import Account
from ..primitives.genesis import ChainConfig, Genesis
from ..primitives.transaction import (
    TYPE_ACCESS_LIST,
    TYPE_BLOB,
    TYPE_DYNAMIC_FEE,
    TYPE_LEGACY,
    TYPE_SET_CODE,
    Transaction,
)
from ..storage.store import Store

# Fork name (EF fixture convention) -> ChainConfig JSON enabling it from
# genesis.  Round 4 extends the runner to the full Frontier..Osaka ladder
# (the reference runs pinned archives over every fork,
# tooling/ef_tests/state_v2/src/runner.rs); pre-Berlin gas/opcode
# variants live in evm/gas.py Schedule + the fork-gated dispatch table.
# pre-Merge forks pin a huge TTD: ChainConfig treats ttd == 0 as merged
# from genesis, which would floor every config at PARIS
_PRE_MERGE_TTD = {"terminalTotalDifficulty": 1 << 70}

_FORK_CONFIGS = {
    "Frontier": {**_PRE_MERGE_TTD},
    "Homestead": {"homesteadBlock": 0, **_PRE_MERGE_TTD},
    "EIP150": {"homesteadBlock": 0, "eip150Block": 0, **_PRE_MERGE_TTD},
    "EIP158": {"homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
               **_PRE_MERGE_TTD},
    "Byzantium": {"homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
                  "byzantiumBlock": 0, **_PRE_MERGE_TTD},
    "Constantinople": {"homesteadBlock": 0, "eip150Block": 0,
                       "eip155Block": 0, "byzantiumBlock": 0,
                       "constantinopleBlock": 0, **_PRE_MERGE_TTD},
    "ConstantinopleFix": {"homesteadBlock": 0, "eip150Block": 0,
                          "eip155Block": 0, "byzantiumBlock": 0,
                          "constantinopleBlock": 0, "petersburgBlock": 0,
                          **_PRE_MERGE_TTD},
    "Istanbul": {"homesteadBlock": 0, "eip150Block": 0, "eip155Block": 0,
                 "byzantiumBlock": 0, "constantinopleBlock": 0,
                 "petersburgBlock": 0, "istanbulBlock": 0,
                 **_PRE_MERGE_TTD},
    "Berlin": {"berlinBlock": 0, **_PRE_MERGE_TTD},
    "London": {"berlinBlock": 0, "londonBlock": 0, **_PRE_MERGE_TTD},
    "Merge": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0},
    "Paris": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0},
    "Shanghai": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0,
                 "shanghaiTime": 0},
    "Cancun": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "Prague": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0,
               "shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0},
    "Osaka": {"berlinBlock": 0, "londonBlock": 0, "mergeNetsplitBlock": 0,
              "shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0,
              "osakaTime": 0},
}

SUPPORTED_FORKS = frozenset(_FORK_CONFIGS)


def _num(v, default=0) -> int:
    if v is None:
        return default
    if isinstance(v, int):
        return v
    s = str(v)
    return int(s, 16) if s.startswith("0x") else int(s)


def _hexb(v) -> bytes:
    if not v:
        return b""
    s = str(v).removeprefix("0x")
    return bytes.fromhex("0" + s if len(s) % 2 else s)


def _addr(v) -> bytes:
    return _hexb(v).rjust(20, b"\x00")


@dataclasses.dataclass
class StateTestCase:
    """One (fork, data-index, gas-index, value-index) execution unit."""

    name: str
    fork: str
    tx: Transaction
    pre: dict                # address -> Account
    env: dict
    expected_hash: bytes
    expected_logs: bytes
    expect_exception: str | None
    indexes: tuple


@dataclasses.dataclass
class CaseResult:
    case: StateTestCase
    passed: bool
    detail: str = ""


def _parse_access_list(raw) -> list:
    out = []
    for entry in raw or []:
        out.append((_addr(entry["address"]),
                    [_num(k) for k in entry.get("storageKeys", [])]))
    return out


def _parse_authorizations(raw) -> list:
    out = []
    for a in raw or []:
        out.append((_num(a["chainId"]), _addr(a["address"]), _num(a["nonce"]),
                    _num(a.get("v", a.get("yParity", 0))), _num(a["r"]),
                    _num(a["s"])))
    return out


def _build_tx(raw_tx: dict, indexes: dict) -> Transaction:
    di, gi, vi = (indexes.get("data", 0), indexes.get("gas", 0),
                  indexes.get("value", 0))
    data = _hexb(raw_tx["data"][di])
    access_lists = raw_tx.get("accessLists")
    access_list = _parse_access_list(access_lists[di]) if access_lists else []
    blob_hashes = [_hexb(h).rjust(32, b"\x00")
                   for h in raw_tx.get("blobVersionedHashes", [])]
    auths = _parse_authorizations(raw_tx.get("authorizationList"))

    if blob_hashes or raw_tx.get("maxFeePerBlobGas") is not None:
        tx_type = TYPE_BLOB
    elif auths:
        tx_type = TYPE_SET_CODE
    elif raw_tx.get("maxFeePerGas") is not None:
        tx_type = TYPE_DYNAMIC_FEE
    elif access_lists is not None:
        tx_type = TYPE_ACCESS_LIST
    else:
        tx_type = TYPE_LEGACY

    to_raw = raw_tx.get("to", "")
    tx = Transaction(
        tx_type=tx_type,
        chain_id=1,
        nonce=_num(raw_tx.get("nonce", 0)),
        gas_price=_num(raw_tx.get("gasPrice", 0)),
        max_priority_fee_per_gas=_num(raw_tx.get("maxPriorityFeePerGas", 0)),
        max_fee_per_gas=_num(raw_tx.get("maxFeePerGas", 0)),
        gas_limit=_num(raw_tx["gasLimit"][gi]),
        to=_addr(to_raw) if to_raw else b"",
        value=_num(raw_tx["value"][vi]),
        data=data,
        access_list=access_list,
        max_fee_per_blob_gas=_num(raw_tx.get("maxFeePerBlobGas", 0)),
        blob_versioned_hashes=blob_hashes,
        authorization_list=auths,
    )
    secret = raw_tx.get("secretKey")
    if secret:
        tx = tx.sign(_num(secret))
    return tx


def _parse_pre(pre: dict) -> dict:
    alloc = {}
    for addr_hex, info in pre.items():
        storage = {_num(k): _num(v)
                   for k, v in info.get("storage", {}).items()}
        alloc[_addr(addr_hex)] = Account.new(
            nonce=_num(info.get("nonce", 0)),
            balance=_num(info.get("balance", 0)),
            code=_hexb(info.get("code", "")),
            storage=storage,
        )
    return alloc


def load_fixture_file(path: str) -> list[StateTestCase]:
    """Expand one fixture JSON into the flat case list (forks x indexes)."""
    with open(path) as f:
        fixture = json.load(f)
    cases = []
    for name, test in fixture.items():
        if "transaction" not in test or "post" not in test:
            continue  # e.g. "_info" blocks in some archives
        pre = _parse_pre(test["pre"])
        env = test["env"]
        for fork, post_cases in test["post"].items():
            if fork not in _FORK_CONFIGS:
                continue
            for post in post_cases:
                idx = post.get("indexes", {})
                cases.append(StateTestCase(
                    name=name, fork=fork,
                    tx=_build_tx(test["transaction"], idx),
                    pre=pre, env=env,
                    expected_hash=_hexb(post["hash"]).rjust(32, b"\x00"),
                    expected_logs=_hexb(post["logs"]).rjust(32, b"\x00"),
                    expect_exception=post.get("expectException"),
                    indexes=(idx.get("data", 0), idx.get("gas", 0),
                             idx.get("value", 0)),
                ))
    return cases


def _logs_hash(logs) -> bytes:
    return keccak256(rlp.encode([log.to_fields() for log in logs]))


def execute_case(case: StateTestCase):
    """Execute one case; returns (post_root, logs_hash, error_str|None,
    gas_used).

    On an invalid transaction the post state is the untouched pre state
    (state-test semantics: rejected txs burn nothing), and error_str carries
    the rejection reason.
    """
    cfg_json = dict(_FORK_CONFIGS[case.fork])
    cfg_json.setdefault("terminalTotalDifficulty", 0)
    cfg_json["chainId"] = 1
    config = ChainConfig.from_json(cfg_json)
    store = Store()
    genesis = Genesis(config=config, alloc=case.pre)
    pre_root = store.init_genesis(genesis).state_root

    env = case.env
    from ..primitives.genesis import Fork

    number = _num(env.get("currentNumber", 1), 1)
    timestamp = _num(env.get("currentTimestamp", 1000), 1000)
    pre_london = config.fork_at(number, timestamp) < Fork.LONDON
    block = BlockEnv(
        number=number,
        coinbase=_addr(env.get("currentCoinbase", "0x" + "00" * 20)),
        timestamp=timestamp,
        gas_limit=_num(env.get("currentGasLimit", 30_000_000)),
        prev_randao=_hexb(env.get("currentRandom",
                                  env.get("currentDifficulty",
                                          "0x" + "00" * 32))
                          ).rjust(32, b"\x00"),
        # no base fee before EIP-1559: the whole gas price goes to the
        # coinbase and nothing is burned
        base_fee=0 if pre_london else _num(env.get("currentBaseFee", 10)),
        excess_blob_gas=_num(env.get("currentExcessBlobGas", 0)),
        difficulty=_num(env.get("currentDifficulty", 0)),
    )

    state = store.state_db(pre_root)
    try:
        result = execute_tx(case.tx, state, block, config)
    except InvalidTransaction as exc:
        return pre_root, _logs_hash([]), str(exc), 0
    post_root = store.apply_account_updates(pre_root, state)
    return post_root, _logs_hash(result.logs), None, result.gas_used


def run_case(case: StateTestCase) -> CaseResult:
    """Execute one case and check the post-state root + logs digest."""
    post_root, got_logs, err, _gas = execute_case(case)

    if case.expect_exception is not None:
        if err is None:
            return CaseResult(case, False,
                              f"expected {case.expect_exception}, tx ran")
    elif err is not None:
        return CaseResult(case, False, f"unexpected invalid tx: {err}")

    if post_root != case.expected_hash:
        return CaseResult(
            case, False,
            f"state root 0x{post_root.hex()} != 0x{case.expected_hash.hex()}")
    if got_logs != case.expected_logs:
        return CaseResult(
            case, False,
            f"logs hash 0x{got_logs.hex()} != 0x{case.expected_logs.hex()}")
    return CaseResult(case, True)


def discover_fixture_dirs() -> list[str]:
    dirs = []
    env_dir = os.environ.get("EF_STATE_FIXTURES")
    if env_dir and os.path.isdir(env_dir):
        dirs.append(env_dir)
    repo_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "tests", "fixtures", "ef_state")
    if os.path.isdir(repo_dir):
        dirs.append(repo_dir)
    return dirs


def run_directory(path: str, fork_filter: str | None = None):
    """Run every fixture file under `path`; returns (passed, failed) lists."""
    passed, failed = [], []
    for root, _dirs, files in os.walk(path):
        for fname in sorted(files):
            if not fname.endswith(".json"):
                continue
            for case in load_fixture_file(os.path.join(root, fname)):
                if fork_filter and case.fork != fork_filter:
                    continue
                res = run_case(case)
                (passed if res.passed else failed).append(res)
    return passed, failed
