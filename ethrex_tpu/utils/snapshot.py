"""Flight recorder: one-file JSON debug snapshots for post-mortems.

A snapshot bundles everything an operator needs after an incident —
metrics dump, time-series windows, active + recent alerts, slowest
traces, actor health, store/journal stats, and TPU/JAX runtime
telemetry — into a single JSON document.  Bundles are produced on
demand (`ethrex_debug_snapshot` RPC), automatically on fatal actor
errors (Sequencer wires `on_fatal` through here), and at the start of a
coordinated shutdown drain, whenever `--debug-snapshot-dir` configured
a destination.

Snapshot writing sits behind the telemetry never-raise contract: every
section is collected independently (a broken subsystem yields an
{"error": ...} stub, not a missing bundle) and `write()` returns None
on any filesystem failure instead of raising into the caller — which
may be a dying actor.
"""

from __future__ import annotations

import json
import logging
import os
import time

from . import jax_cache, timeseries
from .metrics import METRICS, record_snapshot_written
from .tracing import TRACER, to_trace_events

log = logging.getLogger("ethrex_tpu.snapshot")

VERSION = 1
_DIR: str | None = None
_KEEP = 20


def configure(directory: str | None, keep: int = _KEEP) -> None:
    """Set (or clear, with None) the auto-snapshot destination."""
    global _DIR, _KEEP
    _DIR = directory
    _KEEP = keep


def configured_dir() -> str | None:
    return _DIR


def _section(fn):
    try:
        return fn()
    except Exception as exc:
        return {"error": f"{type(exc).__name__}: {exc}"}


def _traces():
    out = {"slowest": TRACER.slowest(10), "recent": TRACER.recent(10),
           "dropped": TRACER.dropped,
           "spansIngested": TRACER.ingested,
           "spanIngestDropped": TRACER.ingest_dropped}
    slow = out["slowest"]
    if slow:
        # the slowest trace ready-to-load in Perfetto / chrome://tracing
        # (docs/OBSERVABILITY.md "Distributed tracing")
        out["perfetto"] = to_trace_events(
            {"traceId": slow[0].get("traceId"),
             "spans": slow[0].get("spans")})
    return out


def _health(node):
    if node is None:
        return None
    from ..rpc.server import _health as rpc_health  # lazy: avoid a cycle

    return rpc_health(node)


def _store(node):
    from ..storage.persistent import storage_stats

    return storage_stats()


def _perf():
    from ..perf import hlo_introspect, occupancy, profiler, roofline

    return {"profiler": profiler.PROFILER.tree(),
            "roofline": roofline.ROOFLINE.report(),
            "collectives": hlo_introspect.REGISTRY.report(),
            "occupancy": occupancy.REGISTRY.report()}


def _traffic(node):
    """RPC lifecycle counters + mempool flow accounting (PERFORMANCE.md
    traffic observability); answers even without a node for the
    connection counters."""
    from ..rpc.server import _rpc_traffic_json  # lazy: avoid a cycle

    out = {"rpc": _rpc_traffic_json()}
    mempool = getattr(node, "mempool", None)
    if mempool is not None:
        out["mempoolFlow"] = mempool.stats_json()
    overload = getattr(node, "rpc_overload", None)
    if overload is not None:
        out["overload"] = overload.to_json()
    return out


def _chain_path():
    from ..perf.chain_path import CHAIN_PATH

    return CHAIN_PATH.to_json()


def collect(node=None, reason: str = "manual") -> dict:
    """Assemble a snapshot bundle.  Never raises; every section is
    independently guarded."""
    engine = getattr(node, "telemetry", None) or timeseries.ENGINE
    alerts = getattr(node, "alerts", None)
    return {
        "version": VERSION,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "metrics": _section(METRICS.snapshot),
        "timeseries": _section(engine.windows_json),
        "alerts": _section(alerts.to_json) if alerts is not None else None,
        "traces": _section(_traces),
        "health": _section(lambda: _health(node)),
        "store": _section(lambda: _store(node)),
        "tpu": _section(jax_cache.runtime_telemetry),
        "perf": _section(_perf),
        "traffic": _section(lambda: _traffic(node)),
        # chain-path X-ray: stage queues, sampled tx lifecycles and the
        # bottleneck explainer — the post-mortem view of where the
        # pipeline was backed up when the snapshot fired
        "chainPath": _section(_chain_path),
    }


def _prune(directory: str) -> None:
    snaps = sorted(f for f in os.listdir(directory)
                   if f.startswith("snapshot-") and f.endswith(".json"))
    for stale in snaps[:-_KEEP] if _KEEP > 0 else snaps:
        try:
            os.unlink(os.path.join(directory, stale))
        except OSError:
            pass


def write(node=None, reason: str = "manual",
          directory: str | None = None, bundle: dict | None = None) -> str | None:
    """Write a bundle to `directory` (default: the configured dir).
    Returns the path, or None when unconfigured or on any failure."""
    directory = directory or _DIR
    if not directory:
        return None
    try:
        if bundle is None:
            bundle = collect(node, reason)
        os.makedirs(directory, exist_ok=True)
        name = f"snapshot-{time.time_ns()}-{reason}.json"
        path = os.path.join(directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)
        _prune(directory)
        record_snapshot_written()
        log.info("debug snapshot written: %s (reason=%s)", path, reason)
        return path
    except Exception as exc:
        log.warning("debug snapshot failed (reason=%s): %s", reason, exc)
        return None


def on_fatal(actor: str, error, node=None) -> str | None:
    """Fatal-actor hook (called from the sequencer loop; must never
    raise there)."""
    try:
        return write(node, reason=f"fatal-{actor}")
    except Exception:
        return None
