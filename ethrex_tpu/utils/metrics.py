"""Prometheus metrics (parity target: the reference's ethrex-metrics crate,
crates/blockchain/metrics — text exposition format, stdlib only)."""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# Fixed exponential buckets: 1ms * 2^i, spanning ~1ms .. ~524s.  One
# shared ladder keeps every latency histogram comparable and the
# exposition size bounded.
DEFAULT_BUCKETS = tuple(0.001 * 2 ** i for i in range(20))

# Label sets one family may hold (mirrors the profiler's MAX_KEYS):
# adversarial reject reasons or per-air labels cannot grow the
# exposition unboundedly; overflow series are dropped and counted in
# metrics_dropped_label_sets_total.
MAX_LABEL_SETS = 512


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: tuple) -> str:
    return ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)


def _fmt_le(le) -> str:
    """Canonical shortest-float bucket boundary: coerce to float first so
    numpy scalars / ints / Decimals all render identically ("0.004",
    "5.0"), keeping le labels stable and joinable across scrapes."""
    return repr(float(le))


class _Histogram:
    """One named histogram family: per-labelset bucket counts + sum."""

    __slots__ = ("buckets", "series", "exemplars")

    def __init__(self, buckets):
        self.buckets = tuple(sorted(buckets))
        # labels tuple -> [bucket counts..., +Inf count, sum]
        self.series: dict[tuple, list] = {}
        # (labels tuple, bucket index) -> (trace_id, value): the most
        # recent exemplar observed into that bucket, rendered in
        # OpenMetrics exemplar syntax so a tail bucket links straight to
        # a loadable trace (docs/OBSERVABILITY.md "Distributed tracing")
        self.exemplars: dict[tuple, tuple] = {}

    def observe(self, value: float, labels: tuple, exemplar=None):
        row = self.series.get(labels)
        if row is None:
            row = [0] * (len(self.buckets) + 1) + [0.0]
            self.series[labels] = row
        landed = len(self.buckets)       # +Inf unless a bucket matches
        for i, le in enumerate(self.buckets):
            if value <= le:
                row[i] += 1
                landed = min(landed, i)
        row[len(self.buckets)] += 1      # +Inf == total count
        row[-1] += value                 # running sum
        if exemplar:
            self.exemplars[(labels, landed)] = (str(exemplar), float(value))


class Metrics:
    """Process-wide metric registry (counters + gauges + histograms)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        # labelled counter families: name -> {sorted labels tuple: value}
        self.lcounters: dict[str, dict[tuple, float]] = {}
        # labelled gauge families: name -> {sorted labels tuple: value}
        self.lgauges: dict[str, dict[tuple, float]] = {}
        self.histograms: dict[str, _Histogram] = {}
        self.help: dict[str, str] = {}
        self.started = time.time()

    def inc(self, name: str, value: float = 1.0, help_text: str = ""):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0.0) + value
            if help_text:
                self.help[name] = help_text

    def set(self, name: str, value: float, help_text: str = ""):
        with self.lock:
            self.gauges[name] = value
            if help_text:
                self.help[name] = help_text

    def _clamped(self, fam: dict, key: tuple) -> bool:
        """Caller holds the lock.  True when a NEW label set would push
        one family past MAX_LABEL_SETS: the series is dropped (existing
        series keep updating) and the drop is counted."""
        if key in fam or len(fam) < MAX_LABEL_SETS:
            return False
        self.counters["metrics_dropped_label_sets_total"] = \
            self.counters.get("metrics_dropped_label_sets_total", 0.0) + 1
        self.help.setdefault(
            "metrics_dropped_label_sets_total",
            "Series dropped by the per-family label-set clamp "
            "(MAX_LABEL_SETS) — cardinality protection against "
            "unbounded label values")
        return True

    def inc_labeled(self, name: str, labels: dict, value: float = 1.0,
                    help_text: str = ""):
        """Increment one series of a labelled counter family (e.g.
        per-reason mempool rejections)."""
        key = tuple(sorted((labels or {}).items()))
        with self.lock:
            fam = self.lcounters.setdefault(name, {})
            if self._clamped(fam, key):
                return
            fam[key] = fam.get(key, 0.0) + float(value)
            if help_text:
                self.help[name] = help_text

    def set_labeled(self, name: str, labels: dict, value: float,
                    help_text: str = ""):
        """Set one series of a labelled gauge family (e.g. per-kernel
        roofline gauges, prover_kernel_flops{air,stage})."""
        key = tuple(sorted((labels or {}).items()))
        with self.lock:
            fam = self.lgauges.setdefault(name, {})
            if self._clamped(fam, key):
                return
            fam[key] = float(value)
            if help_text:
                self.help[name] = help_text

    def observe(self, name: str, value: float,
                labels: dict | None = None, help_text: str = "",
                buckets=DEFAULT_BUCKETS, exemplar: str | None = None):
        """Record one observation into a labelled histogram.

        ``exemplar`` optionally attaches a trace ID to the bucket this
        value lands in, surfaced in OpenMetrics exemplar syntax by
        ``render`` so tail buckets link to a loadable trace."""
        key = tuple(sorted((labels or {}).items()))
        with self.lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = _Histogram(buckets)
            if self._clamped(hist.series, key):
                return
            hist.observe(float(value), key, exemplar=exemplar)
            if help_text:
                self.help[name] = help_text

    def snapshot(self) -> dict:
        """Point-in-time plain-data copy of the registry (JSON-safe).

        The time-series engine samples this periodically; histogram rows
        keep the cumulative-per-bucket layout so window deltas can be
        taken bucket-by-bucket."""
        with self.lock:
            hists = {}
            for name, hist in self.histograms.items():
                nb = len(hist.buckets)
                hists[name] = {
                    "buckets": [float(b) for b in hist.buckets],
                    "series": [
                        {"labels": dict(labels),
                         "counts": [int(c) for c in row[:nb + 1]],
                         "sum": float(row[-1])}
                        for labels, row in hist.series.items()],
                }
            return {"ts": time.time(),
                    "counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "labeled_counters": {
                        name: [{"labels": dict(labels), "value": value}
                               for labels, value in fam.items()]
                        for name, fam in self.lcounters.items()},
                    "labeled_gauges": {
                        name: [{"labels": dict(labels), "value": value}
                               for labels, value in fam.items()]
                        for name, fam in self.lgauges.items()},
                    "histograms": hists}

    def reset(self):
        """Drop every series and restart the uptime clock (test isolation
        and simulated process restarts)."""
        with self.lock:
            self.counters.clear()
            self.gauges.clear()
            self.lcounters.clear()
            self.lgauges.clear()
            self.histograms.clear()
            self.help.clear()
            self.started = time.time()

    def _render_histograms(self, lines: list):
        for name, hist in sorted(self.histograms.items()):
            if name in self.help:
                lines.append(f"# HELP {name} {self.help[name]}")
            lines.append(f"# TYPE {name} histogram")
            nb = len(hist.buckets)
            for labels, row in sorted(hist.series.items()):
                base = _fmt_labels(labels)
                sep = "," if base else ""

                def _ex(i, labels=labels):
                    # OpenMetrics exemplar: `... 5 # {trace_id="x"} 0.23`
                    # (no timestamp — keeps goldens and diffs stable)
                    ex = hist.exemplars.get((labels, i))
                    if not ex:
                        return ""
                    return (f' # {{trace_id="{_escape_label(ex[0])}"}}'
                            f" {ex[1]}")

                for i, le in enumerate(hist.buckets):
                    lines.append(
                        f'{name}_bucket{{{base}{sep}le="{_fmt_le(le)}"}} '
                        f"{row[i]}{_ex(i)}")
                lines.append(
                    f'{name}_bucket{{{base}{sep}le="+Inf"}} '
                    f"{row[nb]}{_ex(nb)}")
                brace = f"{{{base}}}" if base else ""
                lines.append(f"{name}_sum{brace} {row[-1]}")
                lines.append(f"{name}_count{brace} {row[nb]}")

    def render(self) -> str:
        with self.lock:
            lines = []
            for name, value in sorted(self.counters.items()):
                if name in self.help:
                    lines.append(f"# HELP {name} {self.help[name]}")
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {value}")
            for name, fam in sorted(self.lcounters.items()):
                if name in self.help:
                    lines.append(f"# HELP {name} {self.help[name]}")
                lines.append(f"# TYPE {name} counter")
                for labels, value in sorted(fam.items()):
                    lines.append(f"{name}{{{_fmt_labels(labels)}}} {value}")
            for name, value in sorted(self.gauges.items()):
                if name in self.help:
                    lines.append(f"# HELP {name} {self.help[name]}")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {value}")
            for name, fam in sorted(self.lgauges.items()):
                if name in self.help:
                    lines.append(f"# HELP {name} {self.help[name]}")
                lines.append(f"# TYPE {name} gauge")
                for labels, value in sorted(fam.items()):
                    lines.append(f"{name}{{{_fmt_labels(labels)}}} {value}")
            self._render_histograms(lines)
            lines.append("# TYPE process_uptime_seconds gauge")
            lines.append(
                f"process_uptime_seconds {time.time() - self.started}")
            return "\n".join(lines) + "\n"


METRICS = Metrics()  # global registry, like the reference's statics


def record_block(block, elapsed: float):
    METRICS.inc("ethrex_blocks_imported_total", 1,
                "Blocks imported through add_block")
    METRICS.inc("ethrex_gas_used_total", block.header.gas_used,
                "Cumulative gas executed")
    METRICS.inc("ethrex_transactions_total",
                len(block.body.transactions), "Transactions executed")
    METRICS.set("ethrex_head_block", block.header.number,
                "Current head block number")
    if elapsed > 0:
        METRICS.set("ethrex_last_block_mgas_per_s",
                    block.header.gas_used / elapsed / 1e6,
                    "Execution throughput of the last imported block")


def record_reassignment(batch_number: int, prover_type: str):
    METRICS.inc("proof_reassignments_total", 1,
                "Prover assignments re-issued after lease expiry or a "
                "rejected proof")


def record_quarantine(count: int):
    METRICS.set("quarantined_batches", count,
                "Batches quarantined off their primary prover type onto "
                "the fallback backend")


def record_poll_error():
    METRICS.inc("prover_poll_errors_total", 1,
                "Prover client poll passes that failed on an endpoint")


def record_breaker(open_count: int, transition: bool = False):
    METRICS.set("prover_breaker_open", open_count,
                "Coordinator endpoints currently skipped by an open "
                "circuit breaker")
    if transition:
        METRICS.inc("prover_breaker_transitions_total", 1,
                    "Circuit breaker state transitions "
                    "(closed/open/half-open)")


def record_heartbeat():
    METRICS.inc("prover_heartbeats_total", 1,
                "Lease-extending heartbeats accepted by the coordinator")


def record_stale_submit():
    METRICS.inc("proof_stale_submits_total", 1,
                "Proof submits refused for missing or non-current lease "
                "tokens (left lease and failure state untouched)")


def record_submit_rejected():
    METRICS.inc("prover_submit_rejections_total", 1,
                "Proof submits the coordinator rejected at the "
                "application level (endpoint healthy; not a breaker "
                "failure)")


def record_hedged_assignment():
    METRICS.inc("prover_hedged_assignments_total", 1,
                "Speculative (hedged) re-assignments of straggler "
                "batches past the p99-derived deadline, plus "
                "work-stealing grants; first result wins, the loser's "
                "submit is a deduplicated no-op")


def record_cold_deferral():
    METRICS.inc("prover_cold_deferrals_total", 1,
                "Assignments withheld from provers that reported "
                "themselves cold (AOT kernels not yet hydrated) while "
                "recently-seen warm provers could absorb the queue")


def record_scheduler_queue_depth(depth: int):
    METRICS.set("scheduler_queue_depth", depth,
                "Provable batches awaiting an assignment at the last "
                "scheduling decision (unleased work the fleet has not "
                "picked up yet)")


def record_aggregation(count: int, last_batch: int):
    METRICS.inc("proofs_aggregated_total", count,
                "Per-batch proofs folded into aggregated settlement "
                "proofs (the N of every N-to-1 recursion step)")
    METRICS.set("aggregation_ratio", count,
                "Batch proofs covered by the most recent aggregated "
                "settlement (the amortization factor N of that L1 tx)")
    METRICS.set("ethrex_l2_last_aggregated_batch", last_batch,
                "Highest L2 batch settled through the aggregation "
                "pipeline (the aggregation-lag alert reads latest_batch "
                "minus this on nodes that aggregate)")


def record_l1_reorg():
    METRICS.inc("l1_reorgs_total", 1,
                "L1 reorgs detected through a settlement regression "
                "(last_committed/verified moved backwards)")


def record_chain_reorg(depth: int):
    METRICS.inc("chain_reorgs_total", 1,
                "Execution-chain reorgs applied by fork choice (at "
                "least one formerly-canonical block was orphaned)")
    _observe_safe("chain_reorg_depth", float(depth), None,
                  "Blocks orphaned per execution-chain reorg (the "
                  "deep_reorg alert pair reads the p95 of this)")


def record_mempool_reinjection():
    METRICS.inc("mempool_reinjections_total", 1,
                "Transactions re-injected into the mempool from "
                "orphaned blocks after a reorg (the typed reinjected "
                "path: admission fee-floor/sender-cap rules bypassed)")


def record_mempool_reorg_eviction(reason: str):
    METRICS.inc("mempool_reorg_evictions_total", 1,
                "Pool entries dropped by a reorg transition, any reason")
    METRICS.inc_labeled("mempool_reorg_evictions_by_reason",
                        {"reason": reason}, 1.0,
                        help_text="Reorg-driven mempool drops by reason "
                                  "(adopted = included on the winning "
                                  "branch, nonce_below_account / "
                                  "insufficient_balance = revalidation "
                                  "prunes, blob_unrecoverable = orphaned "
                                  "blob tx whose sidecar is gone)")


def record_txloc_stale_read():
    METRICS.inc("txloc_stale_reads_total", 1,
                "Transaction-location lookups that referenced a "
                "non-canonical block and were refused (verify-on-read "
                "guard; should stay 0 while fork choice prunes txlocs "
                "in the same write group)")


def record_recommit():
    METRICS.inc("batches_recommitted_total", 1,
                "Batches re-committed verbatim after an L1 reorg dropped "
                "their commitment")


def record_commit_adopted():
    METRICS.inc("l1_commits_adopted_total", 1,
                "Commit attempts adopted as success because the L1 "
                "already held a matching commitment (retry after a lost "
                "acknowledgment)")


def record_transient_error():
    METRICS.inc("sequencer_transient_errors_total", 1,
                "Sequencer actor iterations that failed with a transient "
                "(network-class) error and were retried with backoff")


def record_store_corruption():
    METRICS.inc("store_corruption_total", 1,
                "Persistent-store records whose checksum failed on read "
                "(detected, quarantined, never served)")


def record_store_rebuild():
    METRICS.inc("store_rebuilds_total", 1,
                "Quarantined records re-derived from surviving chain data "
                "(canonical index rebuilt by parent-hash walk)")


def record_journal_replay():
    METRICS.inc("store_journal_replays_total", 1,
                "Write-ahead journals replayed into the KV log on reopen "
                "(crash landed after the journal was durable)")


def record_journal_discard():
    METRICS.inc("store_journal_discards_total", 1,
                "Torn or corrupt write-ahead journals discarded on reopen "
                "(crash landed mid-journal; the batch never committed)")


def record_shutdown_duration(seconds: float):
    METRICS.set("shutdown_duration_seconds", seconds,
                "Wall-clock of the last coordinated shutdown drain")


def record_batch(batch_number: int, proving_time: float | None = None,
                 trace_id: str | None = None):
    METRICS.set("ethrex_l2_latest_batch", batch_number,
                "Latest committed L2 batch")
    if proving_time is not None:
        METRICS.set("ethrex_l2_batch_proving_seconds", proving_time,
                    "Wall-clock of the last batch proof")
        _observe_safe("batch_proving_seconds", proving_time, None,
                      "Batch proof wall-clock distribution (drives the "
                      "proving-latency p95 SLO)", exemplar=trace_id)


def record_verified_batch(batch_number: int):
    METRICS.set("ethrex_l2_last_verified_batch", batch_number,
                "Highest L2 batch verified on the L1 (settlement-lag "
                "alert reads latest_batch minus this)")


# sequencer HA roles encoded as a numeric gauge (docs/SEQUENCER_HA.md)
_ROLE_VALUES = {"follower": 0.0, "candidate": 1.0, "promoting": 2.0,
                "leader": 3.0}


def record_leadership_role(role: str):
    METRICS.set("sequencer_role", _ROLE_VALUES.get(role, -1.0),
                "Sequencer HA role of this node "
                "(0=follower 1=candidate 2=promoting 3=leader)")


def record_leadership_epoch(epoch: int):
    METRICS.set("leadership_epoch", float(epoch),
                "Fencing epoch of this node's current leader lease "
                "(monotonic across the deployment; stamped on every "
                "externally-visible sequencer write)")


def record_leadership_transition(frm: str, to: str):
    METRICS.inc_labeled("leadership_transitions_by_edge", {
                        "from": frm, "to": to}, 1,
                        help_text="Sequencer HA role transitions by "
                        "from/to edge (failover forensics)")
    METRICS.inc("leadership_transitions_total", 1,
                "Sequencer HA role transitions (unlabelled companion of "
                "leadership_transitions_by_edge; a churning value means "
                "the lease is flapping)")


def record_leadership_fenced():
    METRICS.inc("leadership_fenced_writes_total", 1,
                "Writes refused by the L1 or the rollup store because "
                "they carried a stale fencing epoch (a deposed zombie "
                "leader was stopped from corrupting shared state)")


def record_leadership_promotion(downtime: float):
    METRICS.set("leadership_promotion_downtime_seconds", downtime,
                "Wall-clock of the last follower-to-leader promotion "
                "(lease win to actors unparked: reconciliation + "
                "journal replay + prover-fleet re-home)")
    _observe_safe("leadership_promotion_seconds", downtime, None,
                  "Promotion wall-clock distribution (failover drill "
                  "budget: must stay within the lease ttl)")


def record_kernel_build(air: str, seconds: float, mesh: str = "none"):
    # labelled by mesh shape ("none", "4", "2x4") so mesh<->no-mesh
    # switches and sub-slice churn show up as distinct retrace series
    METRICS.inc_labeled("prover_kernel_retraces_total", {"mesh": mesh}, 1,
                        help_text="STARK phase-program builds (jit "
                        "retraces) by mesh shape: cache misses in the "
                        "in-process phase cache")
    _observe_safe("prover_kernel_build_seconds", seconds,
                  {"air": air, "mesh": mesh},
                  "Wall-clock to build+stage the jitted STARK phase "
                  "programs for one AIR shape (AOT compile included)")


def record_phase_compile(air: str, kernel: str, seconds: float,
                         mesh: str = "none", source: str = "compiled"):
    _observe_safe("prover_phase_compile_seconds", seconds,
                  {"air": air, "kernel": kernel, "mesh": mesh,
                   "source": source},
                  "Per-phase-program build wall by AIR, kernel, mesh "
                  "shape and source (compiled = fresh AOT lower+compile "
                  "— the cold-start baseline; deserialized = hydrated "
                  "from the on-disk executable cache)")


def record_phase_resume(phase: str):
    METRICS.inc("prover_phase_resumes_total", 1,
                "Completed prove phases skipped on restart: loaded from "
                "an on-disk phase checkpoint instead of re-proven")
    METRICS.inc_labeled("prover_phase_resumes_by_phase", {"phase": phase},
                        1, help_text="Checkpoint-resumed prove phases by "
                        "phase name (which phase a restarted prover "
                        "picked up from)")


def record_oom_retry(phase: str):
    METRICS.inc("prover_oom_retries_total", 1,
                "Prove phases retried after a transient runtime failure "
                "(XLA RESOURCE_EXHAUSTED or device loss) via the "
                "degraded-mesh fallback ladder")


def record_mesh_degradation(frm: str, to: str):
    METRICS.inc_labeled("prover_mesh_degradations_total",
                        {"from": frm, "to": to}, 1,
                        help_text="Mesh-layout downgrades by from/to "
                        "shape: the fallback ladder or the pre-prove "
                        "memory gate moved a prove to a smaller layout")
    METRICS.inc("prover_mesh_degradations_count", 1,
                "Mesh-layout downgrades (unlabelled companion of "
                "prover_mesh_degradations_total, feeds the "
                "prover_runtime_degraded alert rate)")


def record_nan_poison(phase: str):
    METRICS.inc("prover_nan_poison_total", 1,
                "Prove phases whose outputs were non-finite or out of "
                "field: the batch is quarantined immediately, never "
                "retried")


def record_mesh_devices(n: int):
    METRICS.set("prover_mesh_devices", float(n),
                help_text="Devices in the prover backend's JAX mesh "
                "(1 = unsharded single-device proving)")


def record_vm_parallelism(n: int):
    METRICS.set("prover_vm_circuits_parallel", float(n),
                help_text="Concurrent mesh slices used for the last "
                "batch's VM-circuit STARK proofs (1 = serial)")


def record_device_occupancy(fraction: float, idle_gap_seconds: float,
                            devices: int = 1):
    METRICS.set("prover_device_occupancy", float(fraction),
                help_text="Device-occupancy fraction of the last prove: "
                "busy-device-seconds / (mesh devices x wall).  The "
                "serial fallback on an N-device mesh is bounded by 1/N "
                "(prover_occupancy_floor alert)")
    METRICS.set("prover_device_idle_gap_seconds", float(idle_gap_seconds),
                help_text="Wall-clock of the last prove's VM batch "
                "during which no mesh slice was busy — the "
                "between-phase bubbles cross-batch pipelining would "
                "fill (ROADMAP item 1c)")


def record_jax_compile(seconds: float):
    METRICS.inc("jax_backend_compiles_total", 1,
                "XLA backend compilations observed via jax.monitoring")
    _observe_safe("jax_backend_compile_seconds", seconds, None,
                  "XLA backend compile wall-clock per compilation")


def record_jax_cache_event(hit: bool):
    if hit:
        METRICS.inc("jax_compilation_cache_hits_total", 1,
                    "Persistent XLA compilation-cache hits")
    else:
        METRICS.inc("jax_compilation_cache_misses_total", 1,
                    "Persistent XLA compilation-cache misses")


def record_jax_device_memory(bytes_in_use: float, peak_bytes: float):
    METRICS.set("jax_device_bytes_in_use", bytes_in_use,
                "Accelerator memory currently allocated, summed over "
                "local devices")
    METRICS.set("jax_device_peak_bytes_in_use", peak_bytes,
                "Peak accelerator memory allocated, summed over local "
                "devices")


def record_jax_live_arrays(count: float):
    METRICS.set("jax_live_arrays", count,
                "Live JAX arrays currently tracked by the runtime")


def record_telemetry_sample():
    METRICS.inc("telemetry_samples_total", 1,
                "Registry samples taken by the time-series engine")


def record_alert_transition(rule: str, event: str):
    METRICS.inc("alert_transitions_total", 1,
                "Alert state transitions (firing or resolved) across all "
                "rules")


def record_alerts_firing(count: int):
    METRICS.set("alerts_firing", count,
                "Alert rules currently in the firing state")


def record_snapshot_written():
    METRICS.inc("debug_snapshots_total", 1,
                "Flight-recorder debug snapshots written to disk")


def _observe_safe(name, value, labels, help_text, exemplar=None):
    # Telemetry sits inside hot/traced paths; it must never raise there.
    try:
        METRICS.observe(name, value, labels, help_text, exemplar=exemplar)
    except Exception:
        pass


def observe_rpc_request(method: str, seconds: float,
                        trace_id: str | None = None):
    _observe_safe("rpc_request_seconds", seconds, {"method": method},
                  "JSON-RPC request latency by method", exemplar=trace_id)


def observe_critical_path(component: str, seconds: float,
                          trace_id: str | None = None):
    _observe_safe("batch_critical_path_seconds", seconds,
                  {"component": component},
                  "Per-component critical-path attribution of a settled "
                  "batch's merged lifecycle trace (queue-wait / assign / "
                  "prove stages / transport / verify / settle; "
                  "docs/OBSERVABILITY.md)", exemplar=trace_id)


def record_trace_ingest(added: int, dropped: int = 0):
    if added:
        METRICS.inc("trace_spans_ingested_total", added,
                    "Remote spans merged into the local trace ring "
                    "(span shipping over ProofSubmit/Heartbeat)")
    if dropped:
        METRICS.inc("trace_spans_ingest_dropped_total", dropped,
                    "Shipped spans dropped at ingestion: malformed, "
                    "over the per-source cap, or over the per-trace "
                    "span budget")


def observe_rpc_queue_wait(seconds: float):
    _observe_safe("rpc_queue_wait_seconds", seconds, None,
                  "Accept-to-handler queue wait: time a connection sat "
                  "between the accept loop and its handler thread "
                  "picking it up (rises when the thread pool or the "
                  "accept loop saturates)")


def record_rpc_accept():
    METRICS.inc("rpc_connections_accepted_total", 1,
                "TCP connections accepted by the JSON-RPC listener")


def record_rpc_reset():
    METRICS.inc("rpc_connections_reset_total", 1,
                "RPC connections that died mid-request "
                "(ECONNRESET/EPIPE) — the backlog-pressure signal: "
                "kernel RSTs from an overflowing listen queue land "
                "here")


def record_rpc_eof():
    METRICS.inc("rpc_connections_eof_total", 1,
                "RPC connections closed before a complete request "
                "arrived (short body or empty read)")


def record_rpc_bytes(request_bytes: int, response_bytes: int):
    METRICS.inc("rpc_request_bytes_total", request_bytes,
                "Cumulative JSON-RPC request body bytes read")
    METRICS.inc("rpc_response_bytes_total", response_bytes,
                "Cumulative JSON-RPC response body bytes written")


def record_rpc_inflight(count: int):
    METRICS.set("rpc_inflight_requests", count,
                "JSON-RPC requests currently executing in handler "
                "threads")


def record_rpc_method_inflight(method: str, count: int):
    METRICS.set_labeled("rpc_method_inflight", {"method": method}, count,
                        help_text="Concurrent executions of one JSON-RPC "
                                  "method right now")


def record_rpc_backlog(size: int):
    METRICS.set("rpc_listen_backlog", size,
                "Configured TCP listen backlog of the JSON-RPC server "
                "(--rpc-backlog / ETHREX_RPC_BACKLOG)")


def record_rpc_slow_request():
    METRICS.inc("rpc_slow_requests_total", 1,
                "Requests slower than the slow-request threshold "
                "(ETHREX_RPC_SLOW_SECONDS); each emits a structured "
                "log line carrying its trace ID")


def record_rpc_batch(entries: int):
    METRICS.inc("rpc_batch_requests_total", 1,
                "JSON-RPC batch arrays received (entries dispatched "
                "concurrently on the event loop, responses reassembled "
                "in order; capped by ETHREX_RPC_MAX_BATCH)")
    METRICS.inc("rpc_batch_entries_total", entries,
                "Individual requests carried inside JSON-RPC batch "
                "arrays (each still admitted and measured on its own)")


def record_rpc_executor_workers(count: int):
    METRICS.set("rpc_executor_workers", count,
                "Bound of the RPC execution-stage thread pool "
                "(ETHREX_RPC_EXECUTOR_WORKERS): blocking handler "
                "bodies run here so they never stall the event loop")


def record_rpc_shed(reason: str, cost_class: str):
    METRICS.inc("rpc_requests_shed_total", 1,
                "Requests refused by admission control with the typed "
                "server-busy error, any reason (the shed-rate alert "
                "reads this; docs/OVERLOAD.md)")
    METRICS.inc_labeled("rpc_requests_shed_by_reason",
                        {"reason": reason, "class": cost_class}, 1.0,
                        help_text="Admission-control sheds by reason "
                                  "(deadline, concurrency, level) and "
                                  "cost class (read, submit, heavy)")


def record_shed_level(level: int):
    METRICS.set("rpc_shed_level", level,
                "Current adaptive shed level of the RPC admission "
                "controller (0 = admit everything, 1 = shed heavy, "
                "2 = +submit, 3 = shed all but control)")


def record_ws_connections(count: int):
    METRICS.set("ws_connections", count,
                "WebSocket subscription connections currently open")


def record_ws_accept():
    METRICS.inc("ws_connections_accepted_total", 1,
                "WebSocket connections accepted (successful RFC 6455 "
                "handshakes)")


def record_ws_notification(count: int = 1):
    METRICS.inc("ws_notifications_total", count,
                "Subscription notification frames pushed to WebSocket "
                "clients")


def record_ws_send_failure():
    METRICS.inc("ws_send_failures_total", 1,
                "Notification pushes that failed on a dead WebSocket "
                "(connection dropped from the fan-out set)")


def record_ws_notification_drop():
    METRICS.inc("ws_notifications_dropped_total", 1,
                "Subscription notifications dropped because a "
                "consumer's bounded send queue was full (the slow "
                "consumer keeps its connection until the deadline)")


def record_ws_slow_consumer_disconnect():
    METRICS.inc("ws_slow_consumer_disconnects_total", 1,
                "WebSocket connections force-closed because the "
                "consumer stayed full past the slow-consumer deadline "
                "instead of blocking fan-out for healthy subscribers")


def record_mempool_admission():
    METRICS.inc("mempool_admitted_total", 1,
                "Transactions admitted into the mempool")


def record_mempool_rejection(reason: str):
    METRICS.inc("mempool_rejections_total", 1,
                "Transactions rejected by mempool admission, any reason")
    METRICS.inc_labeled("mempool_rejections_by_reason", {"reason": reason},
                        1.0,
                        help_text="Mempool admission rejections by typed "
                                  "reason (nonce_too_low, underpriced, "
                                  "insufficient_funds, invalid_signature, "
                                  "pool_full, blobs_missing, privileged, "
                                  "wrong_chain_id, nonce_gap, "
                                  "sender_limit, fee_below_floor)")


def record_mempool_replacement():
    METRICS.inc("mempool_replacements_total", 1,
                "Replacement-by-fee admissions (same sender+nonce with "
                "a >=10% fee bump); the replacement-churn alert reads "
                "this — a fee-bump war churns the pool without adding "
                "throughput")


def record_mempool_eviction(reason: str):
    METRICS.inc("mempool_evictions_total", 1,
                "Transactions evicted from the mempool after admission, "
                "any reason")
    METRICS.inc_labeled("mempool_evictions_by_reason", {"reason": reason},
                        1.0,
                        help_text="Mempool evictions by reason (fifo "
                                  "capacity, blob_pool_full, replaced, "
                                  "invalid_at_build)")


def record_mempool_occupancy(size: int, utilization: float):
    METRICS.set("mempool_size", size,
                "Transactions currently resident in the mempool")
    METRICS.set("mempool_utilization", utilization,
                "Mempool occupancy over capacity — the max of the "
                "regular and blob sub-pool fill fractions (1.0 = every "
                "new tx evicts another; the saturation alert reads "
                "this)")


def observe_time_in_pool(seconds: float, reason: str = "included"):
    # labelled by removal reason so inclusion dwell is not polluted by
    # eviction/prune/reorg dwell (they answer different questions:
    # "how long until a block?" vs "how long do we hold junk?")
    _observe_safe("mempool_time_in_pool_seconds", seconds,
                  {"reason": reason},
                  "Admission-to-removal dwell time of mempool "
                  "transactions, labelled by removal reason (included "
                  "vs evicted/pruned/reorg/...)")


def observe_prover_stage(stage: str, seconds: float):
    _observe_safe("prover_stage_seconds", seconds, {"stage": stage},
                  "Per-stage prover latency (block_until_ready-bounded)")


def observe_block_execution(seconds: float):
    _observe_safe("block_execution_seconds", seconds, None,
                  "EVM execution time per block (execute_block)")


def observe_block_import(seconds: float):
    _observe_safe("block_import_seconds", seconds, None,
                  "End-to-end block import time (add_block)")


def observe_actor_iteration(actor: str, seconds: float):
    _observe_safe("sequencer_actor_seconds", seconds, {"actor": actor},
                  "Sequencer actor loop iteration latency")


def observe_import_stage(stage: str, seconds: float):
    """Sub-stage attribution of block import (execute / merkleize /
    store_write), both the per-block and the pipelined path."""
    _observe_safe("block_import_stage_seconds", seconds, {"stage": stage},
                  "Block import sub-stage latency (execute / merkleize / "
                  "store_write legs of add_block and the pipelined "
                  "importer)")


def record_kernel_flops(air: str, kernel: str, flops: float,
                        achieved: float | None = None,
                        utilization: float | None = None):
    """Roofline gauges for one compiled STARK phase program (never
    raises: called from the prover hot path)."""
    try:
        labels = {"air": air, "stage": kernel}
        METRICS.set_labeled(
            "prover_kernel_flops", labels, flops,
            help_text="XLA cost-model FLOPs of the compiled STARK phase "
                      "program (static, per air+stage)")
        if achieved is not None:
            METRICS.set_labeled(
                "prover_kernel_achieved_flops_per_sec", labels, achieved,
                help_text="Cost-model FLOPs divided by the last measured "
                          "stage wall-clock")
        if utilization is not None:
            METRICS.set_labeled(
                "prover_kernel_utilization", labels, utilization,
                help_text="Achieved-FLOP/s over the estimated backend "
                          "peak (see docs/PERFORMANCE.md caveats)")
    except Exception:
        pass


def record_import_throughput(mgas_per_sec: float):
    METRICS.set("l1_import_mgas_per_sec", mgas_per_sec,
                "Execution throughput of the last pipelined block-batch "
                "import (Mgas/s; the bench headline L1 number, live)")


def record_prover_throughput(cells_per_sec: float):
    METRICS.set("prover_trace_cells_per_sec", cells_per_sec,
                "Trace cells proven per second in the last STARK prove "
                "(n x width over end-to-end prove wall-clock)")


def record_senders_recovered(count: int):
    METRICS.inc("senders_recovered_total", count,
                "Transaction senders recovered by the batched "
                "sender-recovery stage (either engine; excludes "
                "cache hits)")


def observe_sender_recovery_batch(seconds: float):
    _observe_safe("sender_recovery_batch_seconds", seconds, None,
                  "Wall-clock of one batched sender-recovery call "
                  "(whole tx list, all pool workers joined)")


def record_proof_wall(seconds: float):
    """Derive the proofs_per_hour throughput gauge from one end-to-end
    backend prove wall-clock."""
    if seconds > 0:
        METRICS.set("proofs_per_hour", 3600.0 / seconds,
                    "Extrapolated proofs per hour from the last "
                    "end-to-end backend prove wall-clock")


# -- p2p request resilience + snap-sync (docs/P2P_RESILIENCE.md) -----------

def record_p2p_timeout(klass: str):
    METRICS.inc("p2p_request_timeouts_total", 1,
                "P2P requests that outlived their adaptive (phi-accrual) "
                "timeout, across all request classes")
    METRICS.inc_labeled("p2p_request_class_timeouts", {"class": klass}, 1,
                        help_text="P2P request timeouts by request class "
                                  "(headers/ranges/trie/...)")


def record_p2p_retry(klass: str):
    METRICS.inc("p2p_request_retries_total", 1,
                "P2P request retry attempts (fresh request id, jittered "
                "exponential backoff) after a timeout or dropped frame")


def record_p2p_ban():
    METRICS.inc("p2p_peer_bans_total", 1,
                "Peers banned after dropping to SCORE_DISCONNECT; bans "
                "persist in store.meta['p2p_bans'] across restarts")


def record_p2p_broadcast_failure():
    METRICS.inc("p2p_broadcast_failures_total", 1,
                "Block/hash broadcast sends that failed (dead or stalled "
                "peer); each also costs the peer a score penalty")


def record_p2p_peer_rtt(peer: str, seconds: float):
    METRICS.set_labeled("p2p_peer_rtt_seconds", {"peer": peer}, seconds,
                        help_text="EWMA request round-trip time per peer "
                                  "(the phi-accrual estimator mean)")


def record_snap_phase(phase: int):
    METRICS.set("snap_sync_phase", phase,
                "Snap-sync phase: 0 idle, 1 accounts, 2 healing, 3 done")


def record_snap_range():
    METRICS.inc("snap_ranges_synced_total", 1,
                "Account-range windows fetched, proof-verified and "
                "checkpointed by snap-sync (each is one leased unit; "
                "kill-restart re-fetches at most one)")


def record_snap_paused(paused: bool):
    METRICS.set("snap_sync_paused", 1 if paused else 0,
                "1 while snap-sync is paused with zero live peers "
                "(network partition), 0 otherwise")
    if paused:
        METRICS.inc("snap_partition_pauses_total", 1,
                    "Times snap-sync paused on a total peer partition "
                    "and waited for a peer to return")


def record_snap_progress_reset():
    METRICS.inc("snap_progress_resets_total", 1,
                "Torn/garbage snap_sync checkpoint blobs discarded at "
                "load (sync restarted from scratch instead of crashing)")


class MetricsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 9090):
        self.host = host
        self.port = port
        self._httpd = None

    def start(self):
        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                # A scraper may abort mid-response; a dead socket is the
                # scraper's problem, never the server thread's.
                try:
                    if self.path != "/metrics":
                        body = b"not found\n"
                        self.send_response(404)
                        self.send_header("Content-Type",
                                         "text/plain; charset=utf-8")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = METRICS.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
