"""On-disk cache of serialized AOT executables: compiled prover kernels
as durable, shippable artifacts.

The in-process phase cache (stark/prover._PHASE_CACHE) amortizes
compiles within one process; this store amortizes them across processes
and hosts of the same shape.  Every AOT `lower().compile()` result the
prover produces is serialized through
`jax.experimental.serialize_executable` into a content-addressed entry,
and every phase-program build asks this store first — a restarting
prover hydrates in deserialize time (milliseconds per kernel) instead
of recompiling for minutes.  Ship the cache directory in a deploy image
and the first proof after a restart runs at steady-state wall.

Key schema: an entry's filename is the SHA-256 of its JSON-canonical
key parts — the program identity (AIR cache key, log_n, blowup, shift,
kernel, mesh device layout) — joined with the environment parts
(backend platform, jax/jaxlib versions).  A jaxlib upgrade or a backend
switch therefore changes every key: stale entries are structurally
unreachable, not a correctness hazard.  Corruption, truncation, or an
unpicklable payload is a clean miss (plus `executable_cache_errors_total`
and a best-effort unlink); retention is bounded by pruning
least-recently-used entries past a cap.

Env knobs (documented in docs/PERFORMANCE.md "Cold start"):
  ETHREX_EXEC_CACHE_DIR  cache directory (default
                         /tmp/ethrex_tpu_exec_cache_<host fingerprint>)
  ETHREX_EXEC_CACHE_MAX  max entries retained after a store (default 512)
  ETHREX_EXEC_CACHE_OFF  "1" disables both lookup and store
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading

_SCHEMA = 1
_SUFFIX = ".exe.pkl"
_DEFAULT_MAX_ENTRIES = 512

_LOCK = threading.Lock()
_CONFIGURED_DIR: str | None = None
STATS = {"hits": 0, "misses": 0, "errors": 0, "stores": 0}


def record_exec_cache_hit() -> None:
    from .metrics import METRICS

    METRICS.inc("executable_cache_hits_total", 1,
                "Serialized-executable cache hits: AOT prover kernels "
                "hydrated from disk instead of recompiled")


def record_exec_cache_miss() -> None:
    from .metrics import METRICS

    METRICS.inc("executable_cache_misses_total", 1,
                "Serialized-executable cache misses: AOT prover kernels "
                "that had to be compiled from scratch")


def record_exec_cache_error() -> None:
    from .metrics import METRICS

    METRICS.inc("executable_cache_errors_total", 1,
                "Serialized-executable cache failures: entries dropped as "
                "corrupt, truncated or unloadable, and stores rejected "
                "because the payload failed its round-trip validation")


def set_cache_dir(path: str | None) -> None:
    """Explicit cache directory (the `--executable-cache-dir` CLI flag);
    overrides ETHREX_EXEC_CACHE_DIR and the /tmp default."""
    global _CONFIGURED_DIR
    with _LOCK:
        _CONFIGURED_DIR = path


def cache_dir() -> str:
    with _LOCK:
        configured = _CONFIGURED_DIR
    if configured:
        return configured
    env = os.environ.get("ETHREX_EXEC_CACHE_DIR")
    if env:
        return env
    from .jax_cache import cache_dir as _fingerprinted

    return _fingerprinted(prefix="/tmp/ethrex_tpu_exec_cache")


def enabled() -> bool:
    return os.environ.get("ETHREX_EXEC_CACHE_OFF") != "1"


def mesh_fingerprint(mesh) -> tuple | None:
    """Cache identity of a mesh: exact device ids, axis names and layout
    shape (a compiled executable is bound to its devices).  None (no
    mesh) is its own key."""
    if mesh is None:
        return None
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names), tuple(mesh.devices.shape))


_CODE_FINGERPRINT: str | None = None


def _code_fingerprint() -> str:
    """Hash of the kernel-defining sources (ops/, stark/prover.py,
    parallel/core.py + mesh.py).  The program-identity parts are
    *semantic* (AIR key, shapes) and cannot see function bodies, so a
    code change that alters what a compiled program computes must
    invalidate every entry through the environment half of the key.
    Computed once per process; unreadable sources degrade to their
    names so the fingerprint still exists."""
    global _CODE_FINGERPRINT
    with _LOCK:
        if _CODE_FINGERPRINT is not None:
            return _CODE_FINGERPRINT
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(pkg, "stark", "prover.py"),
             os.path.join(pkg, "parallel", "core.py"),
             os.path.join(pkg, "parallel", "mesh.py")]
    try:
        ops = os.path.join(pkg, "ops")
        paths.extend(os.path.join(ops, n) for n in sorted(os.listdir(ops))
                     if n.endswith(".py"))
    except OSError:
        pass
    h = hashlib.sha256()
    for path in paths:
        h.update(os.path.basename(path).encode())
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            pass
    digest = h.hexdigest()[:16]
    with _LOCK:
        _CODE_FINGERPRINT = digest
    return digest


def _env_parts() -> dict:
    """Environment half of the key: anything that makes a serialized
    executable unloadable or wrong when it changes."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend(),
            "code": _code_fingerprint()}


def entry_key(parts: dict) -> str:
    """Content address of an entry: SHA-256 over the canonical JSON of
    the program-identity parts joined with the environment parts, so a
    jaxlib/backend change can never serve a stale executable."""
    material = {"schema": _SCHEMA, "parts": parts, "env": _env_parts()}
    blob = json.dumps(material, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()


def _entry_path(parts: dict) -> str:
    return os.path.join(cache_dir(), entry_key(parts) + _SUFFIX)


def load(parts: dict):
    """Deserialize-first lookup: the loaded executable for `parts`, or
    None on any miss (absent, corrupt, schema/env drift).  Never raises."""
    if not enabled():
        return None
    path = _entry_path(parts)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        with _LOCK:
            STATS["misses"] += 1
        record_exec_cache_miss()
        return None
    try:
        entry = pickle.loads(blob)
        if entry.get("schema") != _SCHEMA or entry.get("env") != _env_parts():
            raise ValueError("executable cache entry schema/env drift")
        from jax.experimental import serialize_executable

        compiled = serialize_executable.deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"])
    except Exception:
        # corruption / truncation / version drift inside the payload:
        # count the error, drop the entry, and report a clean miss
        with _LOCK:
            STATS["errors"] += 1
            STATS["misses"] += 1
        record_exec_cache_error()
        record_exec_cache_miss()
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    with _LOCK:
        STATS["hits"] += 1
    record_exec_cache_hit()
    try:
        os.utime(path)                      # LRU touch for retention
    except OSError:
        pass
    return compiled


def store(parts: dict, compiled) -> bool:
    """Serialize `compiled` under `parts` (atomic rename), then prune to
    the retention cap.  Returns whether the entry landed; never raises."""
    if not enabled():
        return False
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        # An executable whose compile was served from the XLA persistent
        # compilation cache serializes WITHOUT its jit-compiled symbols
        # (jaxlib CPU: a later deserialize fails with "Symbols not
        # found"), so validate the round-trip before publishing — a
        # poisoned entry must never land on disk.  The rejection counts
        # as an error; a warm XLA cache + empty executable cache
        # therefore stays unpopulated (cold starts are still XLA-cache
        # fast) until a genuinely fresh compile comes along.
        serialize_executable.deserialize_and_load(payload, in_tree,
                                                  out_tree)
        entry = {"schema": _SCHEMA, "parts": parts, "env": _env_parts(),
                 "payload": payload, "in_tree": in_tree,
                 "out_tree": out_tree}
        blob = pickle.dumps(entry)
        directory = cache_dir()
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, _entry_path(parts))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        with _LOCK:
            STATS["errors"] += 1
        record_exec_cache_error()
        return False
    with _LOCK:
        STATS["stores"] += 1
    prune()
    return True


def scan(kind: str | None = None) -> list[dict]:
    """Metadata of every loadable entry for the CURRENT environment
    (optionally filtered by parts["kind"]), oldest first — the hydration
    walk.  Unreadable entries are skipped silently; pass each returned
    parts dict to load() for the executable itself."""
    try:
        names = [n for n in os.listdir(cache_dir()) if n.endswith(_SUFFIX)]
    except OSError:
        return []
    env = None
    out = []
    for name in sorted(names):
        path = os.path.join(cache_dir(), name)
        try:
            with open(path, "rb") as f:
                entry = pickle.loads(f.read())
            if entry.get("schema") != _SCHEMA:
                continue
            if env is None:
                env = _env_parts()
            if entry.get("env") != env:
                continue
            parts = entry["parts"]
            if kind is not None and parts.get("kind") != kind:
                continue
            out.append((os.path.getmtime(path), parts))
        except Exception:
            continue
    return [parts for _, parts in sorted(out, key=lambda p: p[0])]


def prune(max_entries: int | None = None) -> int:
    """Drop least-recently-used entries beyond the cap.  Returns how
    many were removed; never raises."""
    if max_entries is None:
        try:
            max_entries = int(os.environ.get("ETHREX_EXEC_CACHE_MAX",
                                             _DEFAULT_MAX_ENTRIES))
        except ValueError:
            max_entries = _DEFAULT_MAX_ENTRIES
    try:
        directory = cache_dir()
        names = [n for n in os.listdir(directory) if n.endswith(_SUFFIX)]
        if len(names) <= max_entries:
            return 0
        aged = []
        for name in names:
            path = os.path.join(directory, name)
            try:
                aged.append((os.path.getmtime(path), path))
            except OSError:
                continue
        aged.sort()
        removed = 0
        for _, path in aged[:max(0, len(aged) - max_entries)]:
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        return removed
    except Exception:
        return 0


def entry_count() -> int:
    try:
        return sum(1 for n in os.listdir(cache_dir())
                   if n.endswith(_SUFFIX))
    except OSError:
        return 0


def clear_stats() -> None:
    """Reset the in-process counters (test isolation)."""
    with _LOCK:
        for k in STATS:
            STATS[k] = 0


def runtime_stats() -> dict:
    """Point-in-time cache facts for ethrex_perf / ethrex_health / the
    monitor perf panel.  Never raises."""
    with _LOCK:
        out = dict(STATS)
    out["enabled"] = enabled()
    try:
        out["dir"] = cache_dir()
        out["entries"] = entry_count()
    except Exception:
        out["dir"] = None
        out["entries"] = 0
    return out
