"""Deterministic fault-injection harness for the prover pipeline.

A process-wide `FaultPlan` (seeded) carries rules bound to named
injection points; production code calls `inject(site, payload)` at those
points, which is a no-op until a plan is installed.  Same seed + same
call sequence -> same fault schedule, so every failure mode in
`tests/test_prover_chaos.py` replays deterministically.

Injection points wired into the pipeline (see docs/PROVER_RESILIENCE.md
and docs/L1_SETTLEMENT_RESILIENCE.md):

    proto.send              protocol.send_msg, after framing
    proto.recv              protocol.recv_msg / recv_msg_file, after read
    backend.prove           ProverClient around backend.prove
    backend.phase           the stark prover around EVERY device phase
                            (execute / commit / quotient / open / fri /
                            binding legs).  error+delay fire on entry
                            (a crashing or slow kernel — an exception
                            that classifies as oom/device_lost walks
                            the degradation ladder, see
                            prover/runtime_errors); corrupt mangles
                            the phase's host-visible artifacts (a
                            non-finite / out-of-field value ->
                            nan_poison quarantine); drop fires at the
                            phase BOUNDARY, after the checkpoint
                            store — a preemption between phases, the
                            kill-at-every-boundary drill's kill point
    device.lost             fired on entry to every device phase,
                            dedicated to device/slice-loss simulation:
                            an error rule here (the raised message
                            names the site) classifies as device_lost
                            and the failed phase retries down the
                            degradation ladder
    coordinator.store_proof ProofCoordinator before rollup.store_proof
    l1.commit               sequencer around L1Client.commit_batch; fires
                            on BOTH legs — before the call (request lost)
                            and after it returns (tx mined, receipt lost;
                            pair with after=1 to target this leg)
    l1.verify               sequencer around L1Client.verify_batches,
                            same two-leg convention
    l1.get_deposits         sequencer before L1Client.get_deposits
    store.open              PersistentBackend.__init__ before kv_open
    store.put               every durable KV write (direct put/delete and
                            each op applied from a committed batch journal)
    store.flush             two legs per batch commit: the journal bytes
                            (corrupt/torn mangle them = crash mid-journal)
                            and post-journal pre-apply (error/drop = crash
                            after the journal is durable); also fired by
                            backend.flush (see docs/STORAGE_RESILIENCE.md)
    rpc.handle              RpcServer.handle after admission control,
                            before the method body: delay = a slow
                            handler (overload pressure), error/drop = a
                            crashing handler (docs/OVERLOAD.md)
    mempool.add             Mempool.add_transaction at entry: delay = a
                            slow admission path, error/drop = admission
                            crash mid-submit
    coordinator.schedule    ProofCoordinator.assign at entry: delay = a
                            slow scheduling decision, error/drop =
                            scheduler crash (the connection drops, the
                            prover backs off and retries; no lease is
                            granted)
    aggregate.prove         ProofAggregator around the recursion proof;
                            fires on BOTH legs — before the aggregate
                            build (work lost) and after it returns
                            (proof built, settlement leg lost; pair with
                            after=1 to target this leg)
    submit.duplicate        ProofCoordinator on a PROOF_SUBMIT for a
                            batch that already has a stored proof (the
                            losing leg of a hedged assignment): delay = a
                            slow duplicate ack, error/drop = crash while
                            no-op-acking the loser
    net.send                RlpxPeer.send_msg before framing: drop = the
                            frame never leaves, corrupt = wire bytes
                            mangled (the far side fails MAC/decode and
                            the request times out), delay = a congested
                            uplink (docs/P2P_RESILIENCE.md)
    net.recv                RlpxPeer.recv_msg after decode on the reader
                            thread: drop kills the session exactly like
                            a peer disconnect mid-read; corrupt hands
                            the handler a mangled message
    peer.request            RlpxPeer.request at entry (drop/delay/error
                            legs): a request that dies before any bytes
                            move — exercises the retry/backoff path
                            without touching the shared session
    snap.serve              the snap/1 serving legs (account-range /
                            storage-range / byte-codes / trie-nodes
                            responses) before send: corrupt = a
                            byzantine snap server (tampered proofs),
                            drop = the response is lost
    l1.lease                LeadershipManager around every lease
                            acquire/renew CAS; fires on BOTH legs —
                            before the call (request lost) and after it
                            returns (lease held on L1, response lost:
                            the candidate must survive its own orphaned
                            term expiring; pair with after=1 to target
                            this leg).  docs/SEQUENCER_HA.md
    seq.fence               every sequencer-side fence checkpoint
                            (LeadershipManager.check / Sequencer._fence,
                            at the top of commit_next_batch, send_proofs
                            and update_state): error = deposition
                            surfacing exactly at the checkpoint
    forkchoice.apply        ReorgHandler.apply around the canonical
                            rewrite; fires on BOTH legs — before the
                            write group (crash with the old canonical
                            index fully intact) and after it commits
                            (index rewritten, mempool re-injection not
                            yet run: the journaled reorg_pending record
                            replays it on recovery; pair with after=1
                            to target this leg).  docs/CHAIN_RESILIENCE.md
    mempool.reinject        Mempool.reinject at entry: the reorg
                            re-injection path crashing mid-reorg (the
                            pending-reorg journal makes the retry
                            idempotent — see docs/CHAIN_RESILIENCE.md)

Fault kinds:

    drop     raise InjectedFault (a ConnectionError): dropped connection
    delay    time.sleep(seconds): a slow peer / slow TPU proof
    corrupt  mutate the payload in place of the real one
    torn     truncate a bytes payload mid-record: a torn disk write
    error    raise an arbitrary exception: internal crash
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

SITES = frozenset({
    "proto.send",
    "proto.recv",
    "backend.prove",
    "backend.phase",
    "device.lost",
    "coordinator.store_proof",
    "l1.commit",
    "l1.verify",
    "l1.get_deposits",
    "store.open",
    "store.put",
    "store.flush",
    "rpc.handle",
    "mempool.add",
    "coordinator.schedule",
    "aggregate.prove",
    "submit.duplicate",
    "net.send",
    "net.recv",
    "peer.request",
    "snap.serve",
    "l1.lease",
    "seq.fence",
    "forkchoice.apply",
    "mempool.reinject",
})

KINDS = frozenset({"drop", "delay", "corrupt", "torn", "error"})


class InjectedFault(ConnectionError):
    """Raised by drop rules; a ConnectionError so every handler that
    survives real network failures survives injected ones the same way."""


class FaultRule:
    __slots__ = ("site", "kind", "p", "times", "seconds", "exc", "mutate",
                 "after", "fired", "seen")

    def __init__(self, site: str, kind: str, p: float = 1.0,
                 times: int | None = None, seconds: float = 0.0,
                 exc: BaseException | None = None, mutate=None,
                 after: int = 0):
        if site not in SITES:
            raise ValueError(f"unknown injection site {site!r}")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.p = p
        self.times = times      # fire budget; None = unlimited
        self.seconds = seconds  # delay kind
        self.exc = exc          # error kind
        self.mutate = mutate    # corrupt kind: payload -> payload
        self.after = after      # skip the first N matching occasions
        self.fired = 0
        self.seen = 0


def _default_corrupt(payload):
    """Deterministic default mutation: flip wire bytes / clobber a proof's
    backend tag — guaranteed to fail frame decoding or submit validation."""
    if isinstance(payload, (bytes, bytearray)):
        buf = bytearray(payload)
        if buf:
            buf[len(buf) // 2] ^= 0xFF
        return bytes(buf)
    if isinstance(payload, dict):
        out = dict(payload)
        if "backend" in out:
            out["backend"] = "__corrupt__"
        out["__corrupt__"] = True
        return out
    return payload


class FaultPlan:
    """A seeded schedule of fault rules.  Chainable builders:

        FaultPlan(seed=7).error("backend.prove", times=1)
        FaultPlan(3).drop("proto.send", times=3).delay("proto.recv", 0.2)
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self.lock = threading.Lock()
        self.log: list[tuple[str, str]] = []  # (site, kind) fire history

    # -- builders ----------------------------------------------------------
    def add(self, rule: FaultRule) -> "FaultPlan":
        self.rules.append(rule)
        return self

    def drop(self, site: str, p: float = 1.0,
             times: int | None = None, after: int = 0) -> "FaultPlan":
        return self.add(FaultRule(site, "drop", p=p, times=times,
                                  after=after))

    def delay(self, site: str, seconds: float, p: float = 1.0,
              times: int | None = None, after: int = 0) -> "FaultPlan":
        return self.add(FaultRule(site, "delay", p=p, times=times,
                                  seconds=seconds, after=after))

    def corrupt(self, site: str, p: float = 1.0, times: int | None = None,
                mutate=None, after: int = 0) -> "FaultPlan":
        return self.add(FaultRule(site, "corrupt", p=p, times=times,
                                  mutate=mutate, after=after))

    def torn(self, site: str, p: float = 1.0, times: int | None = None,
             after: int = 0) -> "FaultPlan":
        return self.add(FaultRule(site, "torn", p=p, times=times,
                                  after=after))

    def error(self, site: str, exc: BaseException | None = None,
              p: float = 1.0, times: int | None = None,
              after: int = 0) -> "FaultPlan":
        return self.add(FaultRule(site, "error", p=p, times=times, exc=exc,
                                  after=after))

    # -- firing ------------------------------------------------------------
    def fire(self, site: str, payload=None, kinds=None):
        matched: list[FaultRule] = []
        with self.lock:
            for rule in self.rules:
                if rule.site != site:
                    continue
                if kinds is not None and rule.kind not in kinds:
                    continue
                if rule.kind in ("corrupt", "torn") and payload is None:
                    continue  # nothing to mangle at this call point
                if rule.times is not None and rule.fired >= rule.times:
                    continue  # budget exhausted
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue  # occasion deliberately skipped (after=N)
                if rule.p < 1.0 and self.rng.random() >= rule.p:
                    continue
                rule.fired += 1
                self.log.append((site, rule.kind))
                matched.append(rule)
        # act outside the lock: a delay rule must not serialize the
        # coordinator's handler threads behind a sleeping prover
        for rule in matched:
            if rule.kind == "delay":
                time.sleep(rule.seconds)
            elif rule.kind == "corrupt":
                payload = (rule.mutate or _default_corrupt)(payload)
            elif rule.kind == "torn":
                if isinstance(payload, (bytes, bytearray)):
                    payload = bytes(payload)[:max(1, len(payload) // 2)]
            elif rule.kind == "error":
                raise rule.exc if rule.exc is not None else InjectedFault(
                    f"injected error at {site}")
            else:  # drop
                raise InjectedFault(f"injected connection drop at {site}")
        return payload


# -- process-wide plumbing (no-op default) ---------------------------------
_ACTIVE: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def clear() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> FaultPlan | None:
    return _ACTIVE


def inject(site: str, payload=None, kinds=None):
    """The production hook: returns the (possibly mutated) payload; may
    sleep or raise per the active plan.  Free when no plan is installed."""
    plan = _ACTIVE
    if plan is None:
        return payload
    return plan.fire(site, payload, kinds=kinds)


@contextlib.contextmanager
def injected(plan: FaultPlan):
    install(plan)
    try:
        yield plan
    finally:
        clear()
