"""Terminal monitor for a running node (the seat of the reference's
ratatui monitor, /root/reference/tooling/monitor — re-imagined as a
stdlib-curses dashboard over JSON-RPC, so it attaches to ANY node URL
rather than living inside the sequencer process).

`ethrex-tpu monitor [--url ...] [--interval 2]`

Panels: chain head + gas, recent blocks, txpool status, L2 batches and
per-actor sequencer health (when the node exposes the ethrex_* L2
namespace).  One RPC snapshot per refresh; `q` quits.
"""

from __future__ import annotations

import time

from .repl import RpcSession


def snapshot(rpc: RpcSession, blocks: int = 8) -> dict:
    """One monitor refresh's data (pure RPC; drives the render and the
    tests)."""
    out: dict = {"ts": time.time()}
    head = rpc.call("eth_getBlockByNumber", ["latest", False])
    number = int(head["number"], 16)
    out["head"] = {
        "number": number,
        "hash": head["hash"],
        "gas_used": int(head["gasUsed"], 16),
        "gas_limit": int(head["gasLimit"], 16),
        "txs": len(head["transactions"]),
        "base_fee": int(head.get("baseFeePerGas", "0x0"), 16),
        "timestamp": int(head["timestamp"], 16),
    }
    recents = []
    for n in range(max(0, number - blocks + 1), number + 1):
        b = rpc.call("eth_getBlockByNumber", [hex(n), False])
        if b:
            recents.append({"number": n, "txs": len(b["transactions"]),
                            "gas_used": int(b["gasUsed"], 16),
                            "hash": b["hash"]})
    out["recent"] = recents
    try:
        st = rpc.call("txpool_status", [])
        out["txpool"] = {k: int(v, 16) if isinstance(v, str) else int(v)
                         for k, v in st.items()}
    except Exception:
        out["txpool"] = None
    try:
        out["batch"] = rpc.call("ethrex_latestBatch", [])
    except Exception:
        out["batch"] = None
    try:
        out["health"] = rpc.call("ethrex_health", [])
    except Exception:
        out["health"] = None
    try:
        # older nodes don't serve ethrex_ready; skip the role line
        out["ready"] = rpc.call("ethrex_ready", [])
    except Exception:
        out["ready"] = None
    try:
        # older nodes don't serve the trace namespace; skip the panel
        out["traces"] = rpc.call("ethrex_trace_slowest", [5])
    except Exception:
        out["traces"] = None
    try:
        # older nodes don't serve the critical-path RPC; skip the panel
        out["criticalPath"] = rpc.call("ethrex_trace_criticalPath", [])
    except Exception:
        out["criticalPath"] = None
    try:
        # older nodes don't serve the alerts namespace; skip the panel
        out["alerts"] = rpc.call("ethrex_alerts", [])
    except Exception:
        out["alerts"] = None
    try:
        # older nodes don't serve the perf namespace; skip the panel
        out["perf"] = rpc.call("ethrex_perf", [])
    except Exception:
        out["perf"] = None
    try:
        out["peers"] = len(rpc.call("admin_peers", []))
    except Exception:
        out["peers"] = None
    return out


def _ms(v) -> str:
    return f"{v * 1000:.1f}ms" if isinstance(v, (int, float)) else "—"


def _latency_lines(snap: dict, width: int) -> list[str]:
    """Latency panel: per-actor loop stats + slowest traces.  Every field
    access is defensive — an L1-only or older node simply has no panel."""
    lines: list[str] = []
    health = snap.get("health")
    actors = {}
    if isinstance(health, dict) and isinstance(health.get("l2"), dict):
        actors = health["l2"].get("actors") or {}
    rows = []
    for name, st in actors.items():
        loop = st.get("loop") if isinstance(st, dict) else None
        if isinstance(loop, dict) and loop.get("lastSeconds") is not None:
            rows.append(f"   {name:<20} last {_ms(loop['lastSeconds']):>9}"
                        f"  avg {_ms(loop.get('avgSeconds')):>9}"
                        f"  max {_ms(loop.get('maxSeconds')):>9}")
    if rows:
        lines.append("─" * width)
        lines.append(" actor loop latency")
        lines.extend(rows)
    traces = snap.get("traces")
    if isinstance(traces, list) and traces:
        lines.append("─" * width)
        lines.append(" slowest traces")
        for t in traces[:5]:
            if not isinstance(t, dict):
                continue
            lines.append(f"   {str(t.get('name', '?')):<24}"
                         f" {_ms(t.get('seconds')):>9}"
                         f"  spans {t.get('spanCount', '?'):<4}"
                         f" {str(t.get('traceId', ''))[:16]}")
    return lines


def _storage_lines(snap: dict, width: int) -> list[str]:
    """Storage resilience panel: corruption/journal counters and the last
    drain duration.  Defensive like the latency panel — an L1-only or
    older node has no `l2.store` section and simply gets no panel."""
    health = snap.get("health")
    store = {}
    if isinstance(health, dict) and isinstance(health.get("l2"), dict):
        store = health["l2"].get("store") or {}
    if not isinstance(store, dict) or not store:
        return []
    last = store.get("lastShutdownSeconds")
    return [
        "─" * width,
        " storage resilience",
        f"   corrupt {store.get('corruptRecords', '?'):<5}"
        f" rebuilt {store.get('rebuiltRecords', '?'):<5}"
        f" journal replays {store.get('journalReplays', '?'):<5}"
        f" discards {store.get('journalDiscards', '?'):<5}"
        f" last shutdown "
        + (f"{last:.2f}s" if isinstance(last, (int, float)) else "—"),
    ]


def _chain_lines(snap: dict, width: int) -> list[str]:
    """Reorg-resilience panel (ethrex_health `chain` section): reorg
    totals/depths and the mempool re-injection ledger.  Defensive like
    the other panels — an older node without the section gets no
    panel."""
    health = snap.get("health")
    chain = health.get("chain") if isinstance(health, dict) else None
    if not isinstance(chain, dict) or not chain:
        return []
    ev = chain.get("evictions") or {}
    ev_line = "  ".join(f"{k}: {v}" for k, v in sorted(ev.items())) \
        if isinstance(ev, dict) and ev else "none"
    pending = chain.get("pendingJournal")
    return [
        "─" * width,
        " chain reorgs",
        f"   reorgs {chain.get('reorgs', '?'):<6}"
        f" last depth {chain.get('lastDepth', '?'):<4}"
        f" deepest {chain.get('deepestDepth', '?'):<4}"
        f" reinjected {chain.get('reinjected', '?'):<6}"
        f" recoveries {chain.get('recoveries', '?'):<4}"
        + (" PENDING-JOURNAL" if pending else ""),
        f"   evictions  {ev_line}",
    ]


def _chain_path_lines(snap: dict, width: int) -> list[str]:
    """Chain-path X-ray panel (ethrex_health `chainPath` section):
    per-stage depth/utilization, live inclusion tps and the named
    bottleneck.  Defensive like the other panels — an older node
    without the section gets no panel."""
    health = snap.get("health")
    cp = health.get("chainPath") if isinstance(health, dict) else None
    if not isinstance(cp, dict) or not cp or "error" in cp:
        return []
    tps = cp.get("inclusionTps")
    tps_s = f"{tps:.1f}" if isinstance(tps, (int, float)) else "—"
    backlog = cp.get("backlogSeconds")
    backlog_s = f"{backlog:.1f}s" if isinstance(backlog,
                                                (int, float)) else "—"
    stall = cp.get("producerStallSeconds")
    stall_s = f"{stall:.1f}s" if isinstance(stall, (int, float)) else "—"
    lines = [
        "─" * width,
        " chain path",
        f"   inclusion {tps_s} tx/s  backlog {backlog_s}"
        f"  stall {stall_s}"
        f"  bottleneck {cp.get('bottleneck') or 'none'}",
    ]
    stages = cp.get("stages")
    if isinstance(stages, dict) and stages:
        cells = []
        for name in sorted(stages):
            st = stages[name] if isinstance(stages[name], dict) else {}
            rho = st.get("utilization")
            if isinstance(rho, (int, float)):
                rho_s = f"{rho:.2f}"
            else:
                # the health surface spells a saturated-but-undrained
                # queue as the string "inf"
                rho_s = rho if isinstance(rho, str) else "—"
            cells.append(f"{name} d={st.get('depth', '?')}"
                         f" ρ={rho_s}")
        lines.append("   " + "  ".join(cells))
    return lines


def _traffic_lines(snap: dict, width: int) -> list[str]:
    """Traffic panel: RPC request-lifecycle counters and mempool flow
    accounting (ethrex_health `rpc` / `mempoolFlow` sections).
    Defensive like the other panels — an older node without those
    sections simply gets no panel."""
    health = snap.get("health")
    if not isinstance(health, dict):
        return []
    rpc = health.get("rpc")
    flow = health.get("mempoolFlow")
    lines: list[str] = []
    if isinstance(rpc, dict):
        lines.append("─" * width)
        lines.append(" rpc traffic")
        lines.append(
            f"   accepted {rpc.get('accepted', '?'):<8}"
            f" resets {rpc.get('resets', '?'):<6}"
            f" eof {rpc.get('eof', '?'):<6}"
            f" inflight {rpc.get('inflight', '?'):<5}"
            f" slow {rpc.get('slowRequests', '?'):<5}"
            f" backlog {rpc.get('listenBacklog', '—')}")
        lines.append(
            f"   bytes in {rpc.get('requestBytes', '?'):<12}"
            f" out {rpc.get('responseBytes', '?'):<12}"
            f" ws conns {rpc.get('wsConnections', '?'):<5}"
            f" notified {rpc.get('wsNotifications', '?'):<8}"
            f" ws fails {rpc.get('wsSendFailures', '?')}")
        lines.append(
            f"   shed {rpc.get('shed', '?'):<8}"
            f" shed level {rpc.get('shedLevel', '?'):<4}"
            f" ws drops {rpc.get('wsNotificationsDropped', '?'):<6}"
            f" slow-consumer kicks "
            f"{rpc.get('wsSlowConsumerDisconnects', '?')}")
    if isinstance(flow, dict):
        lines.append("─" * width)
        util = flow.get("utilization")
        shown = f"{100 * util:.1f}%" if isinstance(util,
                                                   (int, float)) else "—"
        lines.append(
            f" mempool flow  size {flow.get('size', '?')}"
            f"/{flow.get('capacity', '?')}"
            f" ({shown})  admitted {flow.get('admitted', '?')}")
        rej = flow.get("rejections")
        if isinstance(rej, dict) and rej:
            lines.append("   rejected  " + "  ".join(
                f"{k} {v}" for k, v in sorted(rej.items())))
        ev = flow.get("evictions")
        if isinstance(ev, dict) and ev:
            lines.append("   evicted   " + "  ".join(
                f"{k} {v}" for k, v in sorted(ev.items())))
        top = flow.get("topSenders")
        if isinstance(top, list) and top:
            lines.append("   top senders  " + "  ".join(
                f"{str(s.get('sender', '?'))[:12]}…({s.get('txs', '?')})"
                for s in top[:4] if isinstance(s, dict)))
    return lines


def _aggregation_lines(snap: dict, width: int) -> list[str]:
    """Aggregation + fleet-scheduler panel: settlement amortization state
    (ethrex_health `l2.aggregation`) and the coordinator's scheduler
    policy counters (`l2.prover.scheduler`).  Defensive like the other
    panels — an L1-only or older node simply has no panel."""
    health = snap.get("health")
    l2 = health.get("l2") if isinstance(health, dict) else None
    if not isinstance(l2, dict):
        return []
    agg = l2.get("aggregation")
    prover = l2.get("prover")
    sched = prover.get("scheduler") if isinstance(prover, dict) else None
    lines: list[str] = []
    if isinstance(agg, dict):
        lines.append("─" * width)
        rng = agg.get("lastRange")
        shown = f"{rng[0]}..{rng[1]}" if isinstance(rng, list) \
            and len(rng) == 2 else "—"
        lines.append(
            f" aggregation  {'on' if agg.get('enabled') else 'off'}"
            f"  settled {agg.get('aggregations', '?')} runs"
            f" / {agg.get('batchesAggregated', '?')} batches"
            f"  last {shown}"
            f"  window {agg.get('minBatches', '?')}"
            f"–{agg.get('maxBatches', '?')}")
        if agg.get("lastError"):
            lines.append(f"   last error: {agg['lastError']}")
        if agg.get("recoveredInflight"):
            lines.append(f"   recovered inflight: "
                         f"{agg['recoveredInflight']}")
    if isinstance(sched, dict):
        if not lines:
            lines.append("─" * width)
        deadline = sched.get("hedgeDeadlineSeconds")
        dshown = f"{deadline:.2f}s" if isinstance(deadline,
                                                  (int, float)) else "—"
        lines.append(
            f" scheduler  {sched.get('policy', '?')}"
            f"  queue {sched.get('queueDepth', '?')}"
            f"  hedged {sched.get('hedgedAssignments', '?')}"
            f"  dup submits {sched.get('duplicateSubmits', '?')}"
            f"  live hedges {sched.get('liveHedges', '?')}"
            f"  deadline {dshown}")
        provers = sched.get("provers")
        if isinstance(provers, dict) and provers:
            for pid, st in sorted(provers.items())[:4]:
                if not isinstance(st, dict):
                    continue
                ewma = st.get("ewmaSeconds")
                eshown = f"{ewma:.2f}s" if isinstance(ewma,
                                                      (int, float)) else "—"
                lines.append(f"   {str(pid)[:24]:<24}"
                             f" done {st.get('completed', '?'):<5}"
                             f" ewma {eshown}")
    return lines


def _runtime_lines(snap: dict, width: int) -> list[str]:
    """Prover-runtime resilience panel: checkpoint resume traffic, the
    degradation ladder's retry counters, and which phase each live lease
    is in (ethrex_health `l2.prover.runtime`,
    docs/PROVER_RESILIENCE.md).  Defensive like the other panels — a
    node without the section simply has no panel."""
    health = snap.get("health")
    l2 = health.get("l2") if isinstance(health, dict) else None
    prover = l2.get("prover") if isinstance(l2, dict) else None
    run = prover.get("runtime") if isinstance(prover, dict) else None
    if not isinstance(run, dict):
        return []
    lines = [
        "─" * width,
        f" prover runtime  resumes {run.get('phaseResumes', '?'):<5}"
        f" oom retries {run.get('oomRetries', '?'):<4}"
        f" dev lost {run.get('deviceLostRetries', '?'):<4}"
        f" degraded {run.get('degradations', '?'):<4}"
        f" nan {run.get('nanPoisons', '?'):<3}"
        f" gate shrinks {run.get('memoryGateShrinks', '?')}",
    ]
    ckpt = run.get("checkpoints")
    if isinstance(ckpt, dict):
        lines.append(
            f"   checkpoints {'on' if ckpt.get('enabled') else 'OFF':<4}"
            f" stores {ckpt.get('stores', '?'):<6}"
            f" loads {ckpt.get('loads', '?'):<6}"
            f" discards {ckpt.get('discards', '?'):<5}"
            f" batches {ckpt.get('batches', '?')}")
    last = run.get("lastDegradation")
    if isinstance(last, dict):
        lines.append(f"   last degradation  {last.get('from', '?')}"
                     f" -> {last.get('to', '?')}"
                     f"  ({last.get('reason', '?')})")
    degraded = run.get("degradedProvers")
    if isinstance(degraded, dict) and degraded:
        lines.append("   degraded provers  " + "  ".join(
            f"{str(pid)[:16]}({d.get('from', '?')}->{d.get('to', '?')})"
            for pid, d in sorted(degraded.items())[:4]
            if isinstance(d, dict)))
    phases = run.get("livePhases")
    if isinstance(phases, list) and phases:
        lines.append("   in flight  " + "  ".join(
            f"#{p.get('batch', '?')}/{p.get('proverType', '?')}"
            f" {p.get('phase', '?')}"
            for p in phases[:4] if isinstance(p, dict)))
    return lines


_SNAP_PHASES = {0: "idle", 1: "accounts", 2: "healing", 3: "done"}


def _p2p_lines(snap: dict, width: int) -> list[str]:
    """P2P resilience panel: request timeout/retry/ban counters and the
    snap-sync phase machine (ethrex_health `p2p` section).  Defensive
    like the other panels — an older node without the section simply
    gets no panel."""
    health = snap.get("health")
    p2p = health.get("p2p") if isinstance(health, dict) else None
    if not isinstance(p2p, dict):
        return []
    lines = [
        "─" * width,
        " p2p resilience",
        f"   peers {p2p.get('peers', '?'):<5}"
        f" timeouts {p2p.get('requestTimeouts', '?'):<6}"
        f" retries {p2p.get('requestRetries', '?'):<6}"
        f" bans {p2p.get('peerBans', '?'):<4}"
        f" (active {p2p.get('activeBans', '—')})"
        f" bcast fails {p2p.get('broadcastFailures', '?')}",
    ]
    sync = p2p.get("snap")
    if isinstance(sync, dict):
        phase = _SNAP_PHASES.get(sync.get("phase"), sync.get("phase"))
        lines.append(
            f"   snap {phase:<9}"
            f" ranges {sync.get('rangesSynced', '?'):<7}"
            f" {'PAUSED (partition)' if sync.get('paused') else 'live':<19}"
            f" pauses {sync.get('partitionPauses', '?'):<4}"
            f" ckpt resets {sync.get('progressResets', '?')}")
    return lines


def _alerts_lines(snap: dict, width: int) -> list[str]:
    """Alerts panel: firing SLO rules + most recent transitions.
    Defensive — an L1-only node answers enabled=False (no panel) and an
    older node without ethrex_alerts yields None (no panel)."""
    alerts = snap.get("alerts")
    if not isinstance(alerts, dict) or not alerts.get("enabled"):
        return []
    active = alerts.get("active")
    active = active if isinstance(active, list) else []
    lines = ["─" * width,
             f" alerts  firing {len(active)}"]
    for a in active[:5]:
        if not isinstance(a, dict):
            continue
        value = a.get("value")
        shown = f"{value:.4g}" if isinstance(value, (int, float)) else "—"
        lines.append(f"   [{str(a.get('severity', '?')):<4}]"
                     f" {str(a.get('name', '?')):<32}"
                     f" value {shown:>10}"
                     f" ≥ {a.get('threshold', '?')}")
    recent = alerts.get("recent")
    if isinstance(recent, list) and recent:
        for ev in recent[-3:]:
            if not isinstance(ev, dict):
                continue
            lines.append(f"   {str(ev.get('event', '?')):<9}"
                         f" {str(ev.get('rule', '?')):<32}")
    return lines


def _perf_lines(snap: dict, width: int) -> list[str]:
    """Performance panel: live throughput gauges, the stage-attribution
    tree's top components, and per-kernel roofline utilization.
    Defensive like the other panels — an older node without ethrex_perf
    yields None (no panel); empty profiler/roofline sections shrink the
    panel rather than erroring."""
    perf = snap.get("perf")
    if not isinstance(perf, dict) or not perf.get("enabled"):
        return []
    lines = ["─" * width, " performance"]
    tp = perf.get("throughput")
    if isinstance(tp, dict):
        def fmt(v):
            return f"{v:.3g}" if isinstance(v, (int, float)) else "—"
        lines.append(
            f"   import {fmt(tp.get('l1_import_mgas_per_sec')):>8} Mgas/s"
            f"   prover {fmt(tp.get('prover_trace_cells_per_sec')):>10}"
            f" cells/s   proofs/h {fmt(tp.get('proofs_per_hour')):>8}")
    msh = perf.get("mesh")
    if isinstance(msh, dict):
        ndev = msh.get("devices")
        if isinstance(ndev, (int, float)) and ndev > 1:
            par = msh.get("vmCircuitsParallel")
            par_s = f"{par:.0f}" if isinstance(par, (int, float)) else "—"
            lines.append(f"   mesh   {ndev:>8.0f} devices"
                         f"   vm-circuit slices {par_s:>8}")
    cache = perf.get("executableCache")
    if isinstance(cache, dict) and "error" not in cache:
        def cnt(key):
            v = cache.get(key)
            return f"{v:.0f}" if isinstance(v, (int, float)) else "—"
        state = "on" if cache.get("enabled") else "off"
        lines.append(f"   exec cache [{state}]  hits {cnt('hits'):>6}"
                     f"  misses {cnt('misses'):>6}"
                     f"  errors {cnt('errors'):>4}"
                     f"  entries {cnt('entries'):>5}")
    prof = perf.get("profiler")
    comps = prof.get("components") if isinstance(prof, dict) else None
    if isinstance(comps, dict) and comps:
        ranked = sorted(comps.items(),
                        key=lambda kv: kv[1].get("totalSeconds", 0)
                        if isinstance(kv[1], dict) else 0, reverse=True)
        for name, comp in ranked[:4]:
            if not isinstance(comp, dict):
                continue
            stages = comp.get("stages") or {}
            top = sorted(stages.items(),
                         key=lambda kv: kv[1].get("totalSeconds", 0)
                         if isinstance(kv[1], dict) else 0,
                         reverse=True)[:3]
            parts = "  ".join(
                f"{s} {100 * st.get('share', 0):.0f}%" for s, st in top
                if isinstance(st, dict))
            total = comp.get("totalSeconds")
            shown = f"{total:.2f}s" if isinstance(total, (int, float)) \
                else "—"
            lines.append(f"   {name:<12} {shown:>9}  {parts}")
    roof = perf.get("roofline")
    kernels = roof.get("kernels") if isinstance(roof, dict) else None
    if isinstance(kernels, list) and kernels:
        lines.append("   roofline (utilization vs peak)")
        for k in kernels[:4]:
            if not isinstance(k, dict):
                continue
            util = k.get("utilizationVsPeak")
            shown = f"{100 * util:.1f}%" if isinstance(util,
                                                      (int, float)) else "—"
            flops = k.get("flops")
            fshown = f"{flops:.3g}" if isinstance(flops,
                                                  (int, float)) else "—"
            lines.append(f"   {str(k.get('air', '?')):<20}"
                         f" {str(k.get('kernel', '?')):<10}"
                         f" flops {fshown:>10}  util {shown:>7}")
    # scaling autopsy (PR 18): per-kernel collective accounting and the
    # last prove's per-lane device occupancy — both sections are stubs
    # on L1-only / pre-autopsy nodes and simply add no lines
    coll = perf.get("collectives")
    ckernels = coll.get("kernels") if isinstance(coll, dict) else None
    if isinstance(ckernels, list):
        rows = [k for k in ckernels if isinstance(k, dict)
                and (k.get("collectiveOps") or k.get("copyOps"))]
        if rows:
            lines.append("   collectives (ops / est cross-device bytes)")
            rows.sort(key=lambda k: k.get("crossDeviceBytes") or 0,
                      reverse=True)
            for k in rows[:4]:
                ops = k.get("collectiveOps")
                nbytes = k.get("crossDeviceBytes")
                oshown = f"{ops:.0f}" if isinstance(ops,
                                                    (int, float)) else "—"
                bshown = f"{nbytes:.3g}" if isinstance(
                    nbytes, (int, float)) else "—"
                lines.append(f"   {str(k.get('air', '?')):<20}"
                             f" {str(k.get('kernel', '?')):<10}"
                             f" ops {oshown:>5}  bytes {bshown:>10}"
                             f"  x{k.get('devices', 1)}dev")
    occ = perf.get("occupancy")
    last = occ.get("lastProve") if isinstance(occ, dict) else None
    if isinstance(last, dict):
        frac = last.get("occupancy")
        gap = last.get("idleGapSeconds")
        fshown = f"{100 * frac:.0f}%" if isinstance(frac,
                                                    (int, float)) else "—"
        gshown = f"{gap:.2f}s" if isinstance(gap, (int, float)) else "—"
        lines.append(f"   occupancy {fshown:>5} of"
                     f" {last.get('devices', '—')} devices"
                     f"   idle gaps {gshown}")
        lanes = last.get("lanes")
        if isinstance(lanes, list):
            for lane in lanes[:4]:
                if not isinstance(lane, dict):
                    continue
                busy = lane.get("busySeconds")
                idle = lane.get("idleSeconds")
                bs = f"{busy:.2f}s" if isinstance(busy,
                                                  (int, float)) else "—"
                is_ = f"{idle:.2f}s" if isinstance(idle,
                                                   (int, float)) else "—"
                lines.append(
                    f"     lane {str(lane.get('lane', '?')):<4}"
                    f" ({lane.get('devices', 1)} dev)"
                    f"  busy {bs:>8}  idle {is_:>8}")
    return lines if len(lines) > 2 else []


def _lifecycle_lines(snap: dict, width: int) -> list[str]:
    """Batch lifecycle panel: the slowest merged trace's critical-path
    component attribution (ethrex_trace_criticalPath) and the recently
    settled batches' timeline (ethrex_health `l2.lifecycle`).
    Defensive like the other panels — an L1-only or pre-tracing node
    answers found=False / has no section and simply gets no panel."""
    lines: list[str] = []
    cp = snap.get("criticalPath")
    if isinstance(cp, dict) and cp.get("found") \
            and isinstance(cp.get("components"), dict):
        wall = cp.get("wallSeconds")
        shown = f"{wall:.3f}s" if isinstance(wall, (int, float)) else "—"
        lines.append("─" * width)
        lines.append(
            f" critical path  trace {str(cp.get('traceId', ''))[:16]}"
            f"  wall {shown}"
            + ("  (partial)" if cp.get("partial") else ""))
        comps = [(k, v) for k, v in cp["components"].items()
                 if isinstance(v, (int, float))]
        if comps and isinstance(wall, (int, float)) and wall > 0:
            comps.sort(key=lambda kv: kv[1], reverse=True)
            lines.append("   " + "  ".join(
                f"{k} {100 * v / wall:.0f}%" for k, v in comps[:6]))
    health = snap.get("health")
    l2 = health.get("l2") if isinstance(health, dict) else None
    timeline = l2.get("lifecycle") if isinstance(l2, dict) else None
    if isinstance(timeline, list) and timeline:
        if not lines:
            lines.append("─" * width)
        lines.append(" settled batches (critical path)")
        for entry in timeline[-4:]:
            if not isinstance(entry, dict):
                continue
            comps = entry.get("components")
            parts = ""
            if isinstance(comps, dict):
                top = sorted(((k, v) for k, v in comps.items()
                              if isinstance(v, (int, float))),
                             key=lambda kv: kv[1], reverse=True)[:3]
                parts = "  ".join(f"{k} {v:.3f}s" for k, v in top)
            wall = entry.get("wallSeconds")
            wshown = f"{wall:.3f}s" if isinstance(wall,
                                                  (int, float)) else "—"
            lines.append(f"   batch {str(entry.get('batch', '?')):<6}"
                         f" wall {wshown:>9}  {parts}"
                         + ("  (partial)" if entry.get("partial") else ""))
    return lines


def render_lines(snap: dict, width: int = 100) -> list[str]:
    """Snapshot -> dashboard lines (pure; the curses loop just blits)."""
    h = snap["head"]
    lines = []
    lines.append("ethrex-tpu monitor".center(width, "─"))
    pct = 100.0 * h["gas_used"] / max(h["gas_limit"], 1)
    lines.append(
        f" head #{h['number']}  txs {h['txs']}  gas {h['gas_used']:,}"
        f" ({pct:.1f}%)  base fee {h['base_fee']}"
        + (f"  peers {snap['peers']}" if snap.get("peers") is not None
           else ""))
    lines.append(f" {h['hash']}")
    if isinstance(snap.get("ready"), dict):
        rd = snap["ready"]
        role = rd.get("role") or "n/a"
        line = (f" role {role}  ready {str(rd.get('ready')).lower()}")
        lead = rd.get("leadership")
        if isinstance(lead, dict):
            line += (f"  epoch {lead.get('epoch')}"
                     f"  transitions {lead.get('transitions')}"
                     f"  fenced {lead.get('fenced')}")
            dt = lead.get("promotionDowntimeSeconds")
            if dt is not None:
                line += f"  last promotion {dt:.2f}s"
        lines.append(line)
    lines.append("─" * width)
    lines.append(" recent blocks")
    for b in reversed(snap["recent"]):
        lines.append(f"   #{b['number']:<8} txs {b['txs']:<5} "
                     f"gas {b['gas_used']:<12,} {b['hash'][:18]}…")
    if snap.get("txpool"):
        tp = snap["txpool"]
        lines.append("─" * width)
        lines.append(" txpool  " + "  ".join(f"{k}: {v}"
                                             for k, v in tp.items()))
    if snap.get("batch"):
        lines.append("─" * width)
        b = snap["batch"]
        lines.append(" L2 latest batch  " + "  ".join(
            f"{k}: {v}" for k, v in list(b.items())[:6]))
    if snap.get("health"):
        lines.append("─" * width)
        lines.append(" sequencer health")
        hl = snap["health"]
        items = hl.items() if isinstance(hl, dict) else enumerate(hl)
        for k, v in items:
            # traffic/chain/chain-path sections render in their own
            # panels below
            if k in ("rpc", "mempoolFlow", "p2p", "chain", "chainPath"):
                continue
            lines.append(f"   {k}: {v}")
    lines.extend(_chain_lines(snap, width))
    lines.extend(_chain_path_lines(snap, width))
    lines.extend(_traffic_lines(snap, width))
    lines.extend(_p2p_lines(snap, width))
    lines.extend(_aggregation_lines(snap, width))
    lines.extend(_runtime_lines(snap, width))
    lines.extend(_alerts_lines(snap, width))
    lines.extend(_perf_lines(snap, width))
    lines.extend(_latency_lines(snap, width))
    lines.extend(_lifecycle_lines(snap, width))
    lines.extend(_storage_lines(snap, width))
    lines.append("─" * width)
    lines.append(" q quits · refreshes every interval")
    return lines


def run(url: str, interval: float = 2.0) -> int:
    import curses

    # a short per-call timeout keeps `q`/redraw responsive when the node
    # stalls (snapshot makes ~a dozen serial calls per refresh)
    rpc = RpcSession(url, timeout=3.0)

    def loop(stdscr):
        curses.curs_set(0)
        stdscr.nodelay(True)
        last = 0.0
        lines: list[str] = []
        while True:
            now = time.time()
            if now - last >= interval or not lines:
                try:
                    lines = render_lines(snapshot(rpc),
                                         width=stdscr.getmaxyx()[1] - 1)
                except Exception as e:
                    lines = [f"rpc error: {e}", "retrying…"]
                last = now
                stdscr.erase()
                maxy, maxx = stdscr.getmaxyx()
                for i, line in enumerate(lines[:maxy - 1]):
                    stdscr.addnstr(i, 0, line, maxx - 1)
                stdscr.refresh()
            if stdscr.getch() in (ord("q"), 27):
                return 0
            time.sleep(0.05)

    return curses.wrapper(loop)
