"""Load generator (parity target: the reference's tooling/load_test —
eth-transfer / ERC20-style load against a node's JSON-RPC, measuring
inclusion throughput).

Usage:
    python -m ethrex_tpu.utils.load_test --url http://127.0.0.1:8545 \
        --key <hex> --txs 500 [--mode transfer|sstore]
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.request

from ..crypto import secp256k1
from ..primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

# counter contract: every call increments slot 0 (the "IO" load shape)
SSTORE_RUNTIME = "5f546001015f5500"
SSTORE_INITCODE = "67" + SSTORE_RUNTIME + "5f5260086018f3"


def _rpc(url: str, method: str, *params):
    payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)}).encode()
    req = urllib.request.Request(
        url, data=payload, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.loads(resp.read())
    if "error" in out:
        raise RuntimeError(f"{method}: {out['error']}")
    return out["result"]


def run_load(url: str, secret: int, num_txs: int,
             mode: str = "transfer") -> dict:
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    chain_id = int(_rpc(url, "eth_chainId"), 16)
    nonce = int(_rpc(url, "eth_getTransactionCount",
                     "0x" + sender.hex(), "pending"), 16)
    target = bytes.fromhex("aa" * 20)
    gas_limit = 21000
    data = b""
    if mode == "sstore":
        deploy = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=200_000, to=b"",
            data=bytes.fromhex(SSTORE_INITCODE)).sign(secret)
        _rpc(url, "eth_sendRawTransaction",
             "0x" + deploy.encode_canonical().hex())
        receipt = None
        deadline = time.time() + 30
        while receipt is None and time.time() < deadline:
            receipt = _rpc(url, "eth_getTransactionReceipt",
                           "0x" + deploy.hash.hex())
            time.sleep(0.2)
        if receipt is None:
            raise RuntimeError("deploy was not mined")
        if receipt["status"] != "0x1":
            raise RuntimeError("counter deploy reverted")
        target = bytes.fromhex(receipt["contractAddress"][2:])
        gas_limit = 100_000
        nonce += 1

    start_block = int(_rpc(url, "eth_blockNumber"), 16)
    t0 = time.time()
    for i in range(num_txs):
        tx = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce + i,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas_limit, to=target, value=1 if mode == "transfer"
            else 0, data=data).sign(secret)
        _rpc(url, "eth_sendRawTransaction",
             "0x" + tx.encode_canonical().hex())
    submit_time = time.time() - t0

    # wait for full inclusion (incremental scan: only NEW blocks per poll)
    deadline = time.time() + 120
    included = 0
    gas_used = 0
    scanned = start_block
    while time.time() < deadline:
        head = int(_rpc(url, "eth_blockNumber"), 16)
        for n in range(scanned + 1, head + 1):
            blk = _rpc(url, "eth_getBlockByNumber", hex(n), False)
            included += len(blk["transactions"])
            gas_used += int(blk["gasUsed"], 16)
        scanned = max(scanned, head)
        if included >= num_txs:  # the sstore deploy mines BEFORE start_block
            break
        time.sleep(0.3)
    total = time.time() - t0
    return {
        "mode": mode,
        "txs_submitted": num_txs,
        "txs_included": included,
        "submit_tps": round(num_txs / submit_time, 1),
        "end_to_end_tps": round(included / total, 1),
        "mgas_per_s": round(gas_used / total / 1e6, 3),
        "wall_s": round(total, 2),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ethrex-tpu-load-test")
    parser.add_argument("--url", default="http://127.0.0.1:8545")
    parser.add_argument("--key", default=hex(
        0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8))
    parser.add_argument("--txs", type=int, default=200)
    parser.add_argument("--mode", choices=("transfer", "sstore"),
                        default="transfer")
    args = parser.parse_args(argv)
    result = run_load(args.url, int(args.key, 16), args.txs, args.mode)
    import sys

    sys.stdout.write(json.dumps(result, indent=2) + "\n")


if __name__ == "__main__":
    main()
