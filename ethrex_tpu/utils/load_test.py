"""Thin shim over the load harness (ethrex_tpu/perf/loadgen.py).

The closed-loop load generator that lived here (parity target: the
reference's tooling/load_test — eth-transfer / ERC20-style load against
a node's JSON-RPC, measuring inclusion throughput) moved into the perf
package, where the OPEN-loop harness now also lives.  This file keeps
the historical entry point working:

    python -m ethrex_tpu.utils.load_test --url http://127.0.0.1:8545 \
        --key <hex> --txs 500 [--mode transfer|sstore]

Everything public is re-exported so `from ethrex_tpu.utils.load_test
import run_load` users (tests, scripts) see the same API as before the
move.  New work should import `ethrex_tpu.perf.loadgen` directly — it
adds the open-loop Harness (fixed/Poisson schedules, missed-send
accounting, p50/p95/p99 per offered rate) this closed-loop path cannot
measure.
"""

from __future__ import annotations

from ..perf.loadgen import (  # noqa: F401
    SSTORE_INITCODE,
    SSTORE_RUNTIME,
    _rpc,
    main,
    run_load,
)

if __name__ == "__main__":
    main()
