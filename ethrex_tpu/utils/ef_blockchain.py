"""EF BlockchainTest-format runner: import fixture chains block by block
through full validation, expecting declared exceptions, then check the
final head + post state.

Wire-format parity with the reference's blockchain suite
(/root/reference/tooling/ef_tests/blockchain/{types.rs,test_runner.rs}):
a fixture file maps test name -> unit with `genesisBlockHeader`,
`genesisRLP`, `blocks` ([{rlp} | {rlp, expectException}]), `pre`,
`lastblockhash`, `postState` | `postStateHash`, `network`.  Public EF
archives (ethereum/tests, execution-spec-tests) plug in unchanged; the
vendored fixtures under tests/fixtures/ef_blockchain are self-generated
smoke units (the archives themselves are not redistributable inside this
image).

Flow mirrors test_runner.rs run_ef_test: decode genesisRLP and demand it
matches the computed genesis header; seed the store from `pre`; import
each block, requiring declared-invalid blocks to fail and valid ones to
succeed; require the last valid hash to equal `lastblockhash`; then
audit `postState` account by account (or `postStateHash` against the
head's state root).
"""

from __future__ import annotations

import json

from ..blockchain.blockchain import Blockchain, InvalidBlock
from ..primitives.block import Block
from ..primitives.genesis import Genesis
from ..primitives.rlp import RLPError
from ..storage.store import Store

# network name -> time-activation config entries (post-merge only, like
# the reference runner which skips pre-Merge networks)
_FORK_TIMES = {
    "Paris": {},
    "Merge": {},
    "Shanghai": {"shanghaiTime": 0},
    "Cancun": {"shanghaiTime": 0, "cancunTime": 0},
    "Prague": {"shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0},
    "Osaka": {"shanghaiTime": 0, "cancunTime": 0, "pragueTime": 0,
              "osakaTime": 0},
}


class UnsupportedNetwork(Exception):
    pass


class FixtureFailure(Exception):
    pass


def _hx(v) -> str:
    return v if isinstance(v, str) else hex(v)


def genesis_from_unit(unit: dict) -> Genesis:
    hdr = unit["genesisBlockHeader"]
    network = unit.get("network", "")
    times = _FORK_TIMES.get(network)
    if times is None:
        raise UnsupportedNetwork(network)
    config = {"chainId": 1, "terminalTotalDifficulty": 0, **times}
    alloc = {}
    for addr, acct in unit.get("pre", {}).items():
        alloc[addr] = {
            "balance": _hx(acct.get("balance", "0x0")),
            "nonce": _hx(acct.get("nonce", "0x0")),
            "code": acct.get("code", "0x"),
            "storage": acct.get("storage", {}),
        }
    gjson = {
        "config": config,
        "alloc": alloc,
        "coinbase": hdr.get("coinbase", "0x" + "00" * 20),
        "difficulty": _hx(hdr.get("difficulty", "0x0")),
        "extraData": hdr.get("extraData", "0x"),
        "gasLimit": _hx(hdr.get("gasLimit", "0x0")),
        "nonce": _hx(hdr.get("nonce", "0x0")),
        "mixHash": hdr.get("mixHash", "0x" + "00" * 32),
        "timestamp": _hx(hdr.get("timestamp", "0x0")),
    }
    if "baseFeePerGas" in hdr:
        gjson["baseFeePerGas"] = _hx(hdr["baseFeePerGas"])
    if "excessBlobGas" in hdr:
        gjson["excessBlobGas"] = _hx(hdr["excessBlobGas"])
    if "blobGasUsed" in hdr:
        gjson["blobGasUsed"] = _hx(hdr["blobGasUsed"])
    return Genesis.from_json(gjson)


def run_unit(name: str, unit: dict) -> None:
    """Run one BlockchainTest unit; raises FixtureFailure on divergence."""
    genesis = genesis_from_unit(unit)
    store = Store()
    gh = store.init_genesis(genesis)
    genesis_rlp = bytes.fromhex(unit["genesisRLP"].removeprefix("0x"))
    try:
        decoded = Block.decode(genesis_rlp)
    except (RLPError, ValueError) as e:
        raise FixtureFailure(f"{name}: genesisRLP undecodable: {e}")
    if decoded.header.hash != gh.hash:
        raise FixtureFailure(
            f"{name}: computed genesis {gh.hash.hex()} != fixture "
            f"{decoded.header.hash.hex()}")

    chain = Blockchain(store, genesis.config)
    last_valid = gh.hash
    for i, bwr in enumerate(unit.get("blocks", [])):
        expect_fail = bool(bwr.get("expectException"))
        raw = bytes.fromhex(bwr["rlp"].removeprefix("0x"))
        try:
            block = Block.decode(raw)
        except (RLPError, ValueError):
            if expect_fail:
                continue
            raise FixtureFailure(f"{name}: block {i} undecodable")
        try:
            chain.add_block(block)
        except InvalidBlock as e:
            if expect_fail:
                continue
            raise FixtureFailure(f"{name}: block {i} rejected: {e}")
        if expect_fail:
            raise FixtureFailure(
                f"{name}: block {i} accepted but fixture expects "
                f"{bwr['expectException']}")
        last_valid = block.hash

    want_last = bytes.fromhex(unit["lastblockhash"].removeprefix("0x"))
    if last_valid != want_last:
        raise FixtureFailure(
            f"{name}: last valid {last_valid.hex()} != "
            f"{want_last.hex()}")

    head = store.get_header(last_valid)
    post = unit.get("postState")
    post_hash = unit.get("postStateHash")
    if post_hash is not None:
        want = bytes.fromhex(post_hash.removeprefix("0x"))
        if head.state_root != want:
            raise FixtureFailure(f"{name}: post state hash mismatch")
    if post is not None:
        root = head.state_root
        for addr_hex, want_acct in post.items():
            addr = bytes.fromhex(addr_hex.removeprefix("0x").zfill(40))
            st = store.account_state(root, addr)
            if st is None:
                raise FixtureFailure(
                    f"{name}: post account {addr_hex} absent")
            if st.nonce != int(_hx(want_acct.get("nonce", "0x0")), 16):
                raise FixtureFailure(f"{name}: {addr_hex} nonce mismatch")
            if st.balance != int(_hx(want_acct.get("balance", "0x0")), 16):
                raise FixtureFailure(
                    f"{name}: {addr_hex} balance mismatch")
            for slot_hex, want_v in want_acct.get("storage", {}).items():
                got = store.storage_at(root, addr, int(slot_hex, 16))
                if got != int(want_v, 16):
                    raise FixtureFailure(
                        f"{name}: {addr_hex}[{slot_hex}] storage "
                        f"mismatch: {hex(got)} != {want_v}")


def run_fixture_file(path: str, skip=()) -> dict:
    """Run every unit in a fixture file.  Returns
    {"passed": n, "skipped": n, "failures": [...]}."""
    with open(path) as f:
        units = json.load(f)
    passed = 0
    skipped = 0
    failures = []
    for name, unit in units.items():
        if any(s in name for s in skip):
            skipped += 1
            continue
        try:
            run_unit(name, unit)
            passed += 1
        except UnsupportedNetwork:
            skipped += 1
        except FixtureFailure as e:
            failures.append(str(e))
    return {"passed": passed, "skipped": skipped, "failures": failures}
