"""Persistent XLA compile cache keyed by a host-CPU fingerprint, plus
JAX runtime telemetry.

XLA's AOT results embed machine features; loading a cache written on a
different host SIGSEGVs/SIGILLs (observed as "Compile machine features ...
doesn't match" warnings before a crash).  Both the test suite and bench.py
route through this helper so they share one correctly-scoped cache.

Telemetry: jax.monitoring listeners count backend compiles (with
durations) and persistent-cache hits/misses; runtime_telemetry() adds
per-device memory stats and live-array counts for the flight recorder,
and update_metrics_gauges() mirrors them into the Metrics registry.
Every telemetry path is exception-guarded — a missing jax.monitoring
API or a backend without memory_stats() degrades to empty data, never
an error in the prover path.
"""

from __future__ import annotations

import hashlib
import os
import platform
import threading

_LOCK = threading.Lock()
_MONITORING_INSTALLED = False
_DEFAULT_PREFIX = "/tmp/ethrex_tpu_jax_cache"
STATS = {"compiles": 0, "compile_seconds": 0.0,
         "cache_hits": 0, "cache_misses": 0}


def cache_dir(prefix: str = _DEFAULT_PREFIX) -> str:
    """Host-fingerprinted cache directory.  The XLA compile cache's /tmp
    default is overridable via ETHREX_JAX_CACHE_DIR (used verbatim, no
    fingerprint suffix — the operator owns its scoping); callers with
    their own prefix (the executable store, utils/exec_cache) keep it."""
    if prefix == _DEFAULT_PREFIX:
        env = os.environ.get("ETHREX_JAX_CACHE_DIR")
        if env:
            return env
    try:
        with open("/proc/cpuinfo") as f:
            cpu = [ln for ln in f if ln.startswith("flags")][0]
    except (OSError, IndexError):
        cpu = platform.processor() or "unknown"
    fp = hashlib.sha256(cpu.encode()).hexdigest()[:12]
    return f"{prefix}_{fp}"


def _on_duration(event: str, duration: float, **kw) -> None:
    try:
        if "backend_compile" in event:
            with _LOCK:
                STATS["compiles"] += 1
                STATS["compile_seconds"] += duration
            from .metrics import record_jax_compile

            record_jax_compile(duration)
    except Exception:
        pass


def _on_event(event: str, **kw) -> None:
    try:
        if "cache_hit" in event:
            with _LOCK:
                STATS["cache_hits"] += 1
            from .metrics import record_jax_cache_event

            record_jax_cache_event(True)
        elif "cache_miss" in event:
            with _LOCK:
                STATS["cache_misses"] += 1
            from .metrics import record_jax_cache_event

            record_jax_cache_event(False)
    except Exception:
        pass


def install_monitoring() -> bool:
    """Attach jax.monitoring listeners (idempotent, never raises).
    Returns whether listeners are installed."""
    global _MONITORING_INSTALLED
    with _LOCK:
        if _MONITORING_INSTALLED:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
            _MONITORING_INSTALLED = True
        except Exception:
            return False
    return True


def enable_persistent_cache(min_compile_secs: float = 1.0) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    install_monitoring()


def runtime_telemetry() -> dict:
    """JAX runtime facts for the flight recorder.  Never raises."""
    with _LOCK:
        out = {"cache": dict(STATS), "cacheDir": cache_dir(),
               "monitoring": _MONITORING_INSTALLED}
    try:
        import jax

        out["backend"] = jax.default_backend()
        devices = []
        for d in jax.local_devices():
            entry = {"id": d.id, "platform": d.platform,
                     "kind": getattr(d, "device_kind", None)}
            try:
                ms = d.memory_stats()
                entry["memory"] = (
                    {k: ms[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                        "bytes_limit") if k in ms}
                    if ms else None)
            except Exception:
                entry["memory"] = None
            devices.append(entry)
        out["devices"] = devices
        try:
            out["liveArrays"] = len(jax.live_arrays())
        except Exception:
            out["liveArrays"] = None
    except Exception as exc:
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def update_metrics_gauges() -> None:
    """Mirror device memory / live-array stats into gauges.  Called
    after each backend prove; never raises."""
    try:
        from .metrics import (record_jax_device_memory,
                              record_jax_live_arrays)

        tel = runtime_telemetry()
        in_use = peak = 0.0
        seen = False
        for d in tel.get("devices", ()):
            mem = d.get("memory")
            if not mem:
                continue
            seen = True
            in_use += mem.get("bytes_in_use", 0) or 0
            peak += mem.get("peak_bytes_in_use", 0) or 0
        if seen:
            record_jax_device_memory(in_use, peak)
        if tel.get("liveArrays") is not None:
            record_jax_live_arrays(tel["liveArrays"])
    except Exception:
        pass
