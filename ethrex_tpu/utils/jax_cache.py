"""Persistent XLA compile cache keyed by a host-CPU fingerprint.

XLA's AOT results embed machine features; loading a cache written on a
different host SIGSEGVs/SIGILLs (observed as "Compile machine features ...
doesn't match" warnings before a crash).  Both the test suite and bench.py
route through this helper so they share one correctly-scoped cache.
"""

from __future__ import annotations

import hashlib
import platform


def cache_dir(prefix: str = "/tmp/ethrex_tpu_jax_cache") -> str:
    try:
        with open("/proc/cpuinfo") as f:
            cpu = [ln for ln in f if ln.startswith("flags")][0]
    except (OSError, IndexError):
        cpu = platform.processor() or "unknown"
    fp = hashlib.sha256(cpu.encode()).hexdigest()[:12]
    return f"{prefix}_{fp}"


def enable_persistent_cache(min_compile_secs: float = 1.0) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
