"""Node: wires store + blockchain + mempool + RPC + dev block producer
(parity with the reference's cmd/ethrex init flow, initializers.rs init_l1,
minus p2p which arrives with the sync rounds)."""

from __future__ import annotations

import logging
import threading
import time

from .blockchain.blockchain import Blockchain
from .blockchain.fork_choice import ReorgHandler
from .blockchain.mempool import Mempool, MempoolError
from .blockchain.payload import build_payload, create_payload_header
from .evm.executor import InvalidTransaction
from .primitives.genesis import Genesis
from .storage.store import Store

log = logging.getLogger("ethrex_tpu.node")


class Node:
    def __init__(self, genesis: Genesis, coinbase: bytes = b"\x00" * 20,
                 store: Store | None = None):
        self.store = store if store is not None else Store()
        self.genesis_header = self.store.init_genesis(genesis)
        self.config = genesis.config
        self.chain = Blockchain(self.store, self.config)
        self.chain.regenerate_head_state()
        self.mempool = Mempool()
        self.coinbase = coinbase
        self._producer_thread = None
        self._stop = threading.Event()
        self.lock = threading.RLock()
        # new-canonical-block observers (websocket subscriptions etc.);
        # `on_new_block` stays the single p2p gossip hook
        self.block_listeners: list = []
        # the reorg seam: every head move (producer, p2p import, engine
        # forkchoiceUpdated) goes through one handler so the mempool is
        # re-injected/evicted/revalidated and subscribers notified on
        # every reorg (docs/CHAIN_RESILIENCE.md).  Shares the node lock
        # so engine-driven reorgs serialize with block production.
        self.reorg_handler = ReorgHandler(self.store, self.mempool,
                                          lock=self.lock)
        self.reorg_listeners = self.reorg_handler.listeners
        # crash-only restart: if a previous process died between the
        # canonical rewrite and the mempool settlement, replay the
        # journaled re-injection now (no transaction silently lost)
        self.reorg_handler.recover_pending()
        # observability surfaces attached by start_telemetry / the CLI
        self.telemetry = None
        self.alerts = None

    def start_telemetry(self, interval: float = 1.0, alerts=None):
        """Start the metrics sampler (the node owns its lifecycle; the
        shutdown drain's `telemetry` step stops it with a final sample).
        When an AlertEngine is supplied its evaluate() runs after every
        sampler tick."""
        from .utils import timeseries

        engine = timeseries.ENGINE
        if alerts is not None:
            self.alerts = alerts
            engine.add_evaluator(alerts.evaluate)
        self.telemetry = engine.start(interval)
        return engine

    # ------------------------------------------------------------------
    def head_state_root(self) -> bytes:
        return self.store.head_header().state_root

    def pending_nonce(self, address: bytes) -> int:
        acct = self.store.account_state(self.head_state_root(), address)
        nonce = acct.nonce if acct else 0
        queue = self.mempool.by_sender.get(address, {})
        while nonce in queue:
            nonce += 1
        return nonce

    def submit_transaction(self, tx) -> bytes:
        from .primitives.transaction import TYPE_PRIVILEGED

        if tx.tx_type == TYPE_PRIVILEGED:
            # only the L1 watcher may create privileged txs — an unsigned
            # 0x7E tx over RPC would be an arbitrary unauthenticated mint
            raise InvalidTransaction(
                "privileged transactions cannot be submitted directly")
        sender = tx.sender()
        if sender is None:
            raise InvalidTransaction("invalid signature")
        if tx.chain_id is not None and tx.chain_id != self.config.chain_id:
            # counted against the pool's flow accounting even though the
            # check runs above it: RPC rejection reasons share one ledger
            from .utils.metrics import record_mempool_rejection

            self.mempool.rejections["wrong_chain_id"] = \
                self.mempool.rejections.get("wrong_chain_id", 0) + 1
            record_mempool_rejection("wrong_chain_id")
            err = InvalidTransaction("wrong chain id")
            err.reason = "wrong_chain_id"
            raise err
        root = self.head_state_root()
        acct = self.store.account_state(root, sender)
        nonce = acct.nonce if acct else 0
        balance = acct.balance if acct else 0
        base_fee = self.store.head_header().base_fee_per_gas or 0
        try:
            return self.mempool.add_transaction(tx, nonce, balance, base_fee)
        except MempoolError as e:
            # carry the typed rejection reason across the exception
            # translation: the RPC layer forwards it as structured error
            # data so load generators can account rejections per reason
            # instead of folding them into a generic error rate
            err = InvalidTransaction(str(e))
            err.reason = e.reason
            raise err

    # ------------------------------------------------------------------
    def produce_block(self, timestamp: int | None = None,
                      forced_txs: list | None = None):
        """Block production: forced (privileged) txs + mempool -> payload ->
        import.  `forced_txs` are included ahead of the mempool (the L2
        deposit path)."""
        with self.lock:
            parent = self.store.head_header()
            ts = timestamp or max(int(time.time()), parent.timestamp + 1)
            header = create_payload_header(
                parent, self.config, timestamp=ts, coinbase=self.coinbase)
            base_fee = header.base_fee_per_gas or 0
            root = parent.state_root

            def get_nonce(sender):
                acct = self.store.account_state(root, sender)
                return acct.nonce if acct else 0

            from .perf.chain_path import CHAIN_PATH
            from .perf.profiler import record_stage

            t_drain = time.monotonic()
            txs = list(forced_txs or []) \
                + self.mempool.pending(base_fee, get_nonce)
            t0 = time.monotonic()
            # chain-path X-ray: the mempool drain is the first producer
            # stage span; the candidate set marks sampled lifecycles
            record_stage("payload", "drain", t0 - t_drain)
            CHAIN_PATH.txs_selected([tx.hash for tx in txs])
            result = build_payload(self.chain, parent, header, txs, [],
                                   mempool=self.mempool)
            # block records + fork choice commit as one journaled unit on
            # persistent stores (write groups nest; see write_group)
            with self.store.write_group():
                self.chain.add_block(result.block)
                self.reorg_handler.apply(result.block.hash)
            for tx in result.block.body.transactions:
                self.mempool.remove_transaction(tx.hash, reason="included")
            from .utils.metrics import record_block

            build_s = time.monotonic() - t0
            record_block(result.block, build_s)
            CHAIN_PATH.block_produced(
                result.block.header.number,
                [tx.hash for tx in result.block.body.transactions],
                build_s)
            block = result.block
        # gossip OUTSIDE the node lock: a stalled peer's socket must never
        # freeze block production or RPC
        self._gossip(block)
        return block

    def _gossip(self, block):
        hook = getattr(self, "on_new_block", None)
        if hook is not None:
            try:
                hook(block)
            except Exception:  # noqa: BLE001 — gossip must not fail callers
                pass
        for listener in list(self.block_listeners):
            try:
                listener(block)
            except Exception:  # noqa: BLE001 — observers must not fail us
                pass

    def import_block(self, block) -> bool:
        """Serialized p2p import: validates + stores + fork-chooses under
        the node lock, then relays.  Returns True if the block was new."""
        from .blockchain.blockchain import InvalidBlock

        with self.lock:
            if self.store.get_header(block.hash) is not None:
                return False
            with self.store.write_group():
                self.chain.add_block(block)  # raises InvalidBlock
                self.reorg_handler.apply(block.hash)
        self._gossip(block)  # transitive relay (terminates: peers that
        return True          # already have it import nothing and don't relay

    def pending_txs(self, parent) -> list:
        """Mempool transactions executable on top of `parent`, filtered by
        the NEXT block's base fee (shared by the payload build and the
        prewarmer so both see the same tx set)."""
        from .blockchain.blockchain import next_base_fee
        from .primitives.genesis import Fork

        fork = self.config.fork_at(parent.number + 1, parent.timestamp + 1)
        base_fee = next_base_fee(parent) if fork >= Fork.LONDON else 0

        def get_nonce(sender):
            acct = self.store.account_state(parent.state_root, sender)
            return acct.nonce if acct else 0

        return self.mempool.pending(base_fee or 0, get_nonce)

    def start_dev_producer(self, block_time: float = 1.0,
                           prewarm: bool = True):
        from .blockchain.prewarm import prewarm_transactions

        def loop():
            while not self._stop.wait(block_time):
                try:
                    if len(self.mempool):
                        self.produce_block()
                        if prewarm:
                            # AFTER producing: the genuinely idle window
                            # before the next tick warms trie/code/backend
                            # caches for the NEXT build without delaying
                            # this one (blockchain/prewarm.py)
                            parent = self.store.head_header()
                            t_warm = time.monotonic()
                            prewarm_transactions(
                                self.chain, parent,
                                self.pending_txs(parent),
                                deadline=t_warm + block_time / 2)
                            from .perf.profiler import record_stage

                            record_stage("payload", "prewarm",
                                         time.monotonic() - t_warm)
                except Exception as e:  # noqa: BLE001 — keep producing
                    log.warning("block production failed: %s", e)

        self._producer_thread = threading.Thread(target=loop, daemon=True)
        self._producer_thread.start()

    def stop(self, timeout: float = 30.0) -> bool:
        """Returns True when all writers are stopped (safe to close the
        backend); False if the producer is still alive after the timeout.
        Idempotent: a second call (HA demotion racing the shutdown
        drain) is a no-op returning the first call's verdict."""
        self._stop.set()
        thread = self._producer_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=timeout)
            if thread.is_alive():
                log.warning("block producer did not stop within %.1fs",
                            timeout)
                return False
            self._producer_thread = None
        return True
