"""Performance observability: continuous profiling, roofline accounting
and the bench suite (docs/PERFORMANCE.md).

Three pillars, built on the PR-3 tracing spans and the PR-5
timeseries/SLO substrate:

- ``profiler``: a process-wide stage-attribution tree unifying the
  block_until_ready-bounded prover stage spans with the L1 import legs
  (execute / merkleize / store_write), the EVM split (sig_recovery /
  opcode_loop) and the sorted trie commit, plus opt-in ``jax.profiler``
  trace capture around a prove.
- ``roofline``: XLA cost-model FLOPs/bytes per compiled STARK phase
  program combined with measured wall-clock into achieved-FLOP/s and
  utilization-vs-peak estimates.
- ``bench_suite``: the measurement logic behind ``bench.py`` (the repo
  root keeps a thin CLI shim), including the forced-CPU fallback for
  hosts whose TPU plugin is present but dead, and the append-only
  ``bench_history.jsonl`` trajectory.
- ``hlo_introspect`` / ``occupancy`` (PR 18): the scaling autopsy —
  per-kernel collective/reshard accounting straight from the compiled
  programs' HLO plus device-occupancy timelines for the parallel
  prover, consumed by the bench's ``explain_scaling`` diff
  (docs/PERFORMANCE.md "Reading the scaling autopsy").

Everything here is telemetry and sits behind the never-raise contract:
a failing hook degrades to missing numbers, never a failed prove or
import.
"""

from . import profiler, roofline  # noqa: F401
