"""Roofline accounting for the compiled STARK phase programs.

XLA's cost model (``compiled.cost_analysis()``) reports static FLOPs and
bytes-accessed per executable; the prover records each phase's
block_until_ready-bounded wall-clock.  Together they give per-kernel
achieved-FLOP/s, arithmetic intensity (FLOPs/byte) and a
utilization-vs-peak estimate — the same view a training stack's
continuous profiler provides, applied to proving kernels.

Caveats (documented in docs/PERFORMANCE.md and carried in the report):

- cost_analysis shape varies by jaxlib version (list of dicts, a bare
  dict, None on some backends) and may omit either key; every form is
  tolerated and missing numbers surface as null, never an error.
- XLA counts u32 modular-arithmetic ops as "flops"; utilization against
  a floating-point peak is a consistent *relative* signal across runs
  on one backend, not an absolute MXU occupancy.
- The peak is an estimate: override with ``ETHREX_PEAK_FLOPS`` (flop/s)
  for a calibrated roof; otherwise a per-backend default is used.

Every entry point is exception-guarded: a failing cost_analysis can
never fail a prove (acceptance criterion).
"""

from __future__ import annotations

import os
import threading

from ..utils.metrics import record_kernel_flops

# rough per-backend peak-FLOP/s defaults (override: ETHREX_PEAK_FLOPS).
# tpu: one modern TPU chip's dense-unit order of magnitude; cpu: cores x
# ~8 u32 SIMD lanes x ~2GHz — both deliberately coarse anchors.
_PEAK_DEFAULTS = {"tpu": 275.0e12, "gpu": 80.0e12}


def _cpu_peak() -> float:
    return float(os.cpu_count() or 1) * 8.0 * 2.0e9


def peak_flops_estimate(backend: str | None = None) -> float | None:
    env = os.environ.get("ETHREX_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            return None
    if backend == "cpu":
        return _cpu_peak()
    return _PEAK_DEFAULTS.get(backend)


def _cost_field(entry, key: str, attr: str):
    """One cost/memory number from a dict entry (``entry[key]``) or an
    attribute-style entry (``entry.attr``, newer jaxlib properties);
    None when absent, non-numeric, or negative."""
    if isinstance(entry, dict):
        v = entry.get(key)
    else:
        try:
            v = getattr(entry, attr, None)
            if callable(v):
                v = v()
        except Exception:  # raising properties/accessors -> absent field
            return None
    if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0:
        return None
    return float(v)


def _parse_cost(cost) -> dict:
    """Normalize any cost_analysis() shape to {'flops', 'bytes'} with
    float-or-None values.  jax 0.4.x returns a list with one dict per
    computation; older/newer versions return a bare dict; newer jaxlib
    AOT surfaces hand back property objects (``.flops`` /
    ``.bytes_accessed``); CPU backends may return None or omit keys.
    Every form degrades to partial rows, never an error."""
    out = {"flops": None, "bytes": None}
    if cost is None:
        return out
    entries = cost if isinstance(cost, (list, tuple)) else [cost]
    flops = 0.0
    nbytes = 0.0
    saw_flops = saw_bytes = False
    for entry in entries:
        if entry is None or isinstance(entry, (int, float, str)):
            continue
        f = _cost_field(entry, "flops", "flops")
        if f is not None:
            flops += f
            saw_flops = True
        b = _cost_field(entry, "bytes accessed", "bytes_accessed")
        if b is not None:
            nbytes += b
            saw_bytes = True
    if saw_flops:
        out["flops"] = flops
    if saw_bytes:
        out["bytes"] = nbytes
    return out


class RooflineRegistry:
    """Per (air, kernel) static cost + measured wall accumulator."""

    MAX_KEYS = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict[tuple[str, str], dict] = {}

    def _cell(self, air: str, kernel: str) -> dict | None:
        key = (str(air), str(kernel))
        cell = self._kernels.get(key)
        if cell is None:
            if len(self._kernels) >= self.MAX_KEYS:
                return None
            cell = self._kernels[key] = {
                "flops": None, "bytes": None, "devices": 1,
                "wallCount": 0, "wallTotal": 0.0, "wallLast": None,
                "wallMin": None,
            }
        return cell

    def record_cost(self, air: str, kernel: str, cost,
                    devices: int = 1) -> None:
        """`devices`: mesh size the executable was compiled for — the
        report carries it so a sharded kernel's static FLOPs are read
        against the right number of chips (utilization stays relative
        to the single-chip peak estimate, documented in
        docs/PERFORMANCE.md)."""
        parsed = _parse_cost(cost)
        with self._lock:
            cell = self._cell(air, kernel)
            if cell is None:
                return
            if parsed["flops"] is not None:
                cell["flops"] = parsed["flops"]
            if parsed["bytes"] is not None:
                cell["bytes"] = parsed["bytes"]
            cell["devices"] = max(1, int(devices))

    def record_wall(self, air: str, kernel: str, seconds: float) -> None:
        sec = float(seconds)
        with self._lock:
            cell = self._cell(air, kernel)
            if cell is None:
                return
            cell["wallCount"] += 1
            cell["wallTotal"] += sec
            cell["wallLast"] = sec
            if cell["wallMin"] is None or sec < cell["wallMin"]:
                cell["wallMin"] = sec
            flops = cell["flops"]
        # export gauges outside the lock; achieved-FLOP/s uses the LAST
        # wall (the gauge is "current", the report also carries min/avg)
        if flops and sec > 0:
            peak = peak_flops_estimate()
            achieved = flops / sec
            util = achieved / peak if peak else None
            record_kernel_flops(air, kernel, flops, achieved, util)

    def report(self) -> dict:
        peak = peak_flops_estimate()
        with self._lock:
            cells = {k: dict(v) for k, v in self._kernels.items()}
        kernels = []
        for (air, kernel), c in sorted(cells.items()):
            flops, nbytes = c["flops"], c["bytes"]
            last = c["wallLast"]
            achieved = flops / last if flops and last else None
            kernels.append({
                "air": air, "kernel": kernel,
                "devices": c.get("devices", 1),
                "flops": flops, "bytes": nbytes,
                "intensityFlopsPerByte":
                    round(flops / nbytes, 3) if flops and nbytes else None,
                "wallCount": c["wallCount"],
                "wallLastSeconds":
                    round(last, 6) if last is not None else None,
                "wallMinSeconds":
                    round(c["wallMin"], 6)
                    if c["wallMin"] is not None else None,
                "wallAvgSeconds":
                    round(c["wallTotal"] / c["wallCount"], 6)
                    if c["wallCount"] else None,
                "achievedFlopsPerSec":
                    round(achieved, 1) if achieved else None,
                "utilizationVsPeak":
                    round(achieved / peak, 6)
                    if achieved and peak else None,
            })
        return {"peakFlopsEstimate": peak,
                "peakSource": "env" if os.environ.get("ETHREX_PEAK_FLOPS")
                else "default",
                "kernels": kernels}

    def reset(self) -> None:
        with self._lock:
            self._kernels.clear()


ROOFLINE = RooflineRegistry()


def record_cost(air: str, kernel: str, cost, devices: int = 1) -> None:
    """Never-raise hook: fold one compiled program's cost_analysis()
    output (any shape, including None) into the registry; `devices` is
    the mesh size the executable was compiled for (1 = unsharded)."""
    try:
        ROOFLINE.record_cost(air, kernel, cost, devices=devices)
    except Exception:
        pass


def record_wall(air: str, kernel: str, seconds: float) -> None:
    """Never-raise hook: fold one measured phase wall-clock in and
    refresh the prover_kernel_* gauges."""
    try:
        ROOFLINE.record_wall(air, kernel, seconds)
    except Exception:
        pass
