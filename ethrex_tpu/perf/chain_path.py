"""Chain-path X-ray: explicit measured stages over the transaction
pipeline (docs/OBSERVABILITY.md "Chain-path telemetry").

The serving ceiling moved from the RPC front door into the chain path
(ROADMAP item 3), but nothing could name *which* stage pays the wall.
This module instruments ingest→admit→select→execute→include→batch→
prove→settle the SEDA way (Welsh et al.): every pipeline stage gets an
explicit queue with measured arrival/service rates, so overload shows
up as a number on one stage instead of a mystery p99.

Three layers:

- ``StageQueue``: a never-raise per-stage queue instrument — depth
  gauge, arrival/departure/drop counters, dwell histogram, windowed
  arrival/service rates, utilization rho = arrival/service, and a
  Little's-law cross-check (L = lambda * W) that flags when the
  observed depth disagrees with what the measured rates predict
  (instrumentation bug or non-stationary load).
- ``ChainPath``: the process-global wiring.  Three queues — "admission"
  (mempool add -> removal), "producer" (block build service), and
  "batching" (block sealed -> batch committed) — plus a sampled per-tx
  lifecycle ring (admitted/selected/included/batched/proved/settled
  timestamps, joined to the PR-15 batch trace by trace ID) and a live
  ``block_inclusion_tps`` gauge over a sliding window.
- ``explain_chain_path()``: the PR-18 ``explain_scaling`` pattern
  applied to the pipeline — a pure function over the queue stats that
  names the dominant bottleneck stage with a human-readable verdict.

Everything here is telemetry on hot paths: every public entry point is
exception-guarded and must never raise into admission or block
production.  Failures count into ``CHAIN_PATH.errors`` and degrade to
missing numbers.

Knobs (documented in docs/OBSERVABILITY.md):

- ``ETHREX_CHAINPATH_SAMPLE``: lifecycle sampling stride — record every
  N-th admitted transaction (default 16; 1 = every tx, 0 disables).
- ``ETHREX_CHAINPATH_RING``: lifecycle ring capacity (default 512).
- ``ETHREX_CHAINPATH_WINDOW``: sliding window in seconds for rates,
  utilization and the inclusion-tps gauge (default 30).
"""

from __future__ import annotations

import collections
import logging
import math
import os
import threading
import time

from ..utils.metrics import METRICS, _observe_safe

log = logging.getLogger(__name__)

# lifecycle events in pipeline order; each hop histogram is the dwell
# between two adjacent events that both fired for a sampled tx
LIFECYCLE_EVENTS = ("admitted", "selected", "included",
                    "batched", "proved", "settled")

QUEUE_STAGES = ("admission", "producer", "batching")

DEFAULT_SAMPLE = 16
DEFAULT_RING = 512
DEFAULT_WINDOW = 30.0

# an idle/stalled service rate would make backlog-drain estimates
# infinite; clamp so alert thresholds stay comparable
MAX_BACKLOG_SECONDS = 1e6


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# metric helpers (help-text lint: tests/test_tooling.py)
# ---------------------------------------------------------------------------


def record_stage_depth(stage: str, depth: float):
    try:
        METRICS.set_labeled(
            "chain_path_stage_depth", {"stage": stage}, float(depth),
            "Current queue depth of a chain-path pipeline stage "
            "(admission = txs resident in the mempool, batching = "
            "blocks sealed but not yet committed to a batch)")
    except Exception:
        pass


def record_stage_event(stage: str, event: str, n: float = 1.0):
    try:
        METRICS.inc_labeled(
            "chain_path_stage_events_total",
            {"stage": stage, "event": event}, float(n),
            "Arrival/departure/drop events per chain-path stage queue "
            "(drops are departures that left the pipeline: evictions, "
            "prunes, reorg re-injections)")
    except Exception:
        pass


def observe_stage_dwell(stage: str, seconds: float):
    _observe_safe("chain_path_stage_dwell_seconds", seconds,
                  {"stage": stage},
                  "Time a unit of work spent inside one chain-path "
                  "stage queue, from arrival to departure")


def observe_lifecycle_hop(hop: str, seconds: float):
    _observe_safe("chain_path_hop_seconds", seconds, {"hop": hop},
                  "Dwell between adjacent lifecycle events of a sampled "
                  "transaction (e.g. admitted_to_selected); the per-hop "
                  "decomposition of end-to-end inclusion latency")


def record_inclusion_tps(tps: float):
    try:
        METRICS.set(
            "block_inclusion_tps", float(tps),
            "Transactions included in sealed blocks per second over the "
            "chain-path sliding window — the live gauge behind the "
            "bench --measure-inclusion history gate")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# StageQueue
# ---------------------------------------------------------------------------


class StageQueue:
    """One explicitly measured pipeline stage (SEDA style).

    Mutators (``arrive``/``depart``) are thread-safe and never raise;
    ``stats()`` returns a JSON-able dict with windowed arrival/service
    rates, utilization rho and a Little's-law cross-check.  The depth
    integral is maintained on every mutation so the *time-averaged*
    depth (Little's observed L) is exact, not sampled.
    """

    def __init__(self, name: str, window: float | None = None,
                 clock=time.monotonic):
        self.name = name
        self.window = float(window if window is not None
                            else _env_float("ETHREX_CHAINPATH_WINDOW",
                                            DEFAULT_WINDOW))
        self._clock = clock
        self.lock = threading.Lock()
        self.depth = 0
        self.arrivals = 0
        self.departures = 0
        self.drops = 0
        self.errors = 0
        self._dwell_sum = 0.0
        self._dwell_count = 0
        # windowed event logs: (ts, n) arrivals; (ts, n, dwell) services
        self._arrived: collections.deque = collections.deque()
        self._served: collections.deque = collections.deque()
        now = self._clock()
        self._born = now
        self._last_change = now
        self._depth_area = 0.0  # integral of depth dt since _born

    # -- internals (caller holds self.lock) -----------------------------
    def _advance(self, now: float) -> None:
        if now > self._last_change:
            self._depth_area += self.depth * (now - self._last_change)
            self._last_change = now
        horizon = now - self.window
        while self._arrived and self._arrived[0][0] < horizon:
            self._arrived.popleft()
        while self._served and self._served[0][0] < horizon:
            self._served.popleft()

    # -- mutators --------------------------------------------------------
    def arrive(self, n: int = 1) -> None:
        try:
            n = int(n)
            if n <= 0:
                return
            with self.lock:
                now = self._clock()
                self._advance(now)
                self.depth += n
                self.arrivals += n
                self._arrived.append((now, n))
                depth = self.depth
            record_stage_depth(self.name, depth)
            record_stage_event(self.name, "arrival", n)
        except Exception:
            self.errors += 1

    def depart(self, dwell: float | None = None, n: int = 1,
               dropped: bool = False) -> None:
        try:
            n = int(n)
            if n <= 0:
                return
            with self.lock:
                now = self._clock()
                self._advance(now)
                self.depth = max(0, self.depth - n)
                if dropped:
                    self.drops += n
                else:
                    self.departures += n
                d = None
                if dwell is not None:
                    d = max(0.0, float(dwell))
                    self._dwell_sum += d * n
                    self._dwell_count += n
                self._served.append((now, n, d))
                depth = self.depth
            record_stage_depth(self.name, depth)
            record_stage_event(self.name, "drop" if dropped
                               else "departure", n)
            if d is not None:
                observe_stage_dwell(self.name, d)
        except Exception:
            self.errors += 1

    # -- readers ---------------------------------------------------------
    def stats(self) -> dict:
        try:
            with self.lock:
                now = self._clock()
                self._advance(now)
                span = min(self.window, max(now - self._born, 1e-9))
                arr = sum(n for _, n in self._arrived)
                srv = sum(n for _, n, _ in self._served)
                dwells = [(n, d) for _, n, d in self._served
                          if d is not None]
                arrival_rate = arr / span
                service_rate = srv / span
                w_n = sum(n for n, _ in dwells)
                mean_dwell = (sum(n * d for n, d in dwells) / w_n
                              if w_n else None)
                rho = None
                if service_rate > 0:
                    rho = arrival_rate / service_rate
                elif arrival_rate > 0:
                    rho = float("inf")
                # Little's law: L = lambda * W.  Compare the predicted
                # depth with the observed time-averaged depth; a ratio
                # far from 1 under stationary load means the
                # instrumentation (or the stationarity assumption) is
                # lying.
                elapsed = max(now - self._born, 1e-9)
                observed_l = self._depth_area / elapsed
                predicted_l = (arrival_rate * mean_dwell
                               if mean_dwell is not None else None)
                ratio = None
                if predicted_l is not None and observed_l > 1e-9:
                    ratio = predicted_l / observed_l
                return {
                    "depth": self.depth,
                    "arrivals": self.arrivals,
                    "departures": self.departures,
                    "drops": self.drops,
                    "errors": self.errors,
                    "windowSeconds": round(span, 3),
                    "arrivalRate": round(arrival_rate, 4),
                    "serviceRate": round(service_rate, 4),
                    "utilization": (round(rho, 4)
                                    if rho not in (None, float("inf"))
                                    else rho),
                    "meanDwellSeconds": (round(mean_dwell, 6)
                                         if mean_dwell is not None
                                         else None),
                    "busySeconds": round(
                        sum(n * d for n, d in dwells), 6),
                    "littleLaw": {
                        "observedDepth": round(observed_l, 4),
                        "predictedDepth": (round(predicted_l, 4)
                                           if predicted_l is not None
                                           else None),
                        "ratio": (round(ratio, 4)
                                  if ratio is not None else None),
                    },
                }
        except Exception:
            self.errors += 1
            return {"depth": self.depth, "error": "stats failed"}


# ---------------------------------------------------------------------------
# per-tx lifecycle ring
# ---------------------------------------------------------------------------


class ChainPath:
    """Process-global chain-path instrument (singleton ``CHAIN_PATH``).

    Wiring points (each a never-raise call):

    - ``tx_admitted``      mempool.add_transaction success
    - ``tx_removed``       mempool.remove_transaction (any reason)
    - ``txs_selected``     Node.produce_block candidate set
    - ``block_produced``   Node.produce_block after the block is sealed
    - ``blocks_batched``   Sequencer.commit_next_batch success
    - ``batch_proved``     ProofCoordinator proof accepted
    - ``batches_settled``  record_verified_batch call sites
    """

    def __init__(self, sample: int | None = None,
                 ring: int | None = None,
                 window: float | None = None,
                 clock=time.monotonic):
        self._clock = clock
        self.configure(sample=sample, ring=ring, window=window)

    def configure(self, sample: int | None = None,
                  ring: int | None = None,
                  window: float | None = None) -> None:
        """(Re)initialize — tests use this to force sample=1 and small
        rings; production reads the chain-path env knobs (module
        docstring)."""
        self.sample = int(sample if sample is not None
                          else _env_int("ETHREX_CHAINPATH_SAMPLE",
                                        DEFAULT_SAMPLE))
        self.ring = max(1, int(ring if ring is not None
                               else _env_int("ETHREX_CHAINPATH_RING",
                                             DEFAULT_RING)))
        self.window = float(window if window is not None
                            else _env_float("ETHREX_CHAINPATH_WINDOW",
                                            DEFAULT_WINDOW))
        self.lock = threading.Lock()
        self.queues = {name: StageQueue(name, window=self.window,
                                        clock=self._clock)
                       for name in QUEUE_STAGES}
        self.errors = 0
        self._seen = 0          # admissions observed (sampling stride)
        self._sampled = 0       # lifecycle records created
        self._records: collections.OrderedDict = collections.OrderedDict()
        self._by_block: dict[int, list[str]] = {}
        self._block_sealed_at: collections.OrderedDict = \
            collections.OrderedDict()
        self._by_batch: dict[int, list[str]] = {}
        self._included_events: collections.deque = collections.deque()
        self.blocks_produced = 0
        self.txs_included = 0
        self.last_block_at: float | None = None

    def reset(self) -> None:
        self.configure()

    # -- internals (caller holds self.lock) -----------------------------
    def _evict(self) -> None:
        while len(self._records) > self.ring:
            h, rec = self._records.popitem(last=False)
            blk = rec.get("block")
            if blk in self._by_block:
                self._by_block[blk] = [x for x in self._by_block[blk]
                                       if x != h]
                if not self._by_block[blk]:
                    del self._by_block[blk]
            bat = rec.get("batch")
            if bat in self._by_batch:
                self._by_batch[bat] = [x for x in self._by_batch[bat]
                                       if x != h]
                if not self._by_batch[bat]:
                    del self._by_batch[bat]

    def _mark(self, rec: dict, event: str, now: float) -> None:
        ts = rec["ts"]
        if event in ts:
            return
        ts[event] = now
        idx = LIFECYCLE_EVENTS.index(event)
        for prev in reversed(LIFECYCLE_EVENTS[:idx]):
            if prev in ts:
                observe_lifecycle_hop(f"{prev}_to_{event}",
                                      max(0.0, now - ts[prev]))
                break

    def _prune_included(self, now: float) -> None:
        horizon = now - self.window
        while self._included_events and \
                self._included_events[0][0] < horizon:
            self._included_events.popleft()

    # -- wiring hooks ----------------------------------------------------
    def tx_admitted(self, tx_hash) -> None:
        try:
            self.queues["admission"].arrive()
            if self.sample <= 0:
                return
            with self.lock:
                self._seen += 1
                if (self._seen - 1) % self.sample:
                    return
                now = self._clock()
                h = getattr(tx_hash, "hex", lambda: str(tx_hash))()
                self._records[h] = {"tx": h, "ts": {"admitted": now},
                                    "block": None, "batch": None,
                                    "traceId": None}
                self._sampled += 1
                self._evict()
        except Exception:
            self.errors += 1

    def tx_removed(self, tx_hash, reason: str,
                   dwell: float | None = None) -> None:
        """Mempool removal = admission-stage departure.  Only
        ``included`` leaves through the pipeline; every other reason
        (evicted/pruned/reorg/...) is a drop."""
        try:
            self.queues["admission"].depart(
                dwell=dwell, dropped=(reason != "included"))
        except Exception:
            self.errors += 1

    def txs_selected(self, tx_hashes) -> None:
        try:
            with self.lock:
                now = self._clock()
                for th in tx_hashes:
                    h = getattr(th, "hex", lambda t=th: str(t))()
                    rec = self._records.get(h)
                    if rec is not None:
                        self._mark(rec, "selected", now)
        except Exception:
            self.errors += 1

    def block_produced(self, block_number: int, tx_hashes,
                       build_seconds: float) -> None:
        try:
            q = self.queues["producer"]
            q.arrive()
            q.depart(dwell=build_seconds)
            self.queues["batching"].arrive()
            hashes = [getattr(th, "hex", lambda t=th: str(t))()
                      for th in tx_hashes]
            with self.lock:
                now = self._clock()
                self.blocks_produced += 1
                self.txs_included += len(hashes)
                self.last_block_at = now
                self._block_sealed_at[int(block_number)] = now
                while len(self._block_sealed_at) > 4096:
                    self._block_sealed_at.popitem(last=False)
                self._included_events.append((now, len(hashes)))
                self._prune_included(now)
                marked = []
                for h in hashes:
                    rec = self._records.get(h)
                    if rec is not None:
                        self._mark(rec, "included", now)
                        rec["block"] = int(block_number)
                        marked.append(h)
                if marked:
                    self._by_block[int(block_number)] = marked
                tps = self._inclusion_tps_locked(now)
            record_inclusion_tps(tps)
        except Exception:
            self.errors += 1

    def blocks_batched(self, batch_number: int, first_block: int,
                       last_block: int,
                       trace_id: str | None = None) -> None:
        try:
            with self.lock:
                now = self._clock()
                marked = []
                n_blocks = 0
                dwells = []
                for blk in range(int(first_block), int(last_block) + 1):
                    sealed = self._block_sealed_at.pop(blk, None)
                    if sealed is not None:
                        n_blocks += 1
                        dwells.append(max(0.0, now - sealed))
                    for h in self._by_block.get(blk, ()):
                        rec = self._records.get(h)
                        if rec is None:
                            continue
                        self._mark(rec, "batched", now)
                        rec["batch"] = int(batch_number)
                        rec["traceId"] = trace_id or rec["traceId"]
                        marked.append(h)
                if marked:
                    self._by_batch[int(batch_number)] = marked
            q = self.queues["batching"]
            for d in dwells:
                q.depart(dwell=d)
            # blocks sealed before this instrument booted (or >4096
            # ago) still leave the queue, just without a dwell
            extra = (int(last_block) - int(first_block) + 1) - n_blocks
            if extra > 0 and q.depth > 0:
                q.depart(n=min(extra, q.depth))
        except Exception:
            self.errors += 1

    def batch_proved(self, batch_number: int) -> None:
        try:
            with self.lock:
                now = self._clock()
                for h in self._by_batch.get(int(batch_number), ()):
                    rec = self._records.get(h)
                    if rec is not None:
                        self._mark(rec, "proved", now)
        except Exception:
            self.errors += 1

    def batches_settled(self, first_batch: int,
                        last_batch: int | None = None) -> None:
        try:
            last = int(last_batch if last_batch is not None
                       else first_batch)
            with self.lock:
                now = self._clock()
                for b in range(int(first_batch), last + 1):
                    for h in self._by_batch.get(b, ()):
                        rec = self._records.get(h)
                        if rec is not None:
                            self._mark(rec, "settled", now)
        except Exception:
            self.errors += 1

    # -- readers ---------------------------------------------------------
    def _inclusion_tps_locked(self, now: float) -> float:
        self._prune_included(now)
        if not self._included_events:
            return 0.0
        span = min(self.window, max(now - self._included_events[0][0],
                                    1e-9))
        # a single block gives a degenerate span; floor at 1s so the
        # gauge reads "txs in the last second" rather than infinity
        span = max(span, 1.0)
        return sum(n for _, n in self._included_events) / span

    def inclusion_tps(self) -> float:
        try:
            with self.lock:
                return self._inclusion_tps_locked(self._clock())
        except Exception:
            self.errors += 1
            return 0.0

    def backlog_seconds(self) -> float | None:
        """Estimated seconds to drain the admission backlog at the
        current inclusion (service) rate.  None when the backlog is
        empty or this node has never produced a block (L1-only follower
        — the signal must stay armed-but-silent there)."""
        try:
            st = self.queues["admission"].stats()
            depth = st.get("depth") or 0
            if depth <= 0:
                return None
            if self.blocks_produced <= 0:
                return None
            rate = st.get("serviceRate") or 0.0
            if rate <= 0:
                return float(MAX_BACKLOG_SECONDS)
            return min(float(MAX_BACKLOG_SECONDS), depth / rate)
        except Exception:
            self.errors += 1
            return None

    def producer_stall_seconds(self) -> float | None:
        """Seconds since the last sealed block while admitted work is
        waiting.  None while the mempool is empty or before the first
        block (idle is not a stall)."""
        try:
            if self.last_block_at is None:
                return None
            if (self.queues["admission"].depth or 0) <= 0:
                return None
            return max(0.0, self._clock() - self.last_block_at)
        except Exception:
            self.errors += 1
            return None

    def lifecycles_json(self, limit: int = 16) -> list[dict]:
        try:
            with self.lock:
                recs = list(self._records.values())[-int(limit):]
            out = []
            for rec in recs:
                ts = rec["ts"]
                hops = {}
                prev = None
                for ev in LIFECYCLE_EVENTS:
                    if ev not in ts:
                        continue
                    if prev is not None:
                        hops[f"{prev}_to_{ev}"] = round(
                            ts[ev] - ts[prev], 6)
                    prev = ev
                out.append({
                    "tx": rec["tx"],
                    "block": rec["block"],
                    "batch": rec["batch"],
                    "traceId": rec["traceId"],
                    "events": {ev: round(t, 6)
                               for ev, t in ts.items()},
                    "hops": hops,
                })
            return out
        except Exception:
            self.errors += 1
            return []

    def to_json(self) -> dict:
        try:
            with self.lock:
                sampled = self._sampled
                seen = self._seen
            return _jsonable({
                "enabled": True,
                "stages": {n: q.stats()
                           for n, q in self.queues.items()},
                "inclusionTps": round(self.inclusion_tps(), 4),
                "blocksProduced": self.blocks_produced,
                "txsIncluded": self.txs_included,
                "lifecycle": {
                    "sampleEvery": self.sample,
                    "ringCapacity": self.ring,
                    "seen": seen,
                    "sampled": sampled,
                    "records": self.lifecycles_json(),
                },
                "explain": explain_chain_path(self),
                "errors": self.errors,
            })
        except Exception as exc:
            self.errors += 1
            return {"enabled": False,
                    "error": f"{type(exc).__name__}: {exc}"}

    def health_json(self) -> dict:
        """Compact ethrex_health section.  On an L1-only node (never
        produced a block) this degrades to zeros with bottleneck null —
        present, truthful, never an error."""
        try:
            exp = explain_chain_path(self)
            return _jsonable({
                "bottleneck": exp.get("bottleneck"),
                "inclusionTps": round(self.inclusion_tps(), 4),
                "backlogSeconds": self.backlog_seconds(),
                "producerStallSeconds": self.producer_stall_seconds(),
                "blocksProduced": self.blocks_produced,
                "stages": {
                    n: {"depth": q.stats().get("depth"),
                        "utilization": q.stats().get("utilization")}
                    for n, q in self.queues.items()},
            })
        except Exception as exc:
            return {"error": f"{type(exc).__name__}: {exc}"}


# ---------------------------------------------------------------------------
# explain
# ---------------------------------------------------------------------------


def _jsonable(obj):
    """Replace non-finite floats with the string "inf" so stage stats
    survive strict JSON parsers on the RPC/health surfaces (Python's
    json.dumps would happily emit bare ``Infinity``)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return "inf"
    return obj


def explain_chain_path(path: ChainPath | None = None) -> dict:
    """Name the dominant chain-path bottleneck from the queue stats —
    the ``explain_scaling`` pattern applied to the tx pipeline.

    Pure over ``StageQueue.stats()`` output; returns a stub verdict
    (bottleneck null) when no stage shows pressure, so the RPC degrades
    gracefully on idle or L1-only nodes."""
    p = path if path is not None else CHAIN_PATH
    try:
        stages = {n: q.stats() for n, q in p.queues.items()}
        bits: list[str] = []
        pressures: dict[str, float] = {}

        adm = stages.get("admission", {})
        rho = adm.get("utilization")
        adm_p = 0.0
        if adm.get("depth"):
            if rho == float("inf"):
                adm_p = float(adm["depth"])
                bits.append(
                    "admission: %d txs queued with no inclusion in the "
                    "window — txs arrive but nothing drains them"
                    % adm["depth"])
            elif rho is not None and rho > 1.0:
                adm_p = float(rho)
                bits.append(
                    "admission: arrivals %.1f/s vs inclusion %.1f/s "
                    "(rho %.2f), backlog %d txs"
                    % (adm.get("arrivalRate") or 0.0,
                       adm.get("serviceRate") or 0.0, rho,
                       adm["depth"]))
        pressures["admission"] = adm_p

        prod = stages.get("producer", {})
        busy = (prod.get("busySeconds") or 0.0) / max(
            prod.get("windowSeconds") or 1.0, 1e-9)
        prod_p = busy if busy > 0.8 else 0.0
        if prod_p:
            bits.append(
                "producer: block building consumed %.0f%% of the "
                "window (%.3fs mean build) — the producer itself is "
                "the wall" % (busy * 100.0,
                              prod.get("meanDwellSeconds") or 0.0))
        stall = p.producer_stall_seconds()
        if stall is not None and stall > 2.0 * max(
                prod.get("meanDwellSeconds") or 0.0, 1.0):
            prod_p = max(prod_p, 1.0 + stall)
            bits.append(
                "producer: no block for %.1fs while %d txs wait — "
                "producer stalled" % (stall, adm.get("depth") or 0))
        pressures["producer"] = round(prod_p, 4)

        bat = stages.get("batching", {})
        brho = bat.get("utilization")
        bat_p = 0.0
        # only score batching once a batch has actually been committed:
        # on an L1-only node sealed blocks arrive here but nothing ever
        # drains them, and that is normal, not a bottleneck
        if bat.get("depth") and bat.get("departures"):
            if brho == float("inf"):
                bat_p = float(bat["depth"])
                bits.append(
                    "batching: %d sealed blocks await commitment with "
                    "no batch committed in the window" % bat["depth"])
            elif brho is not None and brho > 1.0:
                bat_p = float(brho)
                bits.append(
                    "batching: blocks sealed at %.2f/s vs committed "
                    "%.2f/s (rho %.2f)"
                    % (bat.get("arrivalRate") or 0.0,
                       bat.get("serviceRate") or 0.0, brho))
        pressures["batching"] = bat_p

        bottleneck = None
        if any(v > 0 for v in pressures.values()):
            bottleneck = max(pressures, key=lambda k: pressures[k])
        if bottleneck is None:
            bits.append("no stage under pressure — the chain path is "
                        "keeping up with offered load")
        return {
            "bottleneck": bottleneck,
            "verdict": "; ".join(bits),
            "pressures": {k: (v if v != float("inf") else "inf")
                          for k, v in pressures.items()},
            "inclusionTps": round(p.inclusion_tps(), 4),
            "stages": _jsonable(stages),
        }
    except Exception as exc:
        return {"bottleneck": None,
                "error": f"{type(exc).__name__}: {exc}"}


CHAIN_PATH = ChainPath()
