"""Device-occupancy timelines for the parallel prover.

PR 11 taught `_run_proof_jobs` (prover/tpu_backend.py) to carve the
mesh into slices and run VM proof jobs on them concurrently; the
critical-path tracer then attributes the *host* wall.  What neither
answers is ROADMAP item 1c's question: how busy were the devices?  A
prove that keeps one slice saturated while three sit idle scales
exactly as badly as the sweep shows, and nothing said so.

This module turns per-lane busy intervals (one lane per mesh slice,
weighted by the slice's device count) into:

- an **occupancy fraction** per prove: busy-device-seconds divided by
  devices × wall.  The serial fallback on an N-device mesh is bounded
  by 1/N — the floor the `prover_occupancy_floor` alert watches.
- per-lane busy/idle seconds where busy + idle == wall by
  construction (tested to 5% against the measured wall).
- **idle gaps**: spans of the wall where *no* lane was busy — the
  between-phase bubbles cross-batch pipelining (item 1c) would fill.

Interval math collapses overlaps before summing, so re-entrant spans
on one lane never double-count.  All public entry points follow the
telemetry never-raise contract.
"""

from __future__ import annotations

import threading

from ..utils import metrics as metrics_mod


def merge_intervals(intervals) -> list:
    """Collapse a list of (start, end) pairs into sorted, disjoint
    intervals.  Malformed entries (end <= start, non-numeric) are
    dropped rather than raised on."""
    clean = []
    for pair in intervals or ():
        try:
            t0, t1 = float(pair[0]), float(pair[1])
        except (TypeError, ValueError, IndexError):
            continue
        if t1 > t0:
            clean.append((t0, t1))
    clean.sort()
    merged: list = []
    for t0, t1 in clean:
        if merged and t0 <= merged[-1][1]:
            if t1 > merged[-1][1]:
                merged[-1] = (merged[-1][0], t1)
        else:
            merged.append((t0, t1))
    return merged


def busy_seconds(intervals) -> float:
    return sum(t1 - t0 for t0, t1 in merge_intervals(intervals))


def compute(lanes, devices=None, window=None) -> dict:
    """Occupancy report for one prove.

    ``lanes`` maps a lane id to either a list of (start, end)
    intervals or ``{"intervals": [...], "devices": k}`` (k = device
    count of that mesh slice, default 1).  ``devices`` is the total
    mesh size (defaults to the summed lane weights); ``window``
    optionally pins (start, end) — otherwise the wall spans min start
    to max end across all lanes.
    """
    norm = {}
    for lane, spec in (lanes or {}).items():
        if isinstance(spec, dict):
            ivs = merge_intervals(spec.get("intervals"))
            weight = max(1, int(spec.get("devices", 1) or 1))
        else:
            ivs = merge_intervals(spec)
            weight = 1
        norm[str(lane)] = (ivs, weight)

    all_points = [t for ivs, _ in norm.values() for iv in ivs for t in iv]
    if window is not None:
        start, end = float(window[0]), float(window[1])
    elif all_points:
        start, end = min(all_points), max(all_points)
    else:
        start = end = 0.0
    wall = max(0.0, end - start)

    total_devices = devices
    if not isinstance(total_devices, int) or total_devices < 1:
        total_devices = sum(w for _, w in norm.values()) or 1

    lane_rows = []
    busy_device_s = 0.0
    union: list = []
    for lane in sorted(norm):
        ivs, weight = norm[lane]
        clipped = merge_intervals(
            [(max(t0, start), min(t1, end)) for t0, t1 in ivs])
        busy = sum(t1 - t0 for t0, t1 in clipped)
        busy_device_s += busy * weight
        union.extend(clipped)
        lane_rows.append({
            "lane": lane,
            "devices": weight,
            "busySeconds": busy,
            "idleSeconds": max(0.0, wall - busy),
            "intervals": len(clipped),
        })

    covered = merge_intervals(union)
    covered_s = sum(t1 - t0 for t0, t1 in covered)
    idle_gap_s = max(0.0, wall - covered_s)
    denom = total_devices * wall
    occupancy = (busy_device_s / denom) if denom > 0 else 0.0
    return {
        "wallSeconds": wall,
        "devices": total_devices,
        "lanes": lane_rows,
        "busyDeviceSeconds": busy_device_s,
        "occupancy": min(1.0, occupancy),
        "idleGapSeconds": idle_gap_s,
        "idleGapCount": max(0, len(covered) - 1) if wall > 0 else 0,
    }


class OccupancyRegistry:
    """Recent per-prove occupancy reports, bounded; report() is the
    ethrex_perf / flight-recorder payload and degrades to a stub on
    nodes that never proved (L1-only)."""

    MAX_RECORDS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._records: list = []

    def record(self, report: dict) -> None:
        with self._lock:
            self._records.append(report)
            if len(self._records) > self.MAX_RECORDS:
                self._records = self._records[-self.MAX_RECORDS:]

    def last(self) -> dict | None:
        with self._lock:
            return dict(self._records[-1]) if self._records else None

    def report(self) -> dict:
        with self._lock:
            n = len(self._records)
            last = dict(self._records[-1]) if self._records else None
            worst = min((r.get("occupancy", 0.0) for r in self._records),
                        default=None)
        return {"provesRecorded": n, "lastProve": last,
                "worstOccupancy": worst}

    def reset(self) -> None:
        with self._lock:
            self._records.clear()


REGISTRY = OccupancyRegistry()


def record_prove(lanes, devices=None, window=None) -> None:
    """Never-raise hook called by `_run_proof_jobs` after the VM batch:
    compute one prove's occupancy, stash it, refresh the
    prover_device_occupancy / idle-gap gauges."""
    try:
        report = compute(lanes, devices=devices, window=window)
        REGISTRY.record(report)
        metrics_mod.record_device_occupancy(
            report["occupancy"], report["idleGapSeconds"],
            report["devices"])
    except Exception:
        pass
