"""Open-loop load harness for the JSON-RPC serving layer.

The legacy generator (`utils/load_test.py`, now a shim over this
module) is CLOSED-loop: it fires the next request only after the
previous one returns, so a slow server throttles the generator and the
measured latencies silently omit exactly the stalls that matter
("coordinated omission" — see the Tail at Scale discussion in
docs/PERFORMANCE.md).  This harness is OPEN-loop:

- arrival times are PRECOMPUTED from a fixed or Poisson schedule before
  the clock starts, so response times cannot stretch interarrival gaps;
- a send slot with no free worker is counted as MISSED, never deferred —
  the offered rate is honest even when the server melts;
- per-request latency is measured from the SCHEDULED send instant to the
  response, into the shared exponential-bucket histogram ladder
  (utils/metrics.DEFAULT_BUCKETS), so server stalls surface as rising
  tail latency instead of a quietly reduced send rate;
- sweep mode replays the schedule at several offered rates over real TCP
  and reports max-sustainable-rate plus p50/p95/p99/error-rate per rate.

Traffic is a configurable mix of value transfers and token-template
calls (a per-caller balance-increment contract) from many simulated
funded senders, all pre-signed before the clock starts so signing cost
never pollutes the schedule.

Usage (open-loop):
    python -m ethrex_tpu.perf.loadgen --url http://127.0.0.1:8545 \
        --key <hex> --rates 10,25,50 --duration 5 --arrivals poisson

The legacy closed-loop flags (--txs/--mode) still work and run the old
inclusion-throughput measurement unchanged.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import http.client
import json
import random
import threading
import time
import urllib.request
from urllib.parse import urlparse

from ..crypto import secp256k1
from ..primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ..utils.metrics import Metrics
from ..utils.overload import is_busy_error

DEFAULT_KEY = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8

# counter contract: every call increments slot 0 (the "IO" load shape;
# kept here verbatim for the utils/load_test shim)
SSTORE_RUNTIME = "5f546001015f5500"
SSTORE_INITCODE = "67" + SSTORE_RUNTIME + "5f5260086018f3"

# token template: every call increments the CALLER-keyed storage slot —
# the balance-update shape of an ERC20 transfer without the calldata
# decoding (CALLER SLOAD 1 ADD CALLER SSTORE STOP)
TOKEN_RUNTIME = "3354600101335500"
TOKEN_INITCODE = "67" + TOKEN_RUNTIME + "5f5260086018f3"

# a run is "sustainable" at an offered rate when errors stay under 1%
# and the generator actually delivered ≥95% of the schedule (missed
# sends mean the local worker pool, not the server, was the bottleneck)
MAX_ERROR_RATE = 0.01
MIN_ACHIEVED_FRAC = 0.95


class LoadgenError(RuntimeError):
    """Transport failure or JSON-RPC error response during a run."""


def observe_request_latency(registry, kind: str, seconds: float):
    """Record one send-timestamp→response latency into the run's
    registry (same exponential-bucket ladder as the server side, so the
    client-observed and server-observed histograms are joinable)."""
    registry.observe("loadgen_request_seconds", seconds, {"kind": kind},
                     help_text="Open-loop request latency measured from "
                               "the SCHEDULED send instant to the "
                               "response, so server stalls surface as "
                               "latency, never as a reduced send rate")


def observe_shed_latency(registry, kind: str, seconds: float):
    """Latency of typed server-busy (shed) responses, kept in its OWN
    histogram: the accepted-request percentiles must measure work the
    server actually did, so shedding cannot game the serving p99
    gate."""
    registry.observe("loadgen_shed_seconds", seconds, {"kind": kind},
                     help_text="Latency of typed server-busy (shed) "
                               "responses from the scheduled send "
                               "instant — fast sheds are the overload "
                               "contract (docs/OVERLOAD.md)")


def observe_rejection_latency(registry, kind: str, seconds: float):
    """Latency of typed mempool rejections (per-sender cap, nonce gap,
    fee floor, ...), kept apart from both accepted work and sheds: the
    server answered fast and deliberately — admission control working
    as designed is neither served work nor an error."""
    registry.observe("loadgen_rejection_seconds", seconds,
                     {"kind": kind},
                     help_text="Latency of typed mempool-rejection "
                               "responses (error data carries the "
                               "admission reason) from the scheduled "
                               "send instant — admission control "
                               "pushing back, not a failure")


def build_schedule(rate: float, duration: float, arrivals: str = "fixed",
                   seed: int = 0) -> list[float]:
    """Arrival offsets (seconds from run start), precomputed so nothing
    the server does can stretch the interarrival gaps.

    fixed: deterministic 1/rate spacing.  poisson: exponential
    interarrival gaps (seeded), the memoryless arrival process real
    traffic approximates."""
    if rate <= 0 or duration <= 0:
        return []
    out: list[float] = []
    t = 0.0
    rng = random.Random(seed)
    while True:
        t += (1.0 / rate) if arrivals == "fixed" else rng.expovariate(rate)
        if t > duration:
            return out
        out.append(t)


def percentile_from_rows(buckets, rows, q: float) -> float | None:
    """Percentile estimate from cumulative-per-bucket histogram rows
    (the _Histogram layout), interpolated inside the winning bucket and
    capped at the last finite boundary for +Inf — the same estimator as
    timeseries.percentiles, over absolute counts instead of deltas."""
    if not rows:
        return None
    nb = len(buckets)
    counts = [0] * (nb + 1)
    for row in rows:
        for i in range(nb + 1):
            counts[i] += row[i]
    total = counts[nb]
    if total <= 0:
        return None
    rank = q * total
    value = buckets[-1]
    lower, prev = 0.0, 0
    for i, le in enumerate(buckets):
        if counts[i] >= rank:
            span = counts[i] - prev
            frac = (rank - prev) / span if span else 1.0
            value = lower + frac * (le - lower)
            break
        lower, prev = le, counts[i]
    return value


def derive_secrets(n: int, seed: int = 0) -> list[int]:
    """Deterministic simulated-sender keys (never real funds)."""
    out = []
    for i in range(n):
        h = hashlib.sha256(f"ethrex-loadgen-{seed}-{i}".encode()).digest()
        out.append(int.from_bytes(h, "big") % (secp256k1.N - 1) + 1)
    return out


class RpcConn:
    """One persistent JSON-RPC HTTP connection (keep-alive), with a
    single reconnect retry so a server-side idle close between runs does
    not read as a request error."""

    def __init__(self, url: str, timeout: float = 30.0):
        u = urlparse(url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.path = u.path or "/"
        self.timeout = timeout
        self._conn: http.client.HTTPConnection | None = None

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def post(self, body: bytes) -> dict:
        data = None
        for attempt in (0, 1):
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        self.host, self.port, timeout=self.timeout)
                self._conn.request("POST", self.path, body,
                                   {"Content-Type": "application/json"})
                resp = self._conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    raise LoadgenError(f"HTTP {resp.status}")
                break
            except (http.client.HTTPException, OSError) as exc:
                self.close()
                if attempt:
                    raise LoadgenError(f"transport: {exc}") from exc
        try:
            return json.loads(data)
        except (json.JSONDecodeError, TypeError) as exc:
            raise LoadgenError(f"bad response: {exc}") from exc

    def call(self, method: str, params: list):
        out = self.post(json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method,
             "params": params}).encode())
        if "error" in out:
            raise LoadgenError(f"{method}: {out['error']}")
        return out.get("result")


def _body(method: str, params: list, rid: int = 1) -> bytes:
    return json.dumps({"jsonrpc": "2.0", "id": rid, "method": method,
                       "params": params}).encode()


class _AsyncConn:
    """One persistent keep-alive JSON-RPC connection on the client
    event loop, with a single reconnect retry (mirroring RpcConn.post)
    so a server-side idle close does not read as a request error.
    Handles HTTP/1.0 close-per-response servers by reconnecting."""

    __slots__ = ("host", "port", "path", "timeout", "reader", "writer")

    def __init__(self, host: str, port: int, path: str, timeout: float):
        self.host = host
        self.port = port
        self.path = path
        self.timeout = timeout
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None

    def close(self):
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # noqa: BLE001 — teardown
                pass
            self.reader = self.writer = None

    async def connect(self):
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)

    async def _roundtrip(self, body: bytes) -> bytes:
        if self.writer is None:
            await self.connect()
        self.writer.write(
            b"POST %s HTTP/1.1\r\n"
            b"Host: %s\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n"
            % (self.path.encode(), self.host.encode(), len(body)) + body)
        await self.writer.drain()
        head = await self.reader.readuntil(b"\r\n\r\n")
        status_line, _, header_block = head.partition(b"\r\n")
        parts = status_line.split(None, 2)
        status = int(parts[1])
        headers: dict[bytes, bytes] = {}
        for line in header_block.split(b"\r\n"):
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower()] = v.strip()
        data = await self.reader.readexactly(
            int(headers.get(b"content-length", b"0")))
        connection = headers.get(b"connection", b"").lower()
        if b"close" in connection or (parts[0] == b"HTTP/1.0"
                                      and b"keep-alive" not in connection):
            self.close()
        if status != 200:
            raise LoadgenError(f"HTTP {status}")
        return data

    async def post(self, body: bytes):
        data = None
        for attempt in (0, 1):
            try:
                data = await asyncio.wait_for(self._roundtrip(body),
                                              self.timeout)
                break
            except (OSError, ConnectionError, ValueError, IndexError,
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError) as exc:
                self.close()
                if attempt:
                    raise LoadgenError(f"transport: {exc}") from exc
        try:
            return json.loads(data)
        except (json.JSONDecodeError, TypeError) as exc:
            raise LoadgenError(f"bad response: {exc}") from exc


REJECTION_CODE = -32000


def rejection_reason(err) -> str | None:
    """The typed mempool-rejection reason carried in a JSON-RPC error's
    structured data (rpc/eth.py send_raw_transaction), or None when the
    error is anything else.  Strict shape check mirrors is_busy_error:
    an untyped -32000 stays a generic error."""
    if not isinstance(err, dict) or err.get("code") != REJECTION_CODE:
        return None
    data = err.get("data")
    if not isinstance(data, dict):
        return None
    reason = data.get("reason")
    if isinstance(reason, str) and reason:
        return reason
    return None


def _classify(out) -> tuple[bool, bool, str | None]:
    """(err, shed, rejection_reason) from a decoded response.  A typed
    server-busy answer is graceful shedding and a typed mempool
    rejection is admission control doing its job — both counted apart
    from errors so sweeps distinguish degradation modes instead of
    folding cap pushback into a meaningless error rate.  A batch
    response counts as shed/rejected only when EVERY entry was typed
    (partial service delivered work); any untyped error entry makes the
    whole request an error."""
    if isinstance(out, list):
        if not out:
            return True, False, None
        errors = [e["error"] for e in out
                  if isinstance(e, dict) and "error" in e]
        if any(not is_busy_error(e) and rejection_reason(e) is None
               for e in errors):
            return True, False, None
        if errors and len(errors) == len(out):
            reason = next((rejection_reason(e) for e in errors
                           if rejection_reason(e)), None)
            if reason is not None:
                return False, False, reason
            return False, True, None
        return False, False, None
    if isinstance(out, dict) and "error" in out:
        reason = rejection_reason(out["error"])
        if reason is not None:
            return False, False, reason
        if is_busy_error(out["error"]):
            return False, True, None
        return True, False, None
    return False, False, None


class Harness:
    """Open-loop load harness against one JSON-RPC endpoint.

    payload="tx" sends pre-signed transactions from `senders` simulated
    accounts (mix of transfers and token-template calls; requires
    setup() against a funded root key).  payload="ping" sends
    eth_blockNumber — serving-layer load with no chain setup, which is
    what the open-loop unit tests and read-path sweeps use.
    payload="batch" sends JSON-RPC arrays of `batch_size`
    eth_blockNumber calls, exercising the server's concurrent batch
    dispatch; one array is one scheduled send slot.

    Send/receive runs on an asyncio client loop over `workers`
    persistent connections, so the generator outruns the server: the
    open-loop guarantees (scheduled-send latency base, missed-slot
    accounting) are unchanged — a slot with no free connection is a
    MISS, never deferred."""

    def __init__(self, url: str, key: int = DEFAULT_KEY, senders: int = 8,
                 token_frac: float = 0.25, workers: int = 64,
                 timeout: float = 10.0, seed: int = 0,
                 payload: str = "tx", batch_size: int = 8):
        self.url = url
        self.key = key
        self.senders = senders
        self.token_frac = token_frac
        self.workers = workers
        self.timeout = timeout
        self.seed = seed
        self.payload = payload
        self.batch_size = max(1, int(batch_size))
        self.secrets = derive_secrets(senders, seed) if payload == "tx" \
            else []
        self.addresses = [secp256k1.pubkey_to_address(
            secp256k1.pubkey_from_secret(s)) for s in self.secrets]
        self.chain_id: int | None = None
        self.token_address: bytes | None = None

    # -- setup (closed-loop, before any clock starts) -------------------
    def setup(self, fund_wei: int = 10 ** 18,
              produce: bool = True, fund_chunk: int | None = None) -> None:
        """Fund the simulated senders from the root key and deploy the
        token template.  Runs closed-loop: setup cost must never pollute
        the measured schedule.

        Funding is chunked: every `fund_chunk` transfers a block is
        produced to drain the mempool, so a 10k-sender sweep never
        piles 10k pending funding txs into admission.  The chunk
        defaults to the mempool's per-sender slot cap — the ROOT key is
        one sender, and admission rejects its 65th pending funding tx,
        which would leave every later sender unfunded."""
        if self.payload != "tx":
            return
        if fund_chunk is None:
            from ..blockchain.mempool import MAX_SENDER_SLOTS

            fund_chunk = MAX_SENDER_SLOTS
        rpc = RpcConn(self.url, timeout=30.0)
        try:
            self.chain_id = int(rpc.call("eth_chainId", []), 16)
            root = secp256k1.pubkey_to_address(
                secp256k1.pubkey_from_secret(self.key))
            nonce = int(rpc.call("eth_getTransactionCount",
                                 ["0x" + root.hex(), "pending"]), 16)
            for i, addr in enumerate(self.addresses):
                tx = Transaction(
                    tx_type=TYPE_DYNAMIC_FEE, chain_id=self.chain_id,
                    nonce=nonce, max_priority_fee_per_gas=1,
                    max_fee_per_gas=10 ** 10, gas_limit=21_000,
                    to=addr, value=fund_wei).sign(self.key)
                rpc.call("eth_sendRawTransaction",
                         ["0x" + tx.encode_canonical().hex()])
                nonce += 1
                if produce and fund_chunk and (i + 1) % fund_chunk == 0:
                    rpc.call("ethrex_produceBlock", [])
            deploy = Transaction(
                tx_type=TYPE_DYNAMIC_FEE, chain_id=self.chain_id,
                nonce=nonce, max_priority_fee_per_gas=1,
                max_fee_per_gas=10 ** 10, gas_limit=200_000, to=b"",
                data=bytes.fromhex(TOKEN_INITCODE)).sign(self.key)
            rpc.call("eth_sendRawTransaction",
                     ["0x" + deploy.encode_canonical().hex()])
            if produce:
                rpc.call("ethrex_produceBlock", [])
            receipt = None
            deadline = time.time() + 30
            while receipt is None and time.time() < deadline:
                receipt = rpc.call("eth_getTransactionReceipt",
                                   ["0x" + deploy.hash.hex()])
                if receipt is None:
                    time.sleep(0.2)
            if receipt is None or receipt.get("status") != "0x1":
                raise LoadgenError("token template deploy failed")
            self.token_address = bytes.fromhex(
                receipt["contractAddress"][2:])
        finally:
            rpc.close()

    # -- request pre-build ---------------------------------------------
    def _build_requests(self, n: int) -> list[tuple[str, bytes]]:
        """Pre-sign/pre-encode every request body before the clock
        starts, so signing cost cannot eat into send slots."""
        if self.payload == "batch":
            size = self.batch_size
            return [("batch", json.dumps(
                [{"jsonrpc": "2.0", "id": i * size + j,
                  "method": "eth_blockNumber", "params": []}
                 for j in range(size)]).encode())
                    for i in range(n)]
        if self.payload != "tx":
            return [("ping", _body("eth_blockNumber", [], i))
                    for i in range(n)]
        if self.chain_id is None:
            raise LoadgenError("setup() must run before a tx-mode run")
        rpc = RpcConn(self.url, timeout=30.0)
        try:
            nonces = [int(rpc.call("eth_getTransactionCount",
                                   ["0x" + a.hex(), "pending"]), 16)
                      for a in self.addresses]
        finally:
            rpc.close()
        rng = random.Random(self.seed + n)
        out: list[tuple[str, bytes]] = []
        for i in range(n):
            s = i % len(self.secrets)
            token = (self.token_address is not None
                     and rng.random() < self.token_frac)
            tx = Transaction(
                tx_type=TYPE_DYNAMIC_FEE, chain_id=self.chain_id,
                nonce=nonces[s], max_priority_fee_per_gas=1,
                max_fee_per_gas=10 ** 10,
                gas_limit=100_000 if token else 21_000,
                to=self.token_address if token else bytes([0xAA]) * 20,
                value=0 if token else 1).sign(self.secrets[s])
            nonces[s] += 1
            out.append(("token" if token else "transfer",
                        _body("eth_sendRawTransaction",
                              ["0x" + tx.encode_canonical().hex()], i)))
        return out

    # -- the open loop --------------------------------------------------
    def run(self, rate: float, duration: float = 5.0,
            arrivals: str = "fixed") -> dict:
        """One open-loop run at a single offered rate over real TCP."""
        schedule = build_schedule(rate, duration, arrivals, self.seed)
        requests = self._build_requests(len(schedule))
        registry = Metrics()
        stats = {"sent": 0, "errors": 0, "shed": 0, "missed": 0,
                 "rejected": 0}
        kinds: dict[str, int] = {}
        rejections: dict[str, int] = {}
        asyncio.run(self._run_async(schedule, requests, registry,
                                    stats, kinds, rejections))
        missed = stats["missed"]

        snap = registry.snapshot()

        def _lat(hist_name: str) -> dict:
            hist = snap["histograms"].get(hist_name)
            out: dict = {"count": 0, "meanSeconds": None,
                         "p50": None, "p95": None, "p99": None}
            if hist is not None:
                rows = [s["counts"] for s in hist["series"]]
                buckets = hist["buckets"]
                count = sum(r[-1] for r in rows)
                total = sum(s["sum"] for s in hist["series"])
                out["count"] = count
                out["meanSeconds"] = (total / count) if count else None
                for q in (0.50, 0.95, 0.99):
                    out[f"p{int(q * 100)}"] = percentile_from_rows(
                        buckets, rows, q)
            return out

        lat = _lat("loadgen_request_seconds")
        sent = stats["sent"]
        shed = stats["shed"]
        rejected = stats["rejected"]
        # accounting identity: every scheduled slot ends up in exactly
        # one of delivered / shed / rejected / missed
        # (sent = delivered + shed + rejected)
        return {
            "offeredRate": rate,
            "arrivals": arrivals,
            "durationSeconds": duration,
            "senders": self.senders if self.payload == "tx" else None,
            "scheduled": len(schedule),
            "sent": sent,
            "missed": missed,
            "errors": stats["errors"],
            "shed": shed,
            "rejected": rejected,
            "rejections": dict(sorted(rejections.items())),
            "delivered": sent - shed - rejected,
            "achievedRate": round(sent / duration, 3) if duration else 0.0,
            "errorRate": round(stats["errors"] / sent, 6) if sent else 0.0,
            "shedRate": round(shed / sent, 6) if sent else 0.0,
            "rejectionRate": round(rejected / sent, 6) if sent else 0.0,
            "kinds": dict(sorted(kinds.items())),
            "latency": lat,
            "shedLatency": _lat("loadgen_shed_seconds"),
            "rejectionLatency": _lat("loadgen_rejection_seconds"),
        }

    async def _run_async(self, schedule, requests, registry, stats,
                         kinds, rejections):
        """The open loop on an asyncio client: `workers` persistent
        connections in a free pool, one task per send slot."""
        u = urlparse(self.url)
        host = u.hostname or "127.0.0.1"
        port = u.port or 80
        path = u.path or "/"
        conns = [_AsyncConn(host, port, path, self.timeout)
                 for _ in range(self.workers)]
        # pre-connect OUTSIDE the measured schedule so handshake cost
        # cannot eat send slots (failures fall back to lazy reconnect)
        await asyncio.gather(*(c.connect() for c in conns),
                             return_exceptions=True)
        free = list(conns)
        inflight: set = set()

        async def one(conn, target, kind, body):
            err = shed = False
            reason = None
            try:
                out = await conn.post(body)
                err, shed, reason = _classify(out)
            except LoadgenError:
                err = True
            except Exception:  # noqa: BLE001 — a client bug must not
                err = True     # break the accounting identity
            latency = time.monotonic() - target
            if shed:
                observe_shed_latency(registry, kind, latency)
            elif reason is not None:
                observe_rejection_latency(registry, kind, latency)
            else:
                observe_request_latency(registry, kind, latency)
            stats["sent"] += 1
            kinds[kind] = kinds.get(kind, 0) + 1
            if err:
                stats["errors"] += 1
            if shed:
                stats["shed"] += 1
            if reason is not None:
                stats["rejected"] += 1
                rejections[reason] = rejections.get(reason, 0) + 1
            free.append(conn)

        start = time.monotonic() + 0.02
        for offset, (kind, body) in zip(schedule, requests):
            target = start + offset
            delay = target - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            # open-loop contract: a slot with no free connection is
            # counted and DROPPED — deferring it would serialize sends
            # behind server latency, which is exactly coordinated
            # omission
            if not free:
                stats["missed"] += 1
                continue
            conn = free.pop()
            task = asyncio.ensure_future(one(conn, target, kind, body))
            inflight.add(task)
            task.add_done_callback(inflight.discard)
        if inflight:
            await asyncio.wait(inflight, timeout=self.timeout + 5.0)
        for conn in conns:
            conn.close()

    def sweep(self, rates, duration: float = 5.0,
              arrivals: str = "fixed",
              max_error_rate: float = MAX_ERROR_RATE,
              min_achieved_frac: float = MIN_ACHIEVED_FRAC) -> dict:
        """Run the schedule at each offered rate (ascending) and report
        the highest rate the server sustained: errors under
        max_error_rate and ≥ min_achieved_frac of the schedule actually
        delivered.  A typed busy response is graceful but still NOT
        delivered work, so shed slots count against sustainability —
        and typed mempool rejections are treated exactly the same way
        (admission control refusing work is not work done), without
        ever inflating the error rate."""
        results = [self.run(r, duration, arrivals)
                   for r in sorted(rates)]
        sustainable = None
        for rep in results:
            offered = rep["offeredRate"]
            delivered = rep.get("delivered", rep["sent"]) / rep["scheduled"] \
                if rep["scheduled"] else 0.0
            if (rep["errorRate"] <= max_error_rate
                    and delivered >= min_achieved_frac):
                sustainable = offered
        return {
            "arrivals": arrivals,
            "durationSeconds": duration,
            "senders": self.senders if self.payload == "tx" else None,
            "maxSustainableRate": sustainable,
            "maxErrorRate": max_error_rate,
            "minAchievedFrac": min_achieved_frac,
            "rates": results,
        }


# ---------------------------------------------------------------------------
# legacy closed-loop generator (moved verbatim from utils/load_test.py;
# measures inclusion throughput, NOT serving tail — see module docstring)


def _rpc(url: str, method: str, *params):
    payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)}).encode()
    req = urllib.request.Request(
        url, data=payload, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.loads(resp.read())
    if "error" in out:
        raise RuntimeError(f"{method}: {out['error']}")
    return out["result"]


def run_load(url: str, secret: int, num_txs: int,
             mode: str = "transfer") -> dict:
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    chain_id = int(_rpc(url, "eth_chainId"), 16)
    nonce = int(_rpc(url, "eth_getTransactionCount",
                     "0x" + sender.hex(), "pending"), 16)
    target = bytes.fromhex("aa" * 20)
    gas_limit = 21000
    data = b""
    if mode == "sstore":
        deploy = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=200_000, to=b"",
            data=bytes.fromhex(SSTORE_INITCODE)).sign(secret)
        _rpc(url, "eth_sendRawTransaction",
             "0x" + deploy.encode_canonical().hex())
        receipt = None
        deadline = time.time() + 30
        while receipt is None and time.time() < deadline:
            receipt = _rpc(url, "eth_getTransactionReceipt",
                           "0x" + deploy.hash.hex())
            time.sleep(0.2)
        if receipt is None:
            raise RuntimeError("deploy was not mined")
        if receipt["status"] != "0x1":
            raise RuntimeError("counter deploy reverted")
        target = bytes.fromhex(receipt["contractAddress"][2:])
        gas_limit = 100_000
        nonce += 1

    start_block = int(_rpc(url, "eth_blockNumber"), 16)
    t0 = time.time()
    for i in range(num_txs):
        tx = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce + i,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas_limit, to=target, value=1 if mode == "transfer"
            else 0, data=data).sign(secret)
        _rpc(url, "eth_sendRawTransaction",
             "0x" + tx.encode_canonical().hex())
    submit_time = time.time() - t0

    # wait for full inclusion (incremental scan: only NEW blocks per poll)
    deadline = time.time() + 120
    included = 0
    gas_used = 0
    scanned = start_block
    while time.time() < deadline:
        head = int(_rpc(url, "eth_blockNumber"), 16)
        for n in range(scanned + 1, head + 1):
            blk = _rpc(url, "eth_getBlockByNumber", hex(n), False)
            included += len(blk["transactions"])
            gas_used += int(blk["gasUsed"], 16)
        scanned = max(scanned, head)
        if included >= num_txs:  # the sstore deploy mines BEFORE start_block
            break
        time.sleep(0.3)
    total = time.time() - t0
    return {
        "mode": mode,
        "txs_submitted": num_txs,
        "txs_included": included,
        "submit_tps": round(num_txs / submit_time, 1),
        "end_to_end_tps": round(included / total, 1),
        "mgas_per_s": round(gas_used / total / 1e6, 3),
        "wall_s": round(total, 2),
    }


# ---------------------------------------------------------------------------
# reorg chaos driver (docs/CHAIN_RESILIENCE.md "The reorg storm")


class ReorgDriver:
    """Periodic depth-k fork-choice flips while open-loop load runs —
    the reorg-storm half of the chaos harness (tests/test_reorg_chaos.py
    soak; reusable by future batteries).

    Works over the engine API alone: each flip records the current tip,
    rolls the head back `depth` blocks with engine_forkchoiceUpdatedV3
    (orphaning the top of the chain and re-injecting its txs), then
    re-adopts the recorded tip.  Blocks produced between the two legs
    turn the rollback into a genuine sibling-branch reorg.  `call` is
    any `call(method, *params) -> result` reaching an engine-authorized
    endpoint: tests pass an in-process dispatcher; the CLI builds a
    JWT-bearing HTTP caller from --engine-url/--jwt-hex."""

    def __init__(self, call, interval: float = 1.0, depth: int = 2):
        self.call = call
        self.interval = interval
        self.depth = max(1, int(depth))
        self.flips = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def flip_once(self) -> bool:
        """One rollback + re-adopt pair; returns False while the chain
        is still shorter than the flip depth."""
        head = self.call("eth_getBlockByNumber", "latest", False)
        number = int(head["number"], 16)
        if number < self.depth:
            return False
        ancestor = self.call("eth_getBlockByNumber",
                             hex(number - self.depth), False)
        zero = "0x" + "00" * 32
        for target in (ancestor["hash"], head["hash"]):
            self.call("engine_forkchoiceUpdatedV3",
                      {"headBlockHash": target, "safeBlockHash": zero,
                       "finalizedBlockHash": zero})
        self.flips += 1
        return True

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.flip_once()
            except Exception:  # noqa: BLE001 — the storm must outlive
                self.errors += 1  # transient RPC errors under load

    def start(self) -> "ReorgDriver":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def stats(self) -> dict:
        return {"flips": self.flips, "errors": self.errors,
                "intervalSeconds": self.interval, "depth": self.depth}


def engine_caller(url: str, jwt_secret: bytes):
    """call(method, *params) against an engine-authorized endpoint,
    minting a fresh JWT per request (the iat claim must stay within
    the server's drift window across a long storm)."""
    from ..rpc.engine import jwt_encode

    def call(method, *params):
        payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
        req = urllib.request.Request(
            url, data=payload,
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer " + jwt_encode(jwt_secret)})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        if "error" in out:
            raise RuntimeError(f"{method}: {out['error']}")
        return out["result"]

    return call


# ---------------------------------------------------------------------------
# CLI — open-loop when --rate/--rates given, legacy closed-loop otherwise


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ethrex-tpu-loadgen")
    parser.add_argument("--url", default="http://127.0.0.1:8545")
    parser.add_argument("--key", default=hex(DEFAULT_KEY),
                        help="funded root key (hex) used to fund the "
                             "simulated senders")
    # open-loop flags
    parser.add_argument("--rate", type=float, default=0.0,
                        help="open-loop offered rate (req/s); 0 = use "
                             "--rates or the legacy closed-loop path")
    parser.add_argument("--rates", default="",
                        help="comma-separated offered rates for a sweep "
                             "(e.g. 10,25,50)")
    parser.add_argument("--duration", type=float, default=5.0,
                        help="seconds per offered rate")
    parser.add_argument("--arrivals", choices=("fixed", "poisson"),
                        default="fixed")
    parser.add_argument("--senders", type=int, default=8,
                        help="simulated funded sender accounts")
    parser.add_argument("--token-frac", type=float, default=0.25,
                        dest="token_frac",
                        help="fraction of requests that call the token "
                             "template instead of a plain transfer")
    parser.add_argument("--workers", type=int, default=64,
                        help="persistent connections = max concurrent "
                             "in-flight requests; a full pool at a send "
                             "slot counts a miss")
    parser.add_argument("--timeout", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--payload", choices=("tx", "ping", "batch"),
                        default="tx",
                        help="tx = signed transfers/token calls (needs a "
                             "funded --key); ping = eth_blockNumber "
                             "only; batch = JSON-RPC arrays of "
                             "--batch-size eth_blockNumber calls")
    parser.add_argument("--batch-size", type=int, default=8,
                        dest="batch_size",
                        help="entries per JSON-RPC batch array when "
                             "--payload batch")
    # reorg-storm chaos driver (depth-k fork-choice flips during load)
    parser.add_argument("--reorg-interval", type=float, default=0.0,
                        dest="reorg_interval",
                        help="seconds between depth-k fork-choice flips "
                             "while the load runs (0 = off); needs "
                             "--engine-url and --jwt-hex")
    parser.add_argument("--reorg-depth", type=int, default=2,
                        dest="reorg_depth",
                        help="blocks rolled back per flip")
    parser.add_argument("--engine-url", default="",
                        dest="engine_url",
                        help="engine-authorized endpoint the reorg "
                             "driver flips through")
    parser.add_argument("--jwt-hex", default="", dest="jwt_hex",
                        help="hex JWT secret for --engine-url")
    # legacy closed-loop flags
    parser.add_argument("--txs", type=int, default=200)
    parser.add_argument("--mode", choices=("transfer", "sstore"),
                        default="transfer")
    args = parser.parse_args(argv)

    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if args.rate > 0:
        rates.append(args.rate)
    driver = None
    if args.reorg_interval > 0:
        if not args.engine_url or not args.jwt_hex:
            parser.error("--reorg-interval needs --engine-url and "
                         "--jwt-hex")
        driver = ReorgDriver(
            engine_caller(args.engine_url, bytes.fromhex(args.jwt_hex)),
            interval=args.reorg_interval, depth=args.reorg_depth).start()
    try:
        if rates:
            harness = Harness(args.url, key=int(args.key, 16),
                              senders=args.senders,
                              token_frac=args.token_frac,
                              workers=args.workers, timeout=args.timeout,
                              seed=args.seed, payload=args.payload,
                              batch_size=args.batch_size)
            harness.setup()
            if len(rates) == 1:
                result = harness.run(rates[0], args.duration,
                                     args.arrivals)
            else:
                result = harness.sweep(rates, args.duration,
                                       args.arrivals)
        else:
            result = run_load(args.url, int(args.key, 16), args.txs,
                              args.mode)
    finally:
        if driver is not None:
            driver.stop()
    if driver is not None:
        result["reorgStorm"] = driver.stats()
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
