"""The bench suite: BASELINE measurements, backend probing, the CPU
fallback, the append-only history, and the CI regression gate.  The
repo-root ``bench.py`` is a thin CLI shim over this module.

Headline: BASELINE config 1 — prove a 10-transfer block end-to-end on
one TPU chip — plus BASELINE configs 2/4/5 attached to the same JSON
line when the chip budget allows.

The measured quantity is the full `--prover tpu` pipeline on a real
committed batch: stateless re-execution, per-tx fine-log derivation, and
the DEEP-FRI STARKs (state-update circuit, VM circuits, output binding),
exactly what `TpuBackend.prove` ships to the proof coordinator, followed
by an independent `verify`.

Configs (BASELINE.md):
  1 (headline)      10-transfer block, vm mode, 3 STARKs
  2 (--measure-2)   100-tx ERC-20 batch, token mode, 4 STARKs
  3 (BENCH_FULL=1)  1000-tx mixed transfer+token batch (opt-in: hours of
                    compile on a cold cache)
  4 (--measure-4)   Groth16 BN254 wrap (format=groth16 on the config-1
                    batch: aggregation + wrap + full verify)
  5 (--measure-5)   8-proof recursive aggregation (8 sponge STARKs in
                    ONE outer FriVerifyAir proof, verified)

Host-side configs (chip-independent): --measure-mgas (L1 pipelined
import throughput) and --measure-serving (open-loop JSON-RPC serving
sweep via perf/loadgen — client-observed p50/p95/p99 + error rate at
each offered rate over real TCP against a live in-process node, gated
on p99 and sustained rate).

Cold start: --measure-warmup runs the cold-vs-hydrated warmup drill —
two child processes share one fresh executable-cache dir (via
ETHREX_EXEC_CACHE_DIR), the first compiling and serializing the AOT
executable, the second hydrating it — and appends a gateable
`stark_core_warmup_hydrated_s` record (lower is better) carrying both
warmup walls (`warmup_s`).  --measure-warmup-child is the per-process
entry point.

Mesh scaling: --measure-scaling sweeps the prove-core cells/s at
1/2/4/8 simulated host devices (one forced-CPU child per count via
XLA_FLAGS=--xla_force_host_platform_device_count; list overridable
with BENCH_SCALING_DEVICES) and appends ONE history record whose
`devices`/`scaling` fields keep it out of the same-backend regression
gates.  --measure-scaling-one is the per-count child entry point.

vs_baseline is a measured-vs-measured gas rate: the reference's SP1-CUDA
prover does a 7,898,434-gas mainnet block in 143 s on an RTX 4090
(/root/reference/docs/l2/bench/prover_performance.md:7-9) = 55,234 gas/s;
we report (batch_gas / wall_s) / 55,234.

Resilience: the chip sits behind a flaky network tunnel.  Every
measurement runs in a child process under a hard timeout with retries;
successes are persisted to .bench_last.json; if the end-to-end
measurement cannot run, the suite distinguishes two failure shapes:

  * ABSENT chip (jax imports fine, default_backend is cpu): run the
    same pipeline on CPU up front, tagged ``backend: "cpu"``.
  * BROKEN chip (a present-but-dead TPU plugin hangs `jax.devices()`
    so `detect_backend()` returns None): after the probe retries are
    exhausted, probe a FORCED-CPU child (`jax.config.update` — the
    plugin ignores JAX_PLATFORMS) and, when that works, run the
    headline + core configs forced to CPU, again tagged
    ``backend: "cpu"``.

Either way the record carries real prover numbers with per-stage
breakdowns and is NEVER cached to .bench_last.json as a chip record;
only when both shapes fail does the suite degrade to the last cached
chip record.  Every final record is also appended to
``bench_history.jsonl`` (one JSON object per line, with ts + backend)
so the perf trajectory survives .bench_last.json overwrites — the
regression gate reads same-backend pairs out of this file.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...,
"backend", "stages", "configs": {...}}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_GAS_PER_SEC = 7_898_434 / 143.0
BASELINE_CELLS_PER_SEC = 1.0e8  # round-1/2 estimated anchor (fallback only)
# this module lives at ethrex_tpu/perf/bench_suite.py; the CLI shim and
# the state files live at the repo root next to it
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
BENCH_PATH = os.path.join(_REPO_ROOT, "bench.py")
LAST_PATH = os.path.join(_REPO_ROOT, ".bench_last.json")
HISTORY_PATH = os.path.join(_REPO_ROOT, "bench_history.jsonl")
ATTEMPT_TIMEOUT = int(os.environ.get("BENCH_TIMEOUT", "3000"))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", "3"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
NUM_TXS = int(os.environ.get("BENCH_TXS", "10"))

# forces the cpu platform through jax.config BEFORE any backend is
# touched: the axon TPU plugin ignores JAX_PLATFORMS, and a dead plugin
# can hang jax.devices() indefinitely rather than erroring
_FORCED_CPU_CHECK = ("import jax; "
                     "jax.config.update('jax_platforms', 'cpu'); "
                     "jax.devices()")


def probe_backend_error() -> str | None:
    """Cheap child-process jax.devices() probe so a dead tunnel costs
    PROBE_TIMEOUT, not a full measurement timeout (the tunnel can hang
    indefinitely rather than erroring).  Returns None when the backend is
    usable, else a short diagnostic ("ExcType: message") so a degraded
    record says WHY the probe failed."""
    want_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"
    check = ("import jax; assert jax.default_backend() != 'cpu'"
             if not want_cpu else _FORCED_CPU_CHECK)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", check],
            capture_output=True, timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return f"TimeoutExpired: backend probe exceeded {PROBE_TIMEOUT}s"
    if proc.returncode == 0:
        return None
    # last non-empty stderr line is the exception line of the traceback
    stderr = proc.stderr.decode(errors="replace") if proc.stderr else ""
    lines = [ln.strip() for ln in stderr.splitlines() if ln.strip()]
    detail = lines[-1] if lines else f"exit code {proc.returncode}"
    return detail[:400]


def probe_backend() -> bool:
    return probe_backend_error() is None


def probe_cpu_error() -> str | None:
    """Forced-CPU child probe for the dead-tunnel fallback: can this
    host run JAX at all once the (possibly broken) accelerator plugin is
    forced out of the way?  None when yes, else a short diagnostic."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _FORCED_CPU_CHECK],
            capture_output=True, timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return f"TimeoutExpired: forced-CPU probe exceeded {PROBE_TIMEOUT}s"
    if proc.returncode == 0:
        return None
    stderr = proc.stderr.decode(errors="replace") if proc.stderr else ""
    lines = [ln.strip() for ln in stderr.splitlines() if ln.strip()]
    detail = lines[-1] if lines else f"exit code {proc.returncode}"
    return detail[:400]


def detect_backend() -> str | None:
    """Child-process `jax.default_backend()` — distinguishes a CPU-only
    host (jax imports fine, no chip plugged in) from a broken/hung
    backend (None).  Drives the CPU fallback in main(): a host with no
    chip should publish an honest backend=cpu record, not degrade after
    three probe retries that can never pass."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    out = proc.stdout.decode(errors="replace").strip()
    return out or None


def _guard_backend() -> None:
    if os.environ.get("BENCH_ALLOW_CPU") == "1":
        # the axon TPU plugin ignores JAX_PLATFORMS; force CPU through
        # jax.config before any backend is touched (CPU smoke runs only)
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import jax

    if (jax.default_backend() == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        print("backend is cpu, refusing to publish", file=sys.stderr)
        sys.exit(3)
    from ethrex_tpu.utils.jax_cache import enable_persistent_cache

    enable_persistent_cache()


def measure() -> None:
    """BASELINE config 1: one block of NUM_TXS plain transfers, proven
    end-to-end and independently verified."""
    _guard_backend()

    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.guest.witness import generate_witness
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover.tpu_backend import TpuBackend

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    for n in range(NUM_TXS):
        tx = Transaction(
            tx_type=2, chain_id=1337, nonce=n,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21_000, to=bytes([0x50 + n]) * 20, value=1000 + n,
        ).sign(secret)
        node.submit_transaction(tx)
    block = node.produce_block()
    gas = block.header.gas_used
    witness = generate_witness(node.chain, [block])
    pi = ProgramInput(blocks=[block], witness=witness, config=node.config)

    backend = TpuBackend()
    # one warm-up prove compiles (or hydrates from the on-disk
    # executable cache) every XLA program before the timed section;
    # warmup_s + the cache hit/miss split record which one happened
    t_w0 = time.perf_counter()
    warm = backend.prove(pi, "stark")
    warmup_wall = time.perf_counter() - t_w0
    assert warm.get("vm", {}).get("mode") == "transfer"

    from ethrex_tpu.utils import exec_cache, tracing

    t0 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        proof = backend.prove(pi, "stark")
    wall = time.perf_counter() - t0
    if not backend.verify(proof):
        print("self-verification failed", file=sys.stderr)
        sys.exit(4)

    # per-stage breakdown from the profiling spans of the timed prove
    stages = {}
    critical = {}
    if bench_span is not None:
        stages = {k: round(v, 4) for k, v in sorted(
            tracing.TRACER.stage_breakdown(bench_span.trace_id).items())}
        # critical-path attribution of the same trace: unlike "stages"
        # (which sums possibly-overlapping stage spans), these components
        # partition the wall, so they answer WHICH leg dominated
        cp = tracing.critical_path(
            tracing.TRACER.get_trace(bench_span.trace_id))
        critical = {k: round(v, 4) for k, v in sorted(
            cp.get("components", {}).items())}

    cache_stats = exec_cache.runtime_stats()
    gas_per_sec = gas / wall
    print(json.dumps({
        "metric": "transfer_batch_prove_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(gas_per_sec / BASELINE_GAS_PER_SEC, 4),
        "batch_gas": gas,
        "num_txs": NUM_TXS,
        "gas_per_sec": round(gas_per_sec, 1),
        "proofs_per_hour_chip": round(3600.0 / wall, 2),
        "warmup_s": round(warmup_wall, 3),
        "executable_cache": {k: cache_stats.get(k) for k in
                             ("hits", "misses", "errors", "stores")},
        "stages": stages,
        "critical_path": critical,
        "config": "BASELINE-1 (10-transfer block, vm mode, 3 STARKs)",
    }))


def _token_genesis(sender):
    from ethrex_tpu.guest import token_template as tt

    token = bytes.fromhex("7070" * 10)
    storage = {hex(tt.balance_slot(sender)): hex(10**15)}
    return token, {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {
            "0x" + sender.hex(): {"balance": hex(10**21)},
            "0x" + token.hex(): {"balance": "0x0",
                                 "code": "0x" + tt.TEMPLATE_CODE.hex(),
                                 "storage": storage},
        },
        "gasLimit": hex(60_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }


def _span_stages(bench_span) -> dict:
    """Stage breakdown of one timed region from its trace's spans."""
    from ethrex_tpu.utils import tracing

    if bench_span is None:
        return {}
    return {k: round(v, 4) for k, v in sorted(
        tracing.TRACER.stage_breakdown(bench_span.trace_id).items())}


def measure_config2() -> None:
    """BASELINE config 2: a 100-tx ERC-20 batch, token mode, proven
    end-to-end (state + transfer + token + binding STARKs), verified."""
    _guard_backend()

    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.guest import token_template as tt
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.guest.witness import generate_witness
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover.tpu_backend import TpuBackend
    from ethrex_tpu.utils import tracing

    n_txs = int(os.environ.get("BENCH_ERC20_TXS", "100"))
    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    token, genesis = _token_genesis(sender)
    node = Node(Genesis.from_json(genesis))
    for n in range(n_txs):
        node.submit_transaction(Transaction(
            tx_type=2, chain_id=1337, nonce=n,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=100_000, to=token, value=0,
            data=tt.transfer_calldata(bytes([0x60 + n % 16]) * 20,
                                      100 + n)).sign(secret))
    block = node.produce_block()
    gas = block.header.gas_used
    assert len(block.body.transactions) == n_txs
    witness = generate_witness(node.chain, [block])
    pi = ProgramInput(blocks=[block], witness=witness, config=node.config)
    backend = TpuBackend()
    warm = backend.prove(pi, "stark")
    assert warm.get("vm", {}).get("mode") == "token"
    t0 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        proof = backend.prove(pi, "stark")
    wall = time.perf_counter() - t0
    if not backend.verify(proof):
        print("self-verification failed", file=sys.stderr)
        sys.exit(4)
    print(json.dumps({
        "metric": "erc20_batch_prove_wall_s", "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round((gas / wall) / BASELINE_GAS_PER_SEC, 4),
        "batch_gas": gas, "num_txs": n_txs,
        "gas_per_sec": round(gas / wall, 1),
        "stages": _span_stages(bench_span),
        "config": "BASELINE-2 (100-tx ERC-20 batch, token mode, 4 STARKs)",
    }))


def _phase_compile_walls() -> dict:
    """Per-phase-program AOT compile seconds ("Air/kernel", suffixed
    "@<mesh>" on mesh builds) from the in-process metrics registry —
    populated by a warmup prove's phase-program builds
    (stark/prover.py _aot_phases), single-device and mesh paths alike.
    Gives the cold-start item-2 work a per-program baseline to beat."""
    from ethrex_tpu.utils.metrics import METRICS

    out: dict = {}
    snap = METRICS.snapshot()
    hist = (snap.get("histograms") or {}).get(
        "prover_phase_compile_seconds") or {}
    for row in hist.get("series", []):
        lab = row.get("labels", {})
        key = "{}/{}".format(lab.get("air", "?"), lab.get("kernel", "?"))
        if lab.get("mesh", "none") != "none":
            key += "@" + lab["mesh"]
        out[key] = round(out.get(key, 0.0) + float(row.get("sum", 0.0)), 4)
    return out


def measure_config4() -> None:
    """BASELINE config 4: Groth16 BN254 wrap — format=groth16 on the
    config-1 batch (aggregation + R1CS wrap + pairing verify).  The
    warmup's compile cost is broken down per phase program in the
    record's `phase_compile` map."""
    _guard_backend()

    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.guest.witness import generate_witness
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover.tpu_backend import TpuBackend
    from ethrex_tpu.utils import tracing

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    for n in range(NUM_TXS):
        node.submit_transaction(Transaction(
            tx_type=2, chain_id=1337, nonce=n,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21_000, to=bytes([0x50 + n]) * 20,
            value=1000 + n).sign(secret))
    block = node.produce_block()
    witness = generate_witness(node.chain, [block])
    pi = ProgramInput(blocks=[block], witness=witness, config=node.config)
    backend = TpuBackend()
    t_w0 = time.perf_counter()
    warm = backend.prove(pi, "groth16")
    warmup_wall = time.perf_counter() - t_w0
    assert "groth16" in warm
    t0 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        proof = backend.prove(pi, "groth16")
    wall = time.perf_counter() - t0
    if not backend.verify(proof):
        print("self-verification failed", file=sys.stderr)
        sys.exit(4)
    print(json.dumps({
        "metric": "groth16_wrap_prove_wall_s", "value": round(wall, 3),
        "unit": "s", "vs_baseline": 0.0,
        "batch_gas": block.header.gas_used,
        "stages": _span_stages(bench_span),
        "warmup_wall_s": round(warmup_wall, 3),
        "phase_compile": _phase_compile_walls(),
        "config": "BASELINE-4 (config-1 batch, compressed + Groth16 wrap)",
    }))


def measure_config5() -> None:
    """BASELINE config 5: 8-proof recursive aggregation — eight sponge
    STARKs proven, then ONE outer FriVerifyAir STARK covering every FRI
    query opening of all eight; verify_aggregated must accept."""
    _guard_backend()

    from ethrex_tpu.models import poseidon2_air as pair
    from ethrex_tpu.stark import aggregate as agg_mod
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark.prover import StarkParams
    from ethrex_tpu.utils import tracing

    params = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)
    airs, proofs = [], []
    for i in range(8):
        limbs = pair.pad_message_limbs(list(range(16 * (i + 1))))
        air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
        trace = pair.generate_sponge_trace(limbs)
        pub = pair.sponge_public_inputs(limbs)
        proofs.append(stark_prover.prove(air, trace, pub, params))
        airs.append(air)
    # warm-up aggregation compiles the outer AIR's phase programs
    agg_mod.aggregate(airs, proofs, params)
    t0 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        agg = agg_mod.aggregate(airs, proofs, params)
    wall = time.perf_counter() - t0
    agg_mod.verify_aggregated(airs, agg, params)
    print(json.dumps({
        "metric": "aggregate8_prove_wall_s", "value": round(wall, 3),
        "unit": "s", "vs_baseline": 0.0,
        "stages": _span_stages(bench_span),
        "config": "BASELINE-5 (8 STARKs -> one outer recursion proof)",
    }))


def measure_config3() -> None:
    """BASELINE config 3 (opt-in, BENCH_FULL=1): 1000-tx mixed batch —
    500 transfers + 500 token calls across blocks."""
    _guard_backend()

    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.guest import token_template as tt
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.guest.witness import generate_witness
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover.tpu_backend import TpuBackend
    from ethrex_tpu.utils import tracing

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    token, genesis = _token_genesis(sender)
    node = Node(Genesis.from_json(genesis))
    nonce = 0
    blocks = []
    for _ in range(4):   # 4 blocks x 250 txs
        for i in range(125):
            node.submit_transaction(Transaction(
                tx_type=2, chain_id=1337, nonce=nonce,
                max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                gas_limit=21_000, to=bytes([0x50 + i % 32]) * 20,
                value=100 + i).sign(secret))
            nonce += 1
            node.submit_transaction(Transaction(
                tx_type=2, chain_id=1337, nonce=nonce,
                max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                gas_limit=100_000, to=token, value=0,
                data=tt.transfer_calldata(bytes([0x60 + i % 16]) * 20,
                                          10 + i)).sign(secret))
            nonce += 1
        blocks.append(node.produce_block())
    gas = sum(b.header.gas_used for b in blocks)
    witness = generate_witness(node.chain, blocks)
    pi = ProgramInput(blocks=blocks, witness=witness, config=node.config)
    backend = TpuBackend()
    warm = backend.prove(pi, "stark")
    assert warm.get("vm", {}).get("mode") == "token"
    t0 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        proof = backend.prove(pi, "stark")
    wall = time.perf_counter() - t0
    if not backend.verify(proof):
        sys.exit(4)
    print(json.dumps({
        "metric": "mixed1000_batch_prove_wall_s", "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round((gas / wall) / BASELINE_GAS_PER_SEC, 4),
        "batch_gas": gas, "num_txs": 1000,
        "stages": _span_stages(bench_span),
        "config": "BASELINE-3 (1000-tx mixed batch)",
    }))


def measure_mgas() -> None:
    """L1 execution-throughput microbench (reference anchor: ~669 Mgas/s
    live import on its bench box, docs/perf/README.md:126-131): build a
    chain of full transfer blocks, then re-import it through the
    PIPELINED path (execute N+1 while N merkleizes in the native C++
    MPT engine) into a fresh store.  Host CPU only — no TPU needed."""
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")  # axon ignores the env
    except Exception:
        pass
    from ethrex_tpu.blockchain.blockchain import Blockchain
    from ethrex_tpu.blockchain.fork_choice import apply_fork_choice
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.perf.profiler import PROFILER
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.storage.store import Store

    from ethrex_tpu.blockchain.mempool import MAX_SENDER_SLOTS

    num_blocks = int(os.environ.get("BENCH_MGAS_BLOCKS", "20"))
    txs_per_block = int(os.environ.get("BENCH_MGAS_TXS", "400"))
    # enough senders that no one holds more than the mempool's per-sender
    # slot cap while a block's worth of txs queues (the cap is overload
    # protection on the serving path; the untimed chain build here must
    # live within it, not bypass it)
    n_senders = -(-txs_per_block // MAX_SENDER_SLOTS)
    secrets = [0xA11CE + i for i in range(n_senders)]
    senders = [secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(s)) for s in secrets]
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + a.hex(): {"balance": hex(10**24)}
                  for a in senders},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    nonces = [0] * n_senders
    blocks = []
    for _ in range(num_blocks):
        for i in range(txs_per_block):
            s = i % n_senders
            node.submit_transaction(Transaction(
                tx_type=2, chain_id=1337, nonce=nonces[s],
                max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                gas_limit=21_000, to=bytes([0x50 + i % 64]) * 20,
                value=1 + i).sign(secrets[s]))
            nonces[s] += 1
        blocks.append(node.produce_block())
    gas = sum(b.header.gas_used for b in blocks)
    # RLP round-trip so the import is COLD, like a real sync: the chain
    # build above cached every tx's sender; re-decoding drops those
    # caches, so the timed region pays (batched, parallel) signature
    # recovery like a node importing a chain file would
    from ethrex_tpu.primitives.block import Block as _Block
    blocks = [_Block.decode(b.encode()) for b in blocks]
    # fresh store, re-import through full validation (pipelined)
    store = Store()
    gh = store.init_genesis(Genesis.from_json(genesis))
    chain = Blockchain(store, node.config)
    # stage attribution: the import path feeds the continuous profiler
    # (execute / merkleize / store_write + the evm sig_recovery /
    # opcode_loop split); deltas around the timed region isolate this
    # import from the chain build above
    before = PROFILER.stage_totals("l1_import")
    before_evm = PROFILER.stage_totals("evm")
    t0 = time.perf_counter()
    chain.add_blocks_pipelined(blocks)
    wall = time.perf_counter() - t0
    after = PROFILER.stage_totals("l1_import")
    after_evm = PROFILER.stage_totals("evm")
    stages = {k: round(after.get(k, 0.0) - before.get(k, 0.0), 4)
              for k in sorted(set(after) | set(before))
              if after.get(k, 0.0) - before.get(k, 0.0) > 0}
    stages.update({
        f"evm/{k}": round(after_evm.get(k, 0.0) - before_evm.get(k, 0.0), 4)
        for k in sorted(set(after_evm) | set(before_evm))
        if after_evm.get(k, 0.0) - before_evm.get(k, 0.0) > 0})
    apply_fork_choice(store, blocks[-1].hash)
    assert store.head_header().hash == blocks[-1].hash
    from ethrex_tpu.crypto import native_secp256k1
    print(json.dumps({
        "metric": "l1_import_mgas_per_sec",
        "value": round(gas / wall / 1e6, 2),
        "unit": "Mgas/s",
        "vs_baseline": round((gas / wall / 1e6) / 669.0, 4),
        "blocks": num_blocks, "txs": num_blocks * txs_per_block,
        "batch_gas": gas, "wall_s": round(wall, 3),
        "native_secp256k1": native_secp256k1.available(),
        "stages": stages or {"import": round(wall, 4)},
        "config": "L1 pipelined import (cold senders), ETH transfers "
                  "(ref anchor 669 Mgas/s, docs/perf/README.md:126-131)",
    }))


def measure_core() -> None:
    """Fallback microbench: fully-jitted prove-core throughput (the round
    1-2 metric, against its documented estimated anchor), now AOT-
    compiled so the record pairs measured cells/s with the kernel's
    static FLOPs and a utilization-vs-peak estimate."""
    _guard_backend()
    import jax

    from ethrex_tpu.parallel.core import compile_prove_step
    from ethrex_tpu.perf import roofline

    t_c0 = time.perf_counter()
    fn, args, cost = compile_prove_step(log_n=15, width=64, log_blowup=2,
                                        log_final_size=5, mesh=None)
    jax.block_until_ready(fn(*args))
    t_compile = time.perf_counter() - t_c0
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        runs.append(time.perf_counter() - t0)
    wall = min(runs)
    value = (1 << 15) * 64 / wall
    parsed = roofline._parse_cost(cost)
    flops = parsed.get("flops")
    peak = roofline.peak_flops_estimate()
    achieved = flops / wall if flops and wall > 0 else None
    out = {
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(value, 1),
        "unit": "cells/s",
        "vs_baseline": round(value / BASELINE_CELLS_PER_SEC, 4),
        "stages": {"compile_and_warmup": round(t_compile, 4),
                   "best_of_5_runs": round(wall, 4)},
        "note": "fallback microbench; baseline anchor is an estimate",
    }
    if flops:
        out["flops"] = flops
        out["achieved_flops_per_sec"] = round(achieved, 1)
        out["utilization_vs_peak"] = round(achieved / peak, 6) \
            if peak else None
    print(json.dumps(out))


def measure_warmup_child() -> None:
    """One warmup sample for the cold-start drill: compile (or hydrate)
    the core microbench config and run it once.  The parent
    --measure-warmup spawns this twice against one executable-cache dir
    — first cold (populating it), then hydrated — and the
    executable_cache hit/miss split proves which path each child took."""
    _guard_backend()
    import jax

    from ethrex_tpu.parallel.core import compile_prove_step
    from ethrex_tpu.utils import exec_cache

    t0 = time.perf_counter()
    fn, args, _cost = compile_prove_step(log_n=15, width=64, log_blowup=2,
                                         log_final_size=5, mesh=None)
    jax.block_until_ready(fn(*args))
    warmup = time.perf_counter() - t0
    stats = exec_cache.runtime_stats()
    print(json.dumps({
        "metric": "stark_core_warmup_s",
        "value": round(warmup, 4),
        "unit": "s",
        "backend": jax.default_backend(),
        "stages": {"compile_and_warmup": round(warmup, 4)},
        "executable_cache": {k: stats.get(k) for k in
                             ("hits", "misses", "errors", "stores")},
    }))


def measure_warmup() -> None:
    """Cold-vs-hydrated warmup drill (ROADMAP item 2's yardstick): two
    child processes share one FRESH executable-cache dir — child A pays
    the full AOT compile and serializes it, child B must hydrate.  Emits
    and appends ONE record whose gateable value is the HYDRATED warmup
    (lower is better; the same-backend history gate keeps the cold-start
    win locked in) with the cold wall and the speedup alongside."""
    import tempfile

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(
            prefix="ethrex_tpu_warmup_drill_") as cache_dir:
        # the XLA persistent cache must be fresh too: an XLA-cache-hit
        # compile serializes without its jit symbols, so the cold
        # child's store would be rejected at validation and the drill
        # would measure hit-vs-hit instead of cold-vs-hydrated
        env = {"ETHREX_EXEC_CACHE_DIR": cache_dir,
               "ETHREX_JAX_CACHE_DIR": os.path.join(cache_dir, "xla")}
        cold = _attempt("--measure-warmup-child",
                        min(EXTRA_TIMEOUT, 1500), env=env) \
            or {"_err": "no output"}
        hydrated = _attempt("--measure-warmup-child",
                            min(EXTRA_TIMEOUT, 1500), env=env) \
            or {"_err": "no output"}
    cold_s = cold.get("value")
    hyd_s = hydrated.get("value")
    ok = (isinstance(cold_s, (int, float)) and cold_s > 0
          and isinstance(hyd_s, (int, float)) and hyd_s > 0)
    record = {
        "metric": "stark_core_warmup_hydrated_s",
        "value": round(float(hyd_s), 4) if ok else 0.0,
        "unit": "s",
        "backend": (hydrated.get("backend") or cold.get("backend")
                    or "unknown"),
        "warmup_s": {"cold": cold_s, "hydrated": hyd_s},
        "stages": {"warmup_cold_s": cold_s, "warmup_hydrated_s": hyd_s,
                   "drill_s": round(time.perf_counter() - t0, 4)},
        "executable_cache": {"cold": cold.get("executable_cache"),
                             "hydrated": hydrated.get("executable_cache")},
        "config": "cold-vs-hydrated warmup drill (core microbench "
                  "config, two children sharing one fresh "
                  "executable-cache dir)",
    }
    if ok:
        record["speedup_x"] = round(float(cold_s) / float(hyd_s), 2)
    else:
        record["error"] = (cold.get("_err") or hydrated.get("_err")
                           or "child produced no warmup value")
    append_history(record)
    print(json.dumps(record))


def _scaling_prove_autopsy(ndev: int, mesh) -> dict:
    """Per-kernel autopsy for one scaling child: a small FibonacciAir
    prove on the child's mesh populates per-kernel AOT compile walls
    (prover_phase_compile_seconds), measured walls (roofline), and HLO
    collective accounting (perf/hlo_introspect.py); a second,
    steady-state prove gives the wall the occupancy estimate is read
    against.  Occupancy here is the single-lane host-idle signal: the
    fraction of the prove wall spent inside the four device kernels
    (the rest is host orchestration — Merkle paths, transcript, FRI
    queries), computed through perf/occupancy.compute so the same
    interval math the parallel prover uses carries the bench number.
    BENCH_SCALING_PROVE_ROWS sizes the trace (default 128 rows)."""
    from ethrex_tpu.models import fibonacci as fib
    from ethrex_tpu.parallel import mesh as mesh_lib
    from ethrex_tpu.perf import hlo_introspect
    from ethrex_tpu.perf import occupancy as occ_mod
    from ethrex_tpu.perf.roofline import ROOFLINE
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark.prover import StarkParams

    rows = int(os.environ.get("BENCH_SCALING_PROVE_ROWS", "128"))
    air = fib.FibonacciAir()
    trace = fib.generate_trace(rows)
    pub = fib.public_inputs(trace)
    params = StarkParams(log_blowup=2, num_queries=8, log_final_size=4)
    t0 = time.perf_counter()
    stark_prover.prove(air, trace, pub, params, mesh=mesh)
    warm_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    stark_prover.prove(air, trace, pub, params, mesh=mesh)
    prove_wall = time.perf_counter() - t1

    compile_walls = _phase_compile_walls()
    mesh_label = mesh_lib.shape_label(mesh)
    suffix = "" if mesh_label == "none" else "@" + mesh_label
    intro = {(k["air"], k["kernel"]): k
             for k in hlo_introspect.REGISTRY.report()["kernels"]}
    kernels: dict = {}
    intervals = []
    acc = 0.0
    for row in ROOFLINE.report()["kernels"]:
        if row["air"] != "FibonacciAir":
            continue
        k = row["kernel"]
        wall = row.get("wallLastSeconds") or 0.0
        ir = intro.get(("FibonacciAir", k), {})
        kernels[k] = {
            "wall_s": round(wall, 6),
            "compile_s": compile_walls.get(f"FibonacciAir/{k}{suffix}"),
            "collective_ops": ir.get("collectiveOps", 0),
            "collective_bytes": ir.get("crossDeviceBytes", 0),
            "copy_ops": ir.get("copyOps", 0),
            "hbm_bytes": ir.get("hbmPeakBytes"),
        }
        if wall > 0:
            intervals.append((acc, acc + wall))
            acc += wall
    occ = occ_mod.compute(
        {"0": {"intervals": intervals, "devices": ndev}},
        devices=ndev, window=(0.0, max(prove_wall, acc)))
    return {
        "kernels": kernels,
        "occupancy": {
            "fraction": round(occ["occupancy"], 4),
            "idle_gap_s": round(occ["idleGapSeconds"], 4),
            "busy_device_s": round(occ["busyDeviceSeconds"], 4),
            "wall_s": round(occ["wallSeconds"], 4),
            "devices": ndev,
        },
        "prove_wall_s": round(prove_wall, 4),
        "prove_warmup_s": round(warm_s, 4),
        "prove_rows": rows,
    }


def measure_scaling_one() -> None:
    """One scaling sample: prove-core cells/s with the trace sharded
    across EVERY visible device, plus the per-kernel autopsy fields the
    parent's explain_scaling diff consumes ({wall, compile, collective
    ops/bytes, HBM bytes} per kernel and a device-occupancy estimate —
    docs/PERFORMANCE.md "Reading the scaling autopsy").  The parent
    sweep (--measure-scaling) controls the device count by spawning
    this in a child process with
    XLA_FLAGS=--xla_force_host_platform_device_count=N; on one device
    the headline degrades to exactly the --measure-core configuration.
    BENCH_SCALING_LOG_N sizes the fused core step (default 2^15 rows)."""
    _guard_backend()
    import jax

    from ethrex_tpu.parallel import mesh as mesh_lib
    from ethrex_tpu.parallel.core import compile_prove_step

    ndev = len(jax.devices())
    mesh = mesh_lib.make_mesh() if ndev > 1 else None
    log_n = int(os.environ.get("BENCH_SCALING_LOG_N", "15"))
    t_c0 = time.perf_counter()
    fn, args, _cost = compile_prove_step(log_n=log_n, width=64,
                                         log_blowup=2,
                                         log_final_size=5, mesh=mesh)
    jax.block_until_ready(fn(*args))
    t_compile = time.perf_counter() - t_c0
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        runs.append(time.perf_counter() - t0)
    wall = min(runs)
    value = (1 << log_n) * 64 / wall
    # the autopsy prove is additive telemetry: its failure degrades the
    # child record to the pre-autopsy shape, never kills the sample
    try:
        autopsy = _scaling_prove_autopsy(ndev, mesh)
    except Exception as exc:  # pragma: no cover - degradation path
        autopsy = {"error": f"{type(exc).__name__}: {exc}"}
    print(json.dumps({
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(value, 1),
        "unit": "cells/s",
        "devices": ndev,
        "stages": {"compile_and_warmup": round(t_compile, 4),
                   "best_of_5_runs": round(wall, 4)},
        "kernels": autopsy.get("kernels", {}),
        "occupancy": autopsy.get("occupancy", {}),
        "prove_wall_s": autopsy.get("prove_wall_s"),
        "autopsy_error": autopsy.get("error"),
    }))


def _default_ici_gbps() -> float:
    try:
        from ethrex_tpu.perf import hlo_introspect

        return hlo_introspect.ici_gbps()
    except Exception:
        return 75.0


def explain_scaling(sweep: dict, ici_gbps: "float | None" = None) -> dict:
    """Pure 1-vs-N scaling autopsy over the sweep's child records.

    ``sweep`` maps str(device_count) -> the child JSON from
    --measure-scaling-one.  The baseline is the smallest device count
    carrying kernel data, the target the largest; for each kernel the
    wall delta is attributed across the regressor classes the autopsy
    can see — estimated collective seconds (collective bytes over the
    ETHREX_ICI_GBPS interconnect anchor), compile multiplication, and
    occupancy (host-idle) drop — and the dominant regressor is named
    per kernel and for the whole target wall.  Unit-testable with
    synthetic records; returns {"error": ...} when fewer than two
    samples carry kernels."""
    gbps = float(ici_gbps) if ici_gbps else _default_ici_gbps()

    usable = {}
    for key, rec in (sweep or {}).items():
        try:
            nd = int(key)
        except (TypeError, ValueError):
            continue
        if isinstance(rec, dict) and isinstance(rec.get("kernels"), dict) \
                and rec["kernels"]:
            usable[nd] = rec
    if len(usable) < 2:
        return {"error": "need kernel data at >= 2 device counts",
                "sampled": sorted(usable)}
    base_n, tgt_n = min(usable), max(usable)
    base, tgt = usable[base_n], usable[tgt_n]

    kernels: dict = {}
    total_delta = 0.0
    total_coll_s = 0.0
    for k, trow in tgt["kernels"].items():
        brow = base["kernels"].get(k) or {}
        bw = brow.get("wall_s") or 0.0
        tw = trow.get("wall_s") or 0.0
        delta = tw - bw
        coll_bytes = float(trow.get("collective_bytes") or 0)
        coll_s = coll_bytes / (gbps * 1e9)
        bc, tc = brow.get("compile_s"), trow.get("compile_s")
        compile_ratio = round(tc / bc, 2) if bc and tc else None
        coll_share = min(1.0, coll_s / delta) if delta > 0 else 0.0
        regressor = "collectives" if delta > 0 and coll_share >= 0.5 \
            else ("wall" if delta > 0 else "none")
        pct = round(100.0 * delta / bw, 1) if bw > 0 else None
        bits = []
        if pct is not None:
            bits.append(f"{pct:+.0f}% wall")
        if delta > 0 and coll_bytes:
            bits.append(f"{100.0 * coll_share:.0f}% of delta is "
                        "collective bytes")
        if compile_ratio is not None:
            bits.append(f"compile x{compile_ratio:.1f}")
        kernels[k] = {
            "baselineWallSeconds": bw, "targetWallSeconds": tw,
            "wallDeltaSeconds": round(delta, 6), "wallDeltaPct": pct,
            "collectiveOps": trow.get("collective_ops", 0),
            "collectiveBytes": coll_bytes,
            "estCollectiveSeconds": round(coll_s, 6),
            "collectiveShareOfDelta": round(coll_share, 4),
            "compileRatio": compile_ratio,
            "regressor": regressor,
            "summary": f"{k}: " + "; ".join(bits) if bits else k,
        }
        if delta > 0:
            total_delta += delta
            total_coll_s += min(coll_s, delta)

    base_occ = ((base.get("occupancy") or {}).get("fraction"))
    tgt_occ = ((tgt.get("occupancy") or {}).get("fraction"))
    occ_drop = (base_occ - tgt_occ) \
        if isinstance(base_occ, (int, float)) \
        and isinstance(tgt_occ, (int, float)) else None

    dominant_kernel = max(
        kernels, key=lambda k: kernels[k]["wallDeltaSeconds"],
        default=None)
    if total_delta > 0 and total_coll_s / total_delta >= 0.5:
        dom_class = "collectives"
    elif occ_drop is not None and occ_drop >= 0.3:
        dom_class = "idle"
    elif total_delta > 0:
        dom_class = kernels[dominant_kernel]["regressor"] \
            if dominant_kernel else "wall"
    else:
        dom_class = "none"
    dom_summary = kernels[dominant_kernel]["summary"] \
        if dominant_kernel and total_delta > 0 else \
        f"no kernel wall regressed from {base_n} to {tgt_n} devices"

    bv, tv = base.get("value"), tgt.get("value")
    ratio = round(tv / bv, 3) \
        if isinstance(bv, (int, float)) and bv \
        and isinstance(tv, (int, float)) else None
    return {
        "baselineDevices": base_n, "targetDevices": tgt_n,
        "headline": {"baseline": bv, "target": tv,
                     "targetOverBaseline": ratio},
        "kernels": kernels,
        "occupancy": {"baseline": base_occ, "target": tgt_occ,
                      "drop": round(occ_drop, 4)
                      if occ_drop is not None else None},
        "dominant": {"kernel": dominant_kernel, "regressor": dom_class,
                     "summary": dom_summary},
        "iciGbpsAssumed": gbps,
    }


def measure_scaling() -> None:
    """Multi-device scaling sweep: prove-core cells/s at 1/2/4/8
    simulated host devices (BENCH_SCALING_DEVICES overrides the list),
    one child process per count so each run gets a fresh XLA device
    topology.  Each child also emits the per-kernel autopsy fields and
    the record carries `autopsy` = explain_scaling(sweep) — the named
    dominant regressor for the N-device wall (docs/PERFORMANCE.md
    "Reading the scaling autopsy"); the human-readable summary prints
    to stderr (stdout stays the one-JSON-line contract).  Emits — and
    appends to bench_history.jsonl — ONE record whose top-level
    `devices` / `scaling` fields exclude it from the same-backend
    history gates: different device counts are different hardware, not
    a regression signal."""
    counts = [int(c) for c in os.environ.get(
        "BENCH_SCALING_DEVICES", "1,2,4,8").split(",") if c.strip()]
    sweep = {}
    t0 = time.perf_counter()
    for nd in counts:
        env = {
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={nd}",
            "JAX_PLATFORMS": "cpu",
            "BENCH_ALLOW_CPU": "1",
        }
        res = _attempt("--measure-scaling-one",
                       min(EXTRA_TIMEOUT, 1500), env=env)
        sweep[str(nd)] = res if res is not None else {"error": "no output"}
    best = None
    for nd in counts:
        cand = sweep.get(str(nd)) or {}
        val = cand.get("value")
        if isinstance(val, (int, float)) and (best is None
                                              or val > best[1]):
            best = (nd, float(val))
    try:
        autopsy = explain_scaling(sweep)
    except Exception as exc:  # pragma: no cover - degradation path
        autopsy = {"error": f"{type(exc).__name__}: {exc}"}
    record = {
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(best[1], 1) if best else 0.0,
        "unit": "cells/s",
        "devices": best[0] if best else 0,
        "backend": "cpu",
        "scaling": sweep,
        "autopsy": autopsy,
        "stages": {"sweep_s": round(time.perf_counter() - t0, 4)},
        "config": "scaling sweep (simulated host devices: "
                  + ",".join(str(c) for c in counts)
                  + "; core log_n="
                  + os.environ.get("BENCH_SCALING_LOG_N", "15")
                  + ", autopsy prove rows="
                  + os.environ.get("BENCH_SCALING_PROVE_ROWS", "128")
                  + ")",
    }
    append_history(record)
    dom = autopsy.get("dominant") if isinstance(autopsy, dict) else None
    if isinstance(dom, dict):
        print("scaling autopsy [{}->{} devices] dominant regressor: "
              "{} — {}".format(autopsy.get("baselineDevices"),
                               autopsy.get("targetDevices"),
                               dom.get("regressor"), dom.get("summary")),
              file=sys.stderr)
        for k, row in sorted((autopsy.get("kernels") or {}).items()):
            print("  " + str(row.get("summary")), file=sys.stderr)
    print(json.dumps(record))


def build_serving_record(sweep: dict, setup_s: float = 0.0,
                         sweep_s: float = 0.0,
                         batch: dict | None = None,
                         reference_rate: float | None = None) -> dict:
    """Pure record builder for the serving sweep (unit-testable without
    a live node).  Headline value is the client-observed p99 at the
    highest sustainable offered rate (lower is better); the sustained
    rate itself rides along as a sub-config so the history gate can
    also hold the throughput direction.

    When `reference_rate` is set and the sweep sustains beyond it, the
    headline p99 is taken at the gentlest sustained rate >= the
    reference instead: tail latency is only comparable across history
    at equal offered load, so a server that newly sustains 10x the old
    ceiling must not see its p99 gate judged at the new ceiling while
    the baseline was judged at the old one.  The throughput direction
    is held by the serving_sustained_tps sub-config either way.

    `batch`, when provided, is the JSON-RPC batch-array stage summary
    (offered rate, per-array p99 and the server-side
    rpc_batch_requests_total delta) and rides along unchanged."""
    reports = sweep.get("rates") or []
    sustained = sweep.get("maxSustainableRate")
    pick = None
    for rep in reports:
        if sustained is not None and rep.get("offeredRate") == sustained:
            pick = rep
    if (pick is not None and reference_rate is not None
            and sustained is not None and sustained > reference_rate):
        at_ref = [r for r in reports
                  if reference_rate <= r.get("offeredRate", 0) <= sustained]
        if at_ref:
            pick = min(at_ref, key=lambda r: r.get("offeredRate", 0))
    if pick is None and reports:
        pick = reports[0]   # nothing sustained: report the gentlest rate
    lat = (pick or {}).get("latency") or {}
    stages = {"setup_s": round(setup_s, 4), "sweep_s": round(sweep_s, 4)}
    record = {
        "metric": "serving_rpc_p99_seconds",
        # accepted-request p99 only: shed responses live in a separate
        # histogram, so fast rejections cannot flatter this gate
        "value": round(lat.get("p99") or 0.0, 6),
        "unit": "s",
        "sustained_rate": sustained if sustained is not None else 0.0,
        "shed_rate": (pick or {}).get("shedRate", 0.0),
        "arrivals": sweep.get("arrivals"),
        # the simulated-sender population the sweep ran with: tail
        # latency at 16 senders and at 10k senders are different
        # benchmarks, so the history gate can tell them apart
        "senders": sweep.get("senders"),
        "rates": [{
            "offeredRate": r.get("offeredRate"),
            "achievedRate": r.get("achievedRate"),
            "errorRate": r.get("errorRate"),
            "missed": r.get("missed"),
            "shed": r.get("shed"),
            "shedRate": r.get("shedRate"),
            "p50": (r.get("latency") or {}).get("p50"),
            "p95": (r.get("latency") or {}).get("p95"),
            "p99": (r.get("latency") or {}).get("p99"),
        } for r in reports],
        "stages": stages,
        "backend": "cpu",   # serving is host-side, chip-independent
        "configs": {"serving_rate": {
            "metric": "serving_sustained_tps",
            "value": float(sustained) if sustained else 0.0,
            "unit": "req/s",
        }},
        "config": "open-loop JSON-RPC serving sweep (loadgen Harness, "
                  "real TCP, tx mix, producer thread)",
    }
    if batch is not None:
        record["batch"] = batch
    return record


def measure_serving() -> None:
    """Serving-tail bench: an in-process node behind a real TCP
    RpcServer, a block-producer thread, and the open-loop loadgen
    Harness swept over ≥2 offered rates (BENCH_SERVING_RATES).  Appends
    its own history record — serving is host-side like mgas, so a
    standalone run should still leave a gateable line."""
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.perf import loadgen
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.rpc.server import RpcServer

    # the asyncio front door sustains hundreds-to-thousands of req/s on
    # one core, so the default sweep probes the new regime (the old
    # thread-per-connection server toppled past ~30)
    rates = [float(r) for r in os.environ.get(
        "BENCH_SERVING_RATES", "30,100,300,1000").split(",") if r.strip()]
    duration = float(os.environ.get("BENCH_SERVING_DURATION", "3.0"))
    arrivals = os.environ.get("BENCH_SERVING_ARRIVALS", "poisson")
    senders = int(os.environ.get("BENCH_SERVING_SENDERS", "16"))
    batch_rate = float(os.environ.get("BENCH_SERVING_BATCH_RATE", "100"))
    batch_size = int(os.environ.get("BENCH_SERVING_BATCH_SIZE", "8"))
    # the p99 history gate holds at this offered rate (the old serving
    # ceiling) so tail latency is compared at equal load across records
    reference = float(os.environ.get("BENCH_SERVING_REFERENCE_RATE", "30"))

    root = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(loadgen.DEFAULT_KEY))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + root.hex(): {"balance": hex(10**24)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    server = RpcServer(node, port=0).start()
    stop = threading.Event()

    def producer():
        while not stop.is_set():
            try:
                node.produce_block()
            except Exception:
                pass
            stop.wait(0.3)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
        harness = loadgen.Harness(
            f"http://127.0.0.1:{server.port}", key=loadgen.DEFAULT_KEY,
            senders=senders, payload="tx")
        t0 = time.perf_counter()
        harness.setup()
        setup_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        sweep = harness.sweep(rates, duration=duration, arrivals=arrivals)
        sweep_s = time.perf_counter() - t1
        # batch-array stage: one scheduled slot = one JSON-RPC array of
        # `batch_size` reads, dispatched concurrently server-side.  The
        # server and bench share a process, so the METRICS counter
        # delta proves the batch path (not per-request fallback) served
        # the arrays.
        from ethrex_tpu.utils.metrics import METRICS
        t2 = time.perf_counter()
        before = METRICS.snapshot()["counters"]
        batch_rep = loadgen.Harness(
            f"http://127.0.0.1:{server.port}", payload="batch",
            batch_size=batch_size).run(batch_rate, duration, arrivals)
        after = METRICS.snapshot()["counters"]
        batch_s = time.perf_counter() - t2
        batch = {
            "offeredRate": batch_rep["offeredRate"],
            "achievedRate": batch_rep["achievedRate"],
            "batchSize": batch_size,
            "errorRate": batch_rep["errorRate"],
            "shedRate": batch_rep.get("shedRate", 0.0),
            "p99": (batch_rep.get("latency") or {}).get("p99"),
            "rpc_batch_requests_total": (
                after.get("rpc_batch_requests_total", 0.0)
                - before.get("rpc_batch_requests_total", 0.0)),
            "rpc_batch_entries_total": (
                after.get("rpc_batch_entries_total", 0.0)
                - before.get("rpc_batch_entries_total", 0.0)),
        }
    finally:
        stop.set()
        thread.join(timeout=5)
        server.stop()
        node.stop()
    record = build_serving_record(sweep, setup_s, sweep_s, batch=batch,
                                  reference_rate=reference)
    # every measure_* names its stage breakdown inline (tooling lint)
    record.update({"stages": {"setup_s": round(setup_s, 4),
                              "sweep_s": round(sweep_s, 4),
                              "batch_s": round(batch_s, 4)}})
    append_history(record)
    print(json.dumps(record))


def build_inclusion_record(runs: list, queues: dict | None = None,
                           explain: dict | None = None,
                           setup_s: float = 0.0,
                           sweep_s: float = 0.0) -> dict:
    """Pure record builder for the inclusion sweep (unit-testable
    without a live node).  Headline value is the best included-tps
    among offered rates whose run stayed healthy (errors under
    MAX_ERROR_RATE — typed sheds/rejections are NOT errors: admission
    control refusing the overflow is exactly how the best rate is
    found); falls back to the best overall when nothing stayed clean.
    Higher is better.  Per-stage chain-path queue stats and the
    explain_chain_path verdict ride along so a regression in the gate
    comes with its own autopsy."""
    from ethrex_tpu.perf.loadgen import MAX_ERROR_RATE

    rows = []
    for run in runs or []:
        rep = run.get("report") or {}
        rows.append({
            "offeredRate": rep.get("offeredRate"),
            "achievedRate": rep.get("achievedRate"),
            "errorRate": rep.get("errorRate"),
            "shed": rep.get("shed"),
            "shedRate": rep.get("shedRate"),
            "rejected": rep.get("rejected"),
            "rejectionRate": rep.get("rejectionRate"),
            "rejections": rep.get("rejections"),
            "missed": rep.get("missed"),
            "blocks": run.get("blocks"),
            "txsIncluded": run.get("txsIncluded"),
            "includedTps": run.get("includedTps"),
        })
    healthy = [r["includedTps"] for r in rows
               if isinstance(r.get("includedTps"), (int, float))
               and (r.get("errorRate") or 0.0) <= MAX_ERROR_RATE]
    any_tps = [r["includedTps"] for r in rows
               if isinstance(r.get("includedTps"), (int, float))]
    best = max(healthy) if healthy else (max(any_tps) if any_tps else 0.0)
    return {
        "metric": "block_inclusion_tps",
        "value": round(best, 3),
        "unit": "tx/s",
        "rates": rows,
        "stages": {"setup_s": round(setup_s, 4),
                   "sweep_s": round(sweep_s, 4)},
        # chain-path stage-queue stats at sweep end: where the backlog
        # sat when the offered load outran inclusion
        "queues": queues,
        "explain": explain,
        "backend": "cpu",   # inclusion is host-side, chip-independent
        "config": "open-loop block-inclusion sweep (loadgen Harness, "
                  "real TCP, dev producer, chain-path stage queues)",
    }


def measure_inclusion() -> None:
    """Block-inclusion throughput bench (docs/PERFORMANCE.md "Reading
    the inclusion bench"): an in-process node behind a real TCP
    RpcServer with the dev producer running, swept with sustained
    offered tx load at several rates (ETHREX_INCLUSION_RATES).  Each
    rate reports included-tps (sealed-block tx count over the rate's
    wall, drain grace included) with shed/rejection accounting; the
    chain-path stage queues and explain_chain_path() verdict ride
    along.  Appends a block_inclusion_tps history record (higher is
    better) for the --check-regression gate."""
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.perf import loadgen
    from ethrex_tpu.perf.chain_path import CHAIN_PATH, explain_chain_path
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.rpc.server import RpcServer

    rates = [float(r) for r in os.environ.get(
        "ETHREX_INCLUSION_RATES", "50,150,400").split(",") if r.strip()]
    duration = float(os.environ.get("ETHREX_INCLUSION_DURATION", "3.0"))
    arrivals = os.environ.get("ETHREX_INCLUSION_ARRIVALS", "poisson")
    senders = int(os.environ.get("ETHREX_INCLUSION_SENDERS", "32"))
    block_time = float(os.environ.get("ETHREX_INCLUSION_BLOCK_TIME",
                                      "0.25"))

    root = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(loadgen.DEFAULT_KEY))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + root.hex(): {"balance": hex(10**24)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    server = RpcServer(node, port=0).start()
    stop = threading.Event()

    def producer():
        # the real dev-producer shape: build only when txs wait, at a
        # fixed block time (prewarm off — the bench wants the bare
        # chain-path service rate, not cache-warming variance)
        while not stop.is_set():
            try:
                if len(node.mempool):
                    node.produce_block()
            except Exception:
                pass
            stop.wait(block_time)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    runs = []
    try:
        harness = loadgen.Harness(
            f"http://127.0.0.1:{server.port}", key=loadgen.DEFAULT_KEY,
            senders=senders, payload="tx")
        t0 = time.perf_counter()
        harness.setup()
        CHAIN_PATH.reset()   # measure the sweep, not the funding setup
        setup_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        for rate in sorted(rates):
            blocks0 = node.store.latest_number()
            txs0 = CHAIN_PATH.txs_included
            t_rate = time.perf_counter()
            rep = harness.run(rate, duration, arrivals)
            # drain grace: give the producer a couple of block times to
            # seal what the run admitted, then measure over the full
            # wall so the tps number is conservative and honest
            stop.wait(2.0 * block_time)
            wall = time.perf_counter() - t_rate
            blocks = node.store.latest_number() - blocks0
            included = CHAIN_PATH.txs_included - txs0
            runs.append({
                "report": rep,
                "blocks": blocks,
                "txsIncluded": included,
                "includedTps": round(included / wall, 3) if wall else 0.0,
            })
        sweep_s = time.perf_counter() - t1
        # the sanitized stage view (utilization inf spelled "inf") so the
        # history record stays strict-JSON parseable
        queues = CHAIN_PATH.to_json().get("stages")
        explain = explain_chain_path(CHAIN_PATH)
        # the queue stats above are the canonical view; drop the
        # explainer's embedded copy to keep the record lean
        explain.pop("stages", None)
    finally:
        stop.set()
        thread.join(timeout=5)
        server.stop()
        node.stop()
    record = build_inclusion_record(runs, queues=queues, explain=explain,
                                    setup_s=setup_s, sweep_s=sweep_s)
    # every measure_* names its stage breakdown inline (tooling lint)
    record.update({"stages": {"setup_s": round(setup_s, 4),
                              "sweep_s": round(sweep_s, 4)}})
    append_history(record)
    print(json.dumps(record))


def measure_aggregate() -> None:
    """Aggregation-stage bench (docs/AGGREGATION.md): two small sponge
    STARKs proven as setup, then the ONE outer FriVerifyAir recursion
    proof the l2 aggregator ships to settlement — the headline number is
    the outer prove wall only.  Smaller query count than BASELINE-5 so a
    CPU-fallback run finishes honestly; appends its own history record
    so the lower-is-better gate has a line to hold."""
    _guard_backend()

    import jax

    from ethrex_tpu.models.fibonacci import FibonacciAir, generate_trace
    from ethrex_tpu.stark import aggregate as agg_mod
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark.prover import StarkParams
    from ethrex_tpu.utils import tracing

    params = StarkParams(log_blowup=2, num_queries=2, log_final_size=4)
    outer = StarkParams(log_blowup=3, num_queries=8, log_final_size=4)
    t0 = time.perf_counter()
    airs, proofs = [], []
    for i in range(2):
        air = FibonacciAir()
        trace = generate_trace(16, a0=1, b0=2 + i)
        pub = [1, 2 + i, int(trace[-1, 1])]
        proofs.append(stark_prover.prove(air, trace, pub, params))
        airs.append(air)
    inner_s = time.perf_counter() - t0
    # warm-up aggregation compiles the outer AIR's phase programs, so
    # the timed prove is steady-state, not XLA compile (same reason
    # BASELINE-5 warms up — run-to-run comparability for the gate)
    t1 = time.perf_counter()
    agg_mod.aggregate(airs, proofs, params, outer)
    warmup_s = time.perf_counter() - t1
    t2 = time.perf_counter()
    with tracing.span("bench.prove") as bench_span:
        agg = agg_mod.aggregate(airs, proofs, params, outer)
    wall = time.perf_counter() - t2
    agg_mod.verify_aggregated(airs, agg, params, outer)
    record = {
        "metric": "aggregate_prove_wall_s", "value": round(wall, 3),
        "unit": "s",
        "inner_proofs": len(proofs),
        "stages": {"inner_prove_s": round(inner_s, 3),
                   "warmup_s": round(warmup_s, 3),
                   **_span_stages(bench_span)},
        "backend": jax.default_backend(),
        "config": "2 Fibonacci STARKs -> one outer recursion proof "
                  "(differential-test outer params, 8 queries)",
    }
    append_history(record)
    print(json.dumps(record))


def measure_settle() -> None:
    """Settlement-amortization bench (docs/AGGREGATION.md): the same
    exec-proven mini L2 run settled two ways — drip per-batch (the live
    proof_send_interval pattern: one L1 verify tx per proven batch) vs
    the aggregation pipeline (ONE L1 tx for the run) — reporting settled
    proofs per L1 verification tx.  Host-side like mgas: the exec prover
    just replays batches, no chip involved."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.l2.l1_client import InMemoryL1
    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover import protocol
    from ethrex_tpu.prover.client import ProverClient

    batches = int(os.environ.get("BENCH_SETTLE_BATCHES", "6"))
    exec_t = protocol.PROVER_EXEC
    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }

    def run(aggregation: bool) -> tuple[InMemoryL1, int, dict]:
        """Commit, prove (real TCP), settle; returns the L1, the number
        of settlement L1 txs, and the phase timings."""
        node = Node(Genesis.from_json(genesis))
        l1 = InMemoryL1([exec_t])
        seq = Sequencer(node, l1, SequencerConfig(
            needed_prover_types=(exec_t,),
            aggregation_enabled=aggregation,
            aggregation_min_batches=2,
            aggregation_max_batches=max(2, batches)))
        seq.coordinator.start()
        client = ProverClient(exec_t,
                              [("127.0.0.1", seq.coordinator.port)],
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=0)
        settle_txs = 0
        try:
            t0 = time.perf_counter()
            for n in range(batches):
                tx = Transaction(
                    tx_type=2, chain_id=1337, nonce=n,
                    max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                    gas_limit=21_000, to=bytes([0x51]) * 20, value=100 + n,
                ).sign(secret)
                node.submit_transaction(tx)
                seq.produce_block()
                assert seq.commit_next_batch() is not None
            commit_s = time.perf_counter() - t0
            t1 = time.perf_counter()
            deadline = time.time() + 60.0
            for n in range(1, batches + 1):
                while seq.rollup.get_proof(n, exec_t) is None:
                    if time.time() > deadline:
                        raise RuntimeError(f"batch {n} never proven")
                    client.poll_once()
                if not aggregation:
                    # the live drip: one send_proofs per proven batch
                    if seq.send_proofs() is not None:
                        settle_txs += 1
            prove_s = time.perf_counter() - t1
            t2 = time.perf_counter()
            if aggregation:
                while seq.aggregate_proofs() is not None:
                    settle_txs += 1
            settle_s = time.perf_counter() - t2
        finally:
            seq.stop()
            node.stop()
        assert l1.last_verified_batch() == batches, \
            f"only {l1.last_verified_batch()}/{batches} settled"
        return l1, settle_txs, {"commit_s": round(commit_s, 4),
                                "prove_s": round(prove_s, 4),
                                "settle_s": round(settle_s, 4)}

    l1_pb, txs_pb, t_pb = run(aggregation=False)
    l1_ag, txs_ag, t_ag = run(aggregation=True)
    per_batch_ratio = batches / max(1, txs_pb)
    agg_ratio = l1_ag.proofs_settled_aggregated / max(
        1, l1_ag.aggregated_settlements)
    record = {
        "metric": "settled_proofs_per_l1_tx",
        "value": round(agg_ratio, 3),
        "unit": "proofs/tx",
        "batches": batches,
        "aggregated_l1_txs": txs_ag,
        "per_batch_l1_txs": txs_pb,
        "per_batch_proofs_per_tx": round(per_batch_ratio, 3),
        "amortization_x": round(agg_ratio / max(per_batch_ratio, 1e-9), 2),
        "stages": {"per_batch": t_pb, "aggregated": t_ag},
        "backend": "cpu",   # exec replay is host-side, chip-independent
        "config": f"{batches}-batch exec pipeline, drip per-batch vs "
                  "aggregated settlement (real TCP provers)",
    }
    append_history(record)
    print(json.dumps(record))


def _attempt(flag: str, timeout: int,
             env: dict | None = None) -> dict | None:
    try:
        proc = subprocess.run(
            [sys.executable, BENCH_PATH, flag],
            capture_output=True, text=True, timeout=timeout,
            cwd=_REPO_ROOT,
            env={**os.environ, **env} if env else None)
    except subprocess.TimeoutExpired:
        return {"_err": f"timeout {timeout}s"}
    line = ""
    for cand in reversed(proc.stdout.strip().splitlines()):
        if cand.startswith("{"):
            line = cand
            break
    if proc.returncode == 0 and line:
        try:
            return json.loads(line)
        except ValueError:
            return {"_err": "unparseable output"}
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"_err": f"rc={proc.returncode} " + " | ".join(tail[-3:])[:400]}


EXTRA_TIMEOUT = int(os.environ.get("BENCH_EXTRA_TIMEOUT", "2700"))


def _extra_configs() -> dict:
    """BASELINE configs 2/4/5 (and 3 with BENCH_FULL=1), each in its own
    child attempt; failures are recorded, not fatal."""
    out = {}
    flags = [("2", "--measure-2"), ("4", "--measure-4"),
             ("5", "--measure-5")]
    if os.environ.get("BENCH_FULL") == "1":
        flags.append(("3", "--measure-3"))
    for name, flag in flags:
        probe_err = probe_backend_error()
        if probe_err is not None:
            out[name] = {"error": "backend probe failed",
                         "detail": probe_err}
            continue
        res = _attempt(flag, EXTRA_TIMEOUT)
        out[name] = res if res is not None else {"error": "no output"}
    return out


def _mgas_config() -> dict:
    """The L1-side number (host CPU, chip-independent)."""
    res = _attempt("--measure-mgas", min(EXTRA_TIMEOUT, 1200))
    return res if res is not None else {"error": "no output"}


def _core_config() -> dict:
    """The prove-core cells/s microbench as a sub-record, so every suite
    run (chip or CPU fallback) leaves a gateable kernel-throughput
    number in the history."""
    res = _attempt("--measure-core", min(EXTRA_TIMEOUT, 1500))
    return res if res is not None else {"error": "no output"}


# ---------------------------------------------------------------------------
# append-only history

def append_history(record: dict) -> None:
    """One JSON line per final bench record (ts + backend + the full
    record including sub-configs).  Append-only so the perf trajectory
    survives .bench_last.json overwrites; never raises — a read-only
    checkout must not break the bench."""
    try:
        entry = dict(record)
        entry.setdefault("ts", time.time())
        with open(HISTORY_PATH, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except Exception:
        pass


def _read_history() -> list[dict]:
    out: list[dict] = []
    try:
        with open(HISTORY_PATH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # a torn append must not kill the gate
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _history_series(metric: str) -> list[tuple[str, float]]:
    """Chronological (backend, value) pairs for one metric, pulled from
    top-level records and their sub-configs.  Degraded records are
    replays of old numbers, not measurements — excluded."""
    series: list[tuple[str, float]] = []
    for rec in _read_history():
        if rec.get("degraded"):
            continue
        # multi-device scaling sweeps are a different hardware config:
        # gating a 1-device record against an 8-device one (or vice
        # versa) would compare apples to oranges, so any record carrying
        # a scaling sweep or a non-1 devices field stays out of the
        # same-backend series entirely
        if rec.get("scaling") is not None \
                or rec.get("devices") not in (None, 1):
            continue
        backend = rec.get("backend") or "unknown"
        candidates = [rec]
        cfgs = rec.get("configs")
        if isinstance(cfgs, dict):
            candidates += [c for c in cfgs.values() if isinstance(c, dict)]
        for cand in candidates:
            if (cand.get("metric") == metric
                    and isinstance(cand.get("value"), (int, float))
                    and cand["value"] > 0):
                series.append((backend, float(cand["value"])))
    return series


# ---------------------------------------------------------------------------
# CI regression gate

REGRESSION_THRESHOLD = float(
    os.environ.get("BENCH_REGRESSION_THRESHOLD", "0.8"))


def check_regression(current: dict | None = None,
                     baseline: dict | None = None,
                     threshold: float = REGRESSION_THRESHOLD) -> int:
    """CI gate: compare a fresh mgas run against the cached
    .bench_last.json record.  Exit code 2 when current/baseline drops
    below `threshold` (default 0.8, i.e. a >20% regression); 0 when OK
    or when there is no baseline yet; 1 when the current measurement
    itself failed.  Prints one JSON line either way."""
    if current is None:
        current = _mgas_config()
    if baseline is None:
        try:
            with open(LAST_PATH) as f:
                baseline = json.load(f).get("configs", {}).get("mgas", {})
        except (OSError, ValueError):
            baseline = {}
    cur = current.get("value") if isinstance(current, dict) else None
    base = baseline.get("value") if isinstance(baseline, dict) else None
    out = {"metric": "mgas_regression_check", "current": cur,
           "baseline": base, "threshold": threshold}
    if not isinstance(cur, (int, float)) or cur <= 0:
        out["status"] = "error"
        out["detail"] = current.get("error", "no current measurement") \
            if isinstance(current, dict) else "no current measurement"
        print(json.dumps(out))
        return 1
    if not isinstance(base, (int, float)) or base <= 0:
        out["status"] = "no-baseline"
        print(json.dumps(out))
        return 0
    out["ratio"] = cur / base
    out["status"] = "regression" if out["ratio"] < threshold else "ok"
    print(json.dumps(out))
    return 2 if out["status"] == "regression" else 0


def check_history_metric(metric: str,
                         threshold: float = REGRESSION_THRESHOLD,
                         lower_is_better: bool = False) -> int:
    """Gate one metric on its last two SAME-BACKEND history entries (a
    chip number must never be judged against a CPU-fallback number).
    For lower-is-better metrics (wall-clock) the ratio is inverted so
    `ratio < threshold` always means "got worse".  Exit code 2 on
    regression, else 0 (including no/insufficient history)."""
    series = _history_series(metric)
    out: dict = {"metric": f"{metric}_regression_check",
                 "threshold": threshold}
    if not series:
        out["status"] = "no-baseline"
        print(json.dumps(out))
        return 0
    backend = series[-1][0]
    same = [v for b, v in series if b == backend]
    out["backend"] = backend
    if len(same) < 2:
        out["status"] = "no-baseline"
        out["detail"] = f"fewer than two {backend} records in history"
        print(json.dumps(out))
        return 0
    cur, base = same[-1], same[-2]
    out["current"] = cur
    out["baseline"] = base
    out["ratio"] = (base / cur) if lower_is_better else (cur / base)
    out["status"] = "regression" if out["ratio"] < threshold else "ok"
    print(json.dumps(out))
    return 2 if out["status"] == "regression" else 0


def check_regression_suite(threshold: float = REGRESSION_THRESHOLD) -> int:
    """The full --check-regression gate: live mgas vs .bench_last.json
    (the original check), plus same-backend history gates on the prover
    numbers — headline wall (lower is better) and prove-core cells/s —
    and on `l1_import_mgas_per_sec` itself, so import-path wins hold
    even when no chip record is cached (the legacy cache gate only sees
    chip runs).  One JSON line per check; exit code is the worst
    individual code (2 regression > 1 error > 0 ok)."""
    codes = [
        check_regression(threshold=threshold),
        check_history_metric("transfer_batch_prove_wall_s",
                             threshold=threshold, lower_is_better=True),
        check_history_metric("stark_prove_core_trace_cells_per_sec",
                             threshold=threshold),
        check_history_metric("l1_import_mgas_per_sec",
                             threshold=threshold),
        # serving-tail gates (fed by --measure-serving records): client-
        # observed p99 must not balloon, sustained rate must not collapse
        check_history_metric("serving_rpc_p99_seconds",
                             threshold=threshold, lower_is_better=True),
        check_history_metric("serving_sustained_tps",
                             threshold=threshold),
        # aggregation gates (fed by --measure-aggregate / --measure-settle
        # records): the outer recursion prove must not slow down, and the
        # N->1 settlement amortization must not collapse
        check_history_metric("aggregate_prove_wall_s",
                             threshold=threshold, lower_is_better=True),
        check_history_metric("settled_proofs_per_l1_tx",
                             threshold=threshold),
        # cold-start gate (fed by --measure-warmup records): the
        # hydrated second-process warmup must stay collapsed — growth
        # here means the executable cache stopped hydrating
        check_history_metric("stark_core_warmup_hydrated_s",
                             threshold=threshold, lower_is_better=True),
        # chain-path gate (fed by --measure-inclusion records): the
        # end-to-end block-inclusion throughput must not collapse —
        # this holds the whole admit→select→execute→include pipeline,
        # not just the RPC front door the serving gates watch
        check_history_metric("block_inclusion_tps",
                             threshold=threshold),
    ]
    if 2 in codes:
        return 2
    return max(codes)


# ---------------------------------------------------------------------------
# top-level suite

def _publish(result: dict, cpu_fallback: bool) -> None:
    """Attach sub-configs + backend tag, persist, and print the one
    final JSON line.  Only chip records feed the .bench_last.json
    degraded-replay cache; EVERY record lands in the history."""
    if cpu_fallback:
        result["backend"] = "cpu"
        if os.environ.get("BENCH_SKIP_EXTRAS") != "1":
            # chip-bound extras (2/4/5) are pointless on CPU; the
            # L1-side mgas number is chip-independent, and the core
            # microbench keeps the kernel-throughput history alive
            result["configs"] = {"mgas": _mgas_config(),
                                 "core": _core_config()}
    else:
        result.setdefault("backend", detect_backend() or "chip")
        if os.environ.get("BENCH_SKIP_EXTRAS") != "1":
            result["configs"] = _extra_configs()
            result["configs"]["mgas"] = _mgas_config()
            result["configs"]["core"] = _core_config()
        # only chip records feed the degraded-replay cache
        try:
            with open(LAST_PATH, "w") as f:
                json.dump(result, f)
        except OSError:
            pass
    append_history(result)
    print(json.dumps(result))


def main() -> None:
    cpu_fallback = False
    if (os.environ.get("BENCH_ALLOW_CPU") != "1"
            and detect_backend() == "cpu"):
        # CPU-only host: the tunnel is ABSENT, not flaky — the chip probe
        # can never pass, and retrying it three times only produces a
        # degraded record with no number at all.  Run the same headline
        # pipeline on CPU instead, tagged backend=cpu so the record is
        # never mistaken for (or cached as) a chip measurement.
        os.environ["BENCH_ALLOW_CPU"] = "1"
        cpu_fallback = True
    last_err = ""
    for attempt in range(ATTEMPTS):
        probe_err = probe_backend_error()
        if probe_err is not None:
            last_err = (f"attempt {attempt + 1}: backend probe failed "
                        f"({probe_err})")
            time.sleep(10)
            continue
        result = _attempt("--measure", ATTEMPT_TIMEOUT)
        if result is not None and "_err" not in result:
            _publish(result, cpu_fallback)
            return
        last_err = f"attempt {attempt + 1}: {result.get('_err', '?')}"
        time.sleep(10)
    # dead-tunnel fallback: a present-but-BROKEN plugin makes
    # detect_backend() return None (so the CPU-only branch above never
    # fired) while every chip probe fails.  If a forced-CPU child works,
    # the host can still produce real prover numbers — run the headline
    # pipeline forced to CPU rather than publishing value: 0.0.
    if not cpu_fallback and probe_cpu_error() is None:
        os.environ["BENCH_ALLOW_CPU"] = "1"
        result = _attempt("--measure", ATTEMPT_TIMEOUT)
        if result is not None and "_err" not in result:
            result["fallback_reason"] = last_err
            _publish(result, cpu_fallback=True)
            return
        last_err = (f"forced-CPU fallback: {result.get('_err', '?')} "
                    f"(after {last_err})")
    # live fallback: the core microbench before any cached degradation
    if probe_backend():
        result = _attempt("--measure-core", min(ATTEMPT_TIMEOUT, 1500))
        if result is not None and "_err" not in result:
            result["degraded"] = True
            result["error"] = last_err
            append_history(result)
            print(json.dumps(result))
            return
    result = {
        "metric": "transfer_batch_prove_wall_s",
        "value": 0.0,
        "unit": "s",
        "vs_baseline": 0.0,
    }
    try:
        with open(LAST_PATH) as f:
            cached = json.load(f)
        # never replay a cached record of a different metric (e.g. the
        # retired cells/s line with its estimated-anchor vs_baseline)
        if cached.get("metric") == result["metric"]:
            result = cached
    except (OSError, ValueError):
        pass
    result["degraded"] = True
    result["error"] = last_err
    if os.environ.get("BENCH_SKIP_EXTRAS") != "1":
        # the L1-side number needs no chip: measure it even degraded
        result.setdefault("configs", {})["mgas"] = _mgas_config()
    append_history(result)
    print(json.dumps(result))


def cli(argv: list[str] | None = None) -> None:
    """Flag dispatch for the bench.py shim (and `python -m`)."""
    argv = sys.argv if argv is None else argv
    if "--measure-core" in argv:
        measure_core()
    elif "--measure-scaling-one" in argv:
        measure_scaling_one()
    elif "--measure-scaling" in argv:
        measure_scaling()
    elif "--measure-serving" in argv:
        measure_serving()
    elif "--measure-inclusion" in argv:
        measure_inclusion()
    elif "--measure-aggregate" in argv:
        measure_aggregate()
    elif "--measure-settle" in argv:
        measure_settle()
    elif "--measure-mgas" in argv:
        measure_mgas()
    elif "--measure-2" in argv:
        measure_config2()
    elif "--measure-3" in argv:
        measure_config3()
    elif "--measure-4" in argv:
        measure_config4()
    elif "--measure-5" in argv:
        measure_config5()
    elif "--measure-warmup-child" in argv:
        measure_warmup_child()
    elif "--measure-warmup" in argv:
        measure_warmup()
    elif "--measure" in argv:
        measure()
    elif "--check-regression" in argv:
        sys.exit(check_regression_suite())
    else:
        main()


if __name__ == "__main__":
    cli()
