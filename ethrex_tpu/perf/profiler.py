"""Continuous stage-attribution profiler (docs/PERFORMANCE.md).

One process-wide accumulator keyed (component, stage): every timed leg
of the prover and the L1 import path lands here, either directly
(``record_stage`` from the import/EVM/trie hot paths, which pre-date
tracing spans at that granularity) or through the tracing observer
installed below (the existing block_until_ready-bounded prover stage
spans flow in with zero changes to the prover).

Components in the stock build:

- ``stark``    — the DEEP-FRI phase stages (trace_lde, merkle_commit,
                 quotient, openings, fri_fold, query)
- ``prover``   — TpuBackend's coarse pipeline stages (execute,
                 state_proof, vm_circuits, binding, aggregate,
                 groth16_wrap)
- ``l1_import``— execute / merkleize / store_write legs of add_block
                 and the pipelined importer
- ``evm``      — sig_recovery vs opcode_loop split inside execute_tx
- ``trie``     — sorted bulk commit (build_from_sorted)

Contract: ``record`` is a dict update under one lock (~1us) and NEVER
raises; with nothing recording the profiler costs nothing.  The
``jax.profiler`` capture is opt-in via ``configure()`` /
``ETHREX_PROFILE_DIR`` and equally never-raise — a broken profiler
plugin degrades to no trace file, not a failed prove.
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger("ethrex_tpu.perf")

# bound on distinct (component, stage) keys — runaway-cardinality guard
MAX_KEYS = 512

# tracing-span stage -> component for the observer (spans carry a stage
# attr but no component; the split mirrors where each span lives)
_STARK_STAGES = frozenset(
    ("trace_lde", "merkle_commit", "quotient", "openings", "fri_fold",
     "query"))
_BACKEND_STAGES = frozenset(
    ("execute", "state_proof", "vm_circuits", "binding", "aggregate",
     "groth16_wrap"))


class StageProfiler:
    """Thread-safe (component, stage) -> count/total/max/last wall-clock
    accumulator."""

    def __init__(self):
        self._lock = threading.Lock()
        # (component, stage) -> [count, total, max, last, last_ts]
        self._cells: dict[tuple[str, str], list] = {}
        self.dropped = 0

    def record(self, component: str, stage: str, seconds: float) -> None:
        try:
            key = (str(component), str(stage))
            sec = float(seconds)
            now = time.time()
            with self._lock:
                cell = self._cells.get(key)
                if cell is None:
                    if len(self._cells) >= MAX_KEYS:
                        self.dropped += 1
                        return
                    self._cells[key] = [1, sec, sec, sec, now]
                    return
                cell[0] += 1
                cell[1] += sec
                if sec > cell[2]:
                    cell[2] = sec
                cell[3] = sec
                cell[4] = now
        except Exception:
            pass

    def stage_totals(self, component: str) -> dict[str, float]:
        """{stage: total seconds} for one component (bench attribution
        takes before/after deltas of this)."""
        with self._lock:
            return {stage: cell[1]
                    for (comp, stage), cell in self._cells.items()
                    if comp == component}

    def tree(self) -> dict:
        """The attribution tree: component -> stages with count / total /
        mean / max / last / share-of-component."""
        with self._lock:
            cells = {k: list(v) for k, v in self._cells.items()}
            dropped = self.dropped
        out: dict = {}
        for (comp, stage), (count, total, mx, last, last_ts) in \
                sorted(cells.items()):
            node = out.setdefault(
                comp, {"totalSeconds": 0.0, "stages": {}})
            node["totalSeconds"] += total
            node["stages"][stage] = {
                "count": count,
                "totalSeconds": round(total, 6),
                "meanSeconds": round(total / count, 6) if count else 0.0,
                "maxSeconds": round(mx, 6),
                "lastSeconds": round(last, 6),
                "lastTs": last_ts,
            }
        for node in out.values():
            tot = node["totalSeconds"]
            node["totalSeconds"] = round(tot, 6)
            for st in node["stages"].values():
                st["share"] = round(st["totalSeconds"] / tot, 4) \
                    if tot > 0 else 0.0
        return {"components": out, "droppedKeys": dropped}

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self.dropped = 0


PROFILER = StageProfiler()


def record_stage(component: str, stage: str, seconds: float) -> None:
    """Module-level hook used by the import/EVM/trie hot paths.  Never
    raises (hot-path contract)."""
    PROFILER.record(component, stage, seconds)


def _span_observer(name, stage, seconds):
    """Fold tracing stage spans into the attribution tree.  Stage names
    unknown to the static maps land under component 'other' so a new
    span is visible the day it ships."""
    if stage in _STARK_STAGES:
        PROFILER.record("stark", stage, seconds)
    elif stage in _BACKEND_STAGES or stage.startswith("vm_circuits/"):
        # per-slice vm_circuits/<air> spans (parallel mesh proving)
        # attribute to the prover component alongside the aggregate
        PROFILER.record("prover", stage, seconds)
    else:
        PROFILER.record("other", stage, seconds)


def _install() -> None:
    from ..utils import tracing

    if _span_observer not in tracing.STAGE_OBSERVERS:
        tracing.STAGE_OBSERVERS.append(_span_observer)


try:
    _install()
except Exception:
    pass


# ---------------------------------------------------------------------------
# opt-in jax.profiler trace capture

_PROFILE_DIR: str | None = os.environ.get("ETHREX_PROFILE_DIR") or None
_TRACE_LOCK = threading.Lock()
_TRACE_ACTIVE = False


def configure(profile_dir: str | None) -> None:
    """Set (or clear, with None) the jax.profiler trace destination."""
    global _PROFILE_DIR
    _PROFILE_DIR = profile_dir or None


def configured_dir() -> str | None:
    return _PROFILE_DIR


class capture:
    """Context manager wrapping a region in a ``jax.profiler`` trace
    when a destination is configured; a transparent no-op otherwise.

    Single-flight: nested/concurrent captures degrade to no-ops (the
    profiler cannot nest traces).  Never raises — start/stop failures
    log at debug and the wrapped body always runs.
    """

    __slots__ = ("_name", "_started")

    def __init__(self, name: str = "prove"):
        self._name = name
        self._started = False

    def __enter__(self):
        global _TRACE_ACTIVE
        directory = _PROFILE_DIR
        if not directory:
            return self
        try:
            with _TRACE_LOCK:
                if _TRACE_ACTIVE:
                    return self
                _TRACE_ACTIVE = True
            self._started = True
            import jax

            os.makedirs(directory, exist_ok=True)
            jax.profiler.start_trace(directory)
            log.info("jax.profiler trace started (%s) -> %s",
                     self._name, directory)
        except Exception as exc:
            log.debug("jax.profiler start failed: %s", exc)
            if self._started:
                with _TRACE_LOCK:
                    _TRACE_ACTIVE = False
                self._started = False
        return self

    def __exit__(self, exc_type, exc, tb):
        global _TRACE_ACTIVE
        if self._started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                log.debug("jax.profiler stop failed: %s", e)
            with _TRACE_LOCK:
                _TRACE_ACTIVE = False
            self._started = False
        return False
