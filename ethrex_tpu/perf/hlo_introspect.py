"""HLO collective accounting for the compiled STARK phase programs.

The roofline registry (perf/roofline.py) answers "how fast is each
kernel vs the hardware"; this module answers the ROADMAP item-1
question it cannot: *where does the multi-device wall go*.  Each
compiled phase executable is inspected post-AOT (stark/prover.py
`_aot_phases` and the bench's fused core step) on three axes:

- **HLO text** (``as_text()`` / ``hlo_modules()``): count the
  collective/reshard ops GSPMD inserted — all-gather, all-reduce,
  reduce-scatter, collective-permute, all-to-all, plus layout
  ``copy`` ops — and estimate the bytes each moves from its result
  shape.  ``crossDeviceBytes`` sums the true collectives only; copies
  are intra-device resharding traffic and carry their own row.
- **``memory_analysis()``** (shape varies by jaxlib: an object with
  ``*_size_in_bytes`` attributes, a dict, a list of either, or None):
  the per-kernel HBM working set (arg + output + temp + alias bytes).
- **``cost_analysis()``** stays with the roofline; the two registries
  share the (air, kernel) key space so reports join.

Everything here is telemetry behind the never-raise contract: a
jaxlib that renames an API degrades to partial rows (or none), never
a failed prove.  Recorded per (air, kernel, devices), exported as
labelled gauges, reported through ethrex_perf / the monitor / the
flight recorder, and consumed by the bench's scaling autopsy
(docs/PERFORMANCE.md "Reading the scaling autopsy").
"""

from __future__ import annotations

import os
import re
import threading

from ..utils.metrics import METRICS

# taxonomy (docs/PERFORMANCE.md): the cross-device collectives GSPMD
# inserts at sharding boundaries, plus intra-device reshard copies
COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")
RESHARD_KINDS = ("copy",)

_ALL_KINDS = COLLECTIVE_KINDS + RESHARD_KINDS

_OP_RE = re.compile(
    r"\b(" + "|".join(re.escape(k) for k in _ALL_KINDS) + r")(-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# assumed cross-device interconnect bandwidth used to turn collective
# bytes into an *estimated* seconds share of a kernel wall.  Like the
# roofline peak this is a coarse, relative anchor, not a measurement:
# override with ETHREX_ICI_GBPS (GB/s) for a calibrated link.
_DEFAULT_ICI_GBPS = 75.0


def ici_gbps() -> float:
    env = os.environ.get("ETHREX_ICI_GBPS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return _DEFAULT_ICI_GBPS


def _shape_bytes(dtype: str, dims: str) -> int:
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        return 0
    total = size
    for d in dims.split(","):
        d = d.strip()
        if d:
            total *= int(d)
    return total


def hlo_text(compiled) -> str | None:
    """Best-effort HLO text of a compiled executable, tolerant of every
    jaxlib surface: ``as_text()`` (jax AOT Compiled), ``hlo_modules()``
    (lower-level executables), or None when neither answers."""
    for attr in ("as_text",):
        fn = getattr(compiled, attr, None)
        if callable(fn):
            try:
                text = fn()
                if isinstance(text, str) and text:
                    return text
            except Exception:
                pass
    fn = getattr(compiled, "hlo_modules", None)
    if callable(fn):
        try:
            parts = []
            for mod in fn() or []:
                to_string = getattr(mod, "to_string", None)
                if callable(to_string):
                    parts.append(to_string())
            if parts:
                return "\n".join(parts)
        except Exception:
            pass
    return None


def count_collectives(text) -> dict:
    """Per-op collective counts and result-shape byte estimates from one
    HLO module's text.  Async pairs (``all-gather-start`` /
    ``all-gather-done``) count once, on the start leg.  Bytes are the
    instruction's result shapes (the data the op materializes), summed;
    an unparseable line still counts the op with zero bytes."""
    out: dict = {k: {"count": 0, "bytes": 0} for k in _ALL_KINDS}
    if not isinstance(text, str):
        return out
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if m is None or m.group(2) == "-done":
            continue
        kind = m.group(1)
        cell = out[kind]
        cell["count"] += 1
        eq = line.find("=")
        lhs_end = m.start()
        region = line[eq + 1:lhs_end] if 0 <= eq < lhs_end else ""
        cell["bytes"] += sum(_shape_bytes(d, dims)
                             for d, dims in _SHAPE_RE.findall(region))
    return out


_MEM_FIELDS = {
    "argument_size_in_bytes": "argBytes",
    "output_size_in_bytes": "outputBytes",
    "temp_size_in_bytes": "tempBytes",
    "alias_size_in_bytes": "aliasBytes",
    "generated_code_size_in_bytes": "codeBytes",
}


def parse_memory_analysis(mem) -> dict:
    """Normalize any ``memory_analysis()`` shape — an object with
    ``*_size_in_bytes`` attributes (jax >= 0.4.30 AOT), a dict keyed the
    same way, a list/tuple of either (one entry per computation), or
    None — to {argBytes, outputBytes, tempBytes, aliasBytes, codeBytes,
    peakBytes} with float-or-None values.  peakBytes (the HBM working
    set estimate) is arg+output+temp+alias over whichever of those
    fields were present; absent fields stay None (partial rows, never
    an error)."""
    out: dict = {v: None for v in _MEM_FIELDS.values()}
    out["peakBytes"] = None
    if mem is None:
        return out
    entries = mem if isinstance(mem, (list, tuple)) else [mem]
    for entry in entries:
        if entry is None:
            continue
        for field, key in _MEM_FIELDS.items():
            if isinstance(entry, dict):
                v = entry.get(field)
            else:
                v = getattr(entry, field, None)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v >= 0:
                out[key] = (out[key] or 0.0) + float(v)
    working = [out[k] for k in ("argBytes", "outputBytes", "tempBytes",
                                "aliasBytes") if out[k] is not None]
    if working:
        out["peakBytes"] = float(sum(working))
    return out


def introspect(compiled) -> dict:
    """One executable -> {ops, collectiveOps, crossDeviceBytes, copyOps,
    copyBytes, memory}.  Never raises; an opaque executable yields a
    row of zeros/Nones."""
    try:
        ops = count_collectives(hlo_text(compiled))
    except Exception:
        ops = {k: {"count": 0, "bytes": 0} for k in _ALL_KINDS}
    mem = None
    try:
        fn = getattr(compiled, "memory_analysis", None)
        if callable(fn):
            mem = fn()
    except Exception:
        mem = None
    memory = parse_memory_analysis(mem)
    coll_ops = sum(ops[k]["count"] for k in COLLECTIVE_KINDS)
    coll_bytes = sum(ops[k]["bytes"] for k in COLLECTIVE_KINDS)
    return {
        "ops": ops,
        "collectiveOps": coll_ops,
        "crossDeviceBytes": coll_bytes,
        "copyOps": ops["copy"]["count"],
        "copyBytes": ops["copy"]["bytes"],
        "memory": memory,
    }


class HloIntrospectRegistry:
    """Per (air, kernel) collective/memory accounting, alongside the
    roofline's cost rows (same key space, same MAX_KEYS clamp)."""

    MAX_KEYS = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict[tuple[str, str], dict] = {}

    def record(self, air: str, kernel: str, compiled,
               devices: int = 1) -> None:
        row = introspect(compiled)
        row["devices"] = max(1, int(devices))
        key = (str(air), str(kernel))
        with self._lock:
            if key not in self._kernels \
                    and len(self._kernels) >= self.MAX_KEYS:
                return
            self._kernels[key] = row
        record_kernel_collectives(
            air, kernel, row["collectiveOps"], row["crossDeviceBytes"],
            row["memory"].get("peakBytes"))

    def lookup(self, air: str, kernel: str) -> dict | None:
        with self._lock:
            row = self._kernels.get((str(air), str(kernel)))
        return dict(row) if row else None

    def report(self) -> dict:
        """JSON report for ethrex_perf / the flight recorder.  An
        L1-only node that never compiled a kernel answers the same
        shape with an empty kernel list (degradation stub)."""
        with self._lock:
            cells = {k: dict(v) for k, v in self._kernels.items()}
        kernels = []
        for (air, kernel), row in sorted(cells.items()):
            kernels.append({
                "air": air, "kernel": kernel,
                "devices": row.get("devices", 1),
                "collectiveOps": row.get("collectiveOps", 0),
                "crossDeviceBytes": row.get("crossDeviceBytes", 0),
                "copyOps": row.get("copyOps", 0),
                "copyBytes": row.get("copyBytes", 0),
                "ops": row.get("ops", {}),
                "hbmPeakBytes":
                    (row.get("memory") or {}).get("peakBytes"),
                "memory": row.get("memory", {}),
            })
        return {"kernels": kernels, "iciGbpsAssumed": ici_gbps()}

    def reset(self) -> None:
        with self._lock:
            self._kernels.clear()


REGISTRY = HloIntrospectRegistry()


def record(air: str, kernel: str, compiled, devices: int = 1) -> None:
    """Never-raise hook (called next to roofline.record_cost from
    stark/prover._aot_phases): introspect one compiled phase program's
    HLO + memory analysis into the registry and refresh the gauges."""
    try:
        REGISTRY.record(air, kernel, compiled, devices=devices)
    except Exception:
        pass


def record_kernel_collectives(air: str, kernel: str, ops: float,
                              cross_bytes: float,
                              hbm_bytes: float | None = None) -> None:
    """Labelled gauges for one kernel's collective accounting (never
    raises: rides the AOT-compile path)."""
    try:
        labels = {"air": air, "stage": kernel}
        METRICS.set_labeled(
            "prover_kernel_collective_ops", labels, float(ops),
            help_text="Cross-device collective ops (all-gather, "
                      "all-reduce, reduce-scatter, collective-permute, "
                      "all-to-all) in the compiled STARK phase program's "
                      "HLO, per air+stage")
        METRICS.set_labeled(
            "prover_kernel_collective_bytes", labels, float(cross_bytes),
            help_text="Estimated cross-device bytes moved by the phase "
                      "program's collectives (result-shape bytes summed "
                      "over collective ops)")
        if hbm_bytes is not None:
            METRICS.set_labeled(
                "prover_kernel_hbm_bytes", labels, float(hbm_bytes),
                help_text="Per-kernel HBM working-set estimate from XLA "
                          "memory_analysis (arg+output+temp+alias bytes)")
    except Exception:
        pass


def record_collective_share(air: str, kernel: str,
                            wall_seconds: float) -> None:
    """Estimated share of one measured kernel wall spent moving
    collective bytes (bytes / ETHREX_ICI_GBPS / wall, clamped to 1) —
    the live signal behind the prover_collective_share alert.  Called
    from stark/prover next to the roofline wall hook; never raises."""
    try:
        row = REGISTRY.lookup(air, kernel)
        if row is None or not isinstance(wall_seconds, (int, float)) \
                or wall_seconds <= 0:
            return
        est_s = float(row.get("crossDeviceBytes") or 0) \
            / (ici_gbps() * 1e9)
        share = min(1.0, est_s / float(wall_seconds))
        METRICS.set_labeled(
            "prover_kernel_collective_wall_share",
            {"air": air, "stage": kernel}, share,
            help_text="Estimated fraction of the last measured kernel "
                      "wall spent in cross-device collectives "
                      "(collective bytes over ETHREX_ICI_GBPS; coarse, "
                      "relative — docs/PERFORMANCE.md)")
        METRICS.set(
            "prover_collective_wall_share", share,
            help_text="Estimated collective share of the most recently "
                      "measured kernel wall (max-interesting signal for "
                      "the prover_collective_share alert)")
    except Exception:
        pass
