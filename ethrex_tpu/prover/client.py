"""Prover pull-client: poll coordinator endpoints, prove, submit (parity
with the reference's Prover actor, crates/prover/src/prover.rs:66-242 —
request -> prove -> submit, version-gated, self-rescheduling).
"""

from __future__ import annotations

import socket
import threading
import time

from ..guest.execution import ProgramInput
from . import protocol
from .backend import ProverBackend, get_backend


class ProverClient:
    def __init__(self, backend: ProverBackend | str,
                 endpoints: list[tuple[str, int]],
                 commit_hash: str = protocol.PROTOCOL_VERSION,
                 poll_interval: float = 1.0):
        self.backend = (get_backend(backend) if isinstance(backend, str)
                        else backend)
        self.endpoints = endpoints
        self.commit_hash = commit_hash
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self.proved: list[int] = []   # batch ids proven (observability)

    # ------------------------------------------------------------------
    def poll_once(self) -> int:
        """One pass over all endpoints; returns number of batches proven."""
        proven = 0
        for host, port in self.endpoints:
            try:
                proven += self._poll_endpoint(host, port)
            except (ConnectionError, OSError, ValueError):
                continue
        return proven

    def _poll_endpoint(self, host: str, port: int) -> int:
        with socket.create_connection((host, port), timeout=30) as sock:
            protocol.send_msg(sock, {
                "type": protocol.INPUT_REQUEST,
                "commit_hash": self.commit_hash,
                "prover_type": self.backend.prover_type,
            })
            resp = protocol.recv_msg(sock)
            rtype = resp.get("type")
            if rtype == protocol.VERSION_MISMATCH:
                raise ValueError(
                    f"prover version mismatch: need {resp.get('expected')}")
            if rtype != protocol.INPUT_RESPONSE:
                return 0
            batch_id = resp["batch_id"]
            program_input = ProgramInput.from_json(resp["input"])
            proof = self.backend.prove(program_input, resp["format"])
            protocol.send_msg(sock, {
                "type": protocol.PROOF_SUBMIT,
                "batch_id": batch_id,
                "prover_type": self.backend.prover_type,
                "proof": proof,
            })
            ack = protocol.recv_msg(sock)
            if ack.get("type") == protocol.SUBMIT_ACK:
                self.proved.append(batch_id)
                return 1
            return 0

    # ------------------------------------------------------------------
    def run_forever(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — prover must keep polling
                print(f"prover poll error: {e}")

    def start(self) -> "ProverClient":
        threading.Thread(target=self.run_forever, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()


def start_prover(backend_name: str, endpoints: list[tuple[str, int]],
                 **kwargs) -> ProverClient:
    """Entry point (reference: start_prover, prover.rs:242)."""
    return ProverClient(backend_name, endpoints, **kwargs).start()
