"""Prover pull-client: poll coordinator endpoints, prove, submit (parity
with the reference's Prover actor, crates/prover/src/prover.rs:66-242 —
request -> prove -> submit, version-gated, self-rescheduling), hardened
for real fleets:

  * per-endpoint exponential backoff with jitter — a flapping coordinator
    is retried gently instead of hammered every poll;
  * a circuit breaker per endpoint — after `breaker_threshold`
    consecutive failures the endpoint is skipped entirely until a
    half-open probe after `breaker_cooldown` seconds succeeds;
  * a background heartbeat thread while `backend.prove` runs — a long
    TPU proof extends its coordinator lease instead of being reassigned;
  * submit over a fresh connection — the socket that carried the input
    request can die during a multi-minute proof without losing the
    finished proof;
  * background pre-warm before the first InputRequest — the backend's
    AOT kernels are hydrated from the on-disk executable cache
    (utils/exec_cache) while the client starts polling, and every
    InputRequest carries an advisory `warm` flag so the coordinator's
    fleet scheduler can route the first post-restart batches to
    already-hydrated provers (docs/PERFORMANCE.md "Cold start").
"""

from __future__ import annotations

import dataclasses
import logging
import random
import secrets
import socket
import threading
import time

from ..guest.execution import ProgramInput
from ..utils import faults, tracing
from . import checkpoint as ckpt_mod
from . import protocol
from . import runtime_errors as rt_mod
from .backend import ProverBackend, get_backend

log = logging.getLogger("ethrex_tpu.prover.client")


@dataclasses.dataclass
class EndpointState:
    """Per-endpoint breaker/backoff state (exposed for health checks)."""

    failures: int = 0           # consecutive
    next_attempt: float = 0.0   # monotonic backoff gate
    breaker: str = "closed"     # closed | open | half-open
    open_until: float = 0.0
    transitions: int = 0


class _HeartbeatThread(threading.Thread):
    """Best-effort lease keep-alive over short-lived connections while the
    backend proves; failures are ignored — lease expiry is the backstop."""

    def __init__(self, host: str, port: int, batch_id: int,
                 prover_type: str, interval: float,
                 lease_token: str | None = None,
                 trace_id: str | None = None,
                 prover_id: str | None = None,
                 ctx: "ckpt_mod.BatchContext | None" = None):
        super().__init__(daemon=True)
        self.host, self.port = host, port
        self.batch_id = batch_id
        self.prover_type = prover_type
        self.interval = interval
        self.lease_token = lease_token
        self.prover_id = prover_id
        # the batch context stamps each beat with the in-flight phase
        # (the coordinator re-anchors its hedging deadline on every
        # phase transition) and any mesh downgrade the degradation
        # ladder applied (the scheduler steers heavy batches away)
        self.ctx = ctx
        # when set, each beat piggybacks the spans completed so far for
        # this trace (stage spans finish while the proof runs), so a
        # prover that crashes mid-prove still leaves its partial subtree
        # at the coordinator; the payload is cumulative and the
        # coordinator deduplicates by span ID
        self.trace_id = trace_id
        self.acked = 0
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self.interval):
            try:
                msg = {
                    "type": protocol.HEARTBEAT,
                    "batch_id": self.batch_id,
                    "prover_type": self.prover_type,
                    "lease_token": self.lease_token,
                    "prover_id": self.prover_id,
                }
                if self.ctx is not None:
                    msg.update(self.ctx.snapshot())
                if self.trace_id:
                    spans = tracing.export_wire(self.trace_id)
                    if spans is not None:
                        msg["spans"] = spans
                with socket.create_connection(
                        (self.host, self.port), timeout=5) as sock:
                    protocol.send_msg(sock, msg)
                    ack = protocol.recv_msg(sock)
                if ack.get("type") == protocol.HEARTBEAT_ACK \
                        and ack.get("ok"):
                    self.acked += 1
            except (ConnectionError, OSError, ValueError):
                pass

    def stop(self):
        self._stop.set()


class ProverClient:
    def __init__(self, backend: ProverBackend | str,
                 endpoints: list[tuple[str, int]],
                 commit_hash: str = protocol.PROTOCOL_VERSION,
                 poll_interval: float = 1.0,
                 heartbeat_interval: float = 30.0,
                 backoff_base: float = 0.5,
                 backoff_max: float = 30.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown: float = 10.0,
                 rng_seed: int | None = None,
                 prover_id: str | None = None,
                 prewarm: bool = True):
        self.backend = (get_backend(backend) if isinstance(backend, str)
                        else backend)
        # advisory fleet identity: lets the coordinator's scheduler
        # attribute throughput to this prover across polls (the lease
        # token, not this, remains the authority over lease state)
        self.prover_id = prover_id if prover_id is not None else \
            f"{self.backend.prover_type}-{secrets.token_hex(4)}"
        self.endpoints = endpoints
        self.commit_hash = commit_hash
        self.poll_interval = poll_interval
        self.heartbeat_interval = heartbeat_interval
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._rng = random.Random(rng_seed)
        self._stop = threading.Event()
        self.proved: list[int] = []   # batch ids proven (observability)
        self.submit_rejections = 0    # application-level rejects (not
        #                               transport; never trips the breaker)
        self.poisoned: list[int] = []  # batches aborted as nan_poison
        # sticky mesh downgrade: once the degradation ladder demoted
        # this process, every later batch's heartbeats keep reporting
        # the floor until restart (the runtime condition — a sick slice,
        # leaked device memory — outlives any one batch)
        self.degraded: dict | None = None
        self.endpoint_states: dict[tuple[str, int], EndpointState] = {
            ep: EndpointState() for ep in endpoints}
        # pre-warm: hydrate the backend's AOT executables from the
        # on-disk cache in the background, so the first assignment can
        # run at steady-state wall; `warm` rides every InputRequest
        # (advisory, like prover_id) so the fleet scheduler can prefer
        # hydrated provers for the first batches after a restart
        self.hydrated_groups = 0
        self._prewarm_done = threading.Event()
        if prewarm:
            threading.Thread(target=self._prewarm_worker,
                             daemon=True).start()
        else:
            self._prewarm_done.set()

    def _prewarm_worker(self):
        try:
            hook = getattr(self.backend, "prewarm", None)
            if callable(hook):
                self.hydrated_groups = int(hook() or 0)
        except Exception:  # noqa: BLE001 — a failed prewarm is just cold
            log.exception("prover prewarm failed; starting cold")
        finally:
            self._prewarm_done.set()
            if self.hydrated_groups:
                log.info("prover %s prewarmed: %d kernel group(s) "
                         "hydrated from the executable cache",
                         self.prover_id, self.hydrated_groups)

    @property
    def warm(self) -> bool:
        """Whether this prover's next proof should run at steady-state
        wall: the prewarm pass finished AND it either hydrated compiled
        kernels from disk or has already proven in this process."""
        return self._prewarm_done.is_set() and (
            self.hydrated_groups > 0 or bool(self.proved))

    # ------------------------------------------------------------------
    # breaker / backoff
    # ------------------------------------------------------------------
    def _should_attempt(self, st: EndpointState, now: float) -> bool:
        if st.breaker == "open":
            if now < st.open_until:
                return False
            st.breaker = "half-open"   # one probe allowed
            st.transitions += 1
            return True
        return now >= st.next_attempt

    def _record_success(self, ep, st: EndpointState):
        if st.breaker != "closed":
            st.breaker = "closed"
            st.transitions += 1
            log.info("endpoint %s:%d recovered, breaker closed", *ep)
            self._publish_breaker(transition=True)
        st.failures = 0
        st.next_attempt = 0.0

    def _record_failure(self, ep, st: EndpointState, now: float,
                        err: Exception):
        from ..utils.metrics import record_poll_error

        record_poll_error()
        st.failures += 1
        log.warning("endpoint %s:%d poll failed (%d consecutive): %s",
                    ep[0], ep[1], st.failures,
                    f"{type(err).__name__}: {err}")
        if st.breaker == "half-open" or \
                st.failures >= self.breaker_threshold:
            st.breaker = "open"
            st.open_until = now + self.breaker_cooldown
            st.transitions += 1
            log.warning("endpoint %s:%d breaker open for %.1fs",
                        ep[0], ep[1], self.breaker_cooldown)
            self._publish_breaker(transition=True)
        else:
            # exponential backoff with jitter in [0.5x, 1x)
            delay = min(self.backoff_base * (2 ** (st.failures - 1)),
                        self.backoff_max)
            st.next_attempt = now + delay * (0.5 + self._rng.random() / 2)

    def _publish_breaker(self, transition: bool = False):
        from ..utils.metrics import record_breaker

        record_breaker(sum(1 for s in self.endpoint_states.values()
                           if s.breaker == "open"), transition=transition)

    # ------------------------------------------------------------------
    def poll_once(self) -> int:
        """One pass over all endpoints; returns number of batches proven.
        Endpoint failures are absorbed into breaker/backoff state — the
        prover never dies because a coordinator does."""
        proven = 0
        for ep in self.endpoints:
            st = self.endpoint_states.setdefault(ep, EndpointState())
            now = time.monotonic()
            if not self._should_attempt(st, now):
                continue
            try:
                proven += self._poll_endpoint(*ep)
            except Exception as e:  # noqa: BLE001 — keep polling others
                self._record_failure(ep, st, time.monotonic(), e)
            else:
                self._record_success(ep, st)
        return proven

    def _poll_endpoint(self, host: str, port: int) -> int:
        # connection 1: request work (closed before the proof starts)
        with socket.create_connection((host, port), timeout=30) as sock:
            protocol.send_msg(sock, {
                "type": protocol.INPUT_REQUEST,
                "commit_hash": self.commit_hash,
                "prover_type": self.backend.prover_type,
                "prover_id": self.prover_id,
                "warm": self.warm,
            })
            resp = protocol.recv_msg(sock)
        rtype = resp.get("type")
        if rtype == protocol.VERSION_MISMATCH:
            raise ValueError(
                f"prover version mismatch: need {resp.get('expected')}")
        if rtype != protocol.INPUT_RESPONSE:
            return 0
        batch_id = resp["batch_id"]
        lease_token = resp.get("lease_token")
        # continue the trace the coordinator opened at assignment, so the
        # whole batch lifecycle shares one trace ID across the TCP seam
        trace_id = resp.get("trace_id")
        parent_span = resp.get("span_id")
        program_input = ProgramInput.from_json(resp["input"])
        # the batch context scopes this attempt's phase checkpoints (a
        # restart with a fresh lease resumes from the last completed
        # phase) and carries the advisory state heartbeats report
        with ckpt_mod.batch_context(batch_id,
                                    lease_token=lease_token) as ctx:
            if self.degraded:
                ctx.degraded = dict(self.degraded)
            # heartbeats keep the coordinator lease alive through a
            # long proof
            hb = None
            if self.heartbeat_interval and self.heartbeat_interval > 0:
                hb = _HeartbeatThread(host, port, batch_id,
                                      self.backend.prover_type,
                                      self.heartbeat_interval,
                                      lease_token=lease_token,
                                      trace_id=trace_id,
                                      prover_id=self.prover_id,
                                      ctx=ctx)
                hb.start()
            with tracing.trace_context(trace_id, parent_span) as tid:
                try:
                    with tracing.span("prover.prove", batch=batch_id,
                                      backend=self.backend.prover_type):
                        faults.inject("backend.prove")
                        proof = self.backend.prove(program_input,
                                                   resp["format"])
                        proof = faults.inject("backend.prove", proof,
                                              kinds=("corrupt",))
                except rt_mod.NanPoisonError as poison:
                    # poisoned batch: retrying cannot help — tell the
                    # coordinator exactly which phase went non-finite so
                    # it quarantines on the FIRST attempt, and spend
                    # zero retries here
                    if hb is not None:
                        hb.stop()
                    self.poisoned.append(batch_id)
                    log.error("batch %d poisoned in phase %s; reporting "
                              "for quarantine", batch_id, poison.phase)
                    self._report_poison(host, port, batch_id,
                                        lease_token, poison)
                    return 0
                finally:
                    if hb is not None:
                        hb.stop()
                    if ctx.degraded:
                        self.degraded = dict(ctx.degraded)
                # connection 2: submit over a fresh socket — the
                # input-request connection may long since have died
                # under the proof
                with tracing.span("prover.submit", batch=batch_id) as sub:
                    # ship the completed span subtree (prove + stage
                    # spans) with the proof; the coordinator merges it
                    # into its ring so the batch renders as one
                    # cross-process trace
                    with socket.create_connection((host, port),
                                                  timeout=30) as sock:
                        protocol.send_msg(sock, {
                            "type": protocol.PROOF_SUBMIT,
                            "batch_id": batch_id,
                            "prover_type": self.backend.prover_type,
                            "proof": proof,
                            "lease_token": lease_token,
                            "prover_id": self.prover_id,
                            "trace_id": trace_id,
                            "span_id": sub.span_id if sub else None,
                            "spans": tracing.export_wire(tid),
                        })
                        ack = protocol.recv_msg(sock)
        if ack.get("type") == protocol.SUBMIT_ACK:
            # the proof is accepted: its recovery state has no further
            # value, drop the batch's checkpoints
            ckpt_mod.complete(batch_id)
            self.proved.append(batch_id)
            return 1
        # application-level rejection (invalid proof, stale token): the
        # coordinator answered fine, so the endpoint is healthy — do NOT
        # feed this into the breaker/backoff failure count; a prover with
        # a corrupt backend must not open its own breaker against a
        # perfectly good coordinator
        from ..utils.metrics import record_submit_rejected

        record_submit_rejected()
        self.submit_rejections += 1
        log.warning("submit rejected for batch %d by %s:%d: %s",
                    batch_id, host, port,
                    ack.get("message", ack.get("type")))
        return 0

    def _report_poison(self, host: str, port: int, batch_id: int,
                       lease_token: str | None,
                       poison: "rt_mod.NanPoisonError") -> None:
        """Best-effort poison report: a HEARTBEAT carrying the offending
        phase; the coordinator quarantines the batch immediately instead
        of burning its failure budget on doomed retries."""
        try:
            with socket.create_connection((host, port), timeout=5) as sock:
                protocol.send_msg(sock, {
                    "type": protocol.HEARTBEAT,
                    "batch_id": batch_id,
                    "prover_type": self.backend.prover_type,
                    "lease_token": lease_token,
                    "prover_id": self.prover_id,
                    "poison": {"phase": str(poison.phase),
                               "detail": str(poison.detail)},
                })
                protocol.recv_msg(sock)
        except (ConnectionError, OSError, ValueError):
            pass  # lease expiry is the backstop, as for normal beats

    # ------------------------------------------------------------------
    def run_forever(self):
        from ..utils.metrics import record_poll_error

        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — prover must keep polling
                record_poll_error()
                log.exception("prover poll pass failed")

    def start(self) -> "ProverClient":
        threading.Thread(target=self.run_forever, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()


def start_prover(backend_name: str, endpoints: list[tuple[str, int]],
                 **kwargs) -> ProverClient:
    """Entry point (reference: start_prover, prover.rs:242)."""
    return ProverClient(backend_name, endpoints, **kwargs).start()
