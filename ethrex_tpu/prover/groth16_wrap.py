"""Groth16 wrap circuit: bind a STARK public digest into one BN254 SNARK.

The reference's Groth16 format wraps its STARK verifier in a SNARK so L1
contracts verify one pairing equation (/root/reference/crates/prover/src/
backend/sp1.rs:97-102, OnChainProposer's ISP1Verifier seat).  Round-2
scope here: the wrap circuit proves knowledge of the aggregated STARK
digest (8 BabyBear limbs, range-checked to 31 bits) hashing under
MiMC-5/Fr to the single on-chain public input — the commitment the
settlement contract stores and the off-chain verifier cross-checks
against the STARK aggregate (stark/aggregate.py).  The circuit does NOT
yet re-verify the STARK inside the SNARK; that verifier circuit slots
into exactly this R1CS seam (documented gap, mirrors how the reference
delegates the equivalent circuit to SP1's wrapper).

MiMC-5: x -> (x + c_i)^5 for 110 rounds (x^5 is a permutation of Fr since
gcd(5, r - 1) = 1); sponge: state' = perm(state + limb) per limb, final
state is the public hash.  Constants are SHAKE-256-derived (same
reproducible-constants policy as ops/poseidon2.py).
"""

from __future__ import annotations

import hashlib

from ..crypto import groth16
from ..crypto.groth16 import R, R1CS

ROUNDS = 110
LIMBS = 8
LIMB_BITS = 31
_DOMAIN = b"ethrex-tpu/groth16-wrap/mimc5/v1"


def _constants() -> list[int]:
    out = []
    stream = hashlib.shake_256(_DOMAIN).digest(40 * ROUNDS)
    for i in range(ROUNDS):
        out.append(int.from_bytes(stream[40 * i:40 * (i + 1)], "big") % R)
    return out


CONSTANTS = _constants()


def mimc_perm(x: int) -> int:
    for c in CONSTANTS:
        x = pow((x + c) % R, 5, R)
    return x


def wrap_hash(limbs: list[int]) -> int:
    """Host mirror of the in-circuit sponge (the on-chain recomputation)."""
    if len(limbs) != LIMBS:
        raise ValueError("digest must be 8 limbs")
    state = 0
    for limb in limbs:
        state = mimc_perm((state + int(limb)) % R)
    return state


def build_wrap_r1cs():
    """The fixed wrap R1CS.  z = [1, h, limb_0..7, bits..., round vars...].

    Returns (r1cs, layout) where layout maps names to variable indices for
    witness construction.
    """
    constraints = []
    var = 2 + LIMBS          # after [1, h, limbs]
    bit_vars = []
    # range checks: limb_i = sum bits * 2^j, bits boolean
    for i in range(LIMBS):
        bits = list(range(var, var + LIMB_BITS))
        var += LIMB_BITS
        bit_vars.append(bits)
        for b in bits:
            constraints.append(({b: 1}, {b: 1}, {b: 1}))   # b*b = b
        lin = {b: (1 << j) % R for j, b in enumerate(bits)}
        constraints.append((lin, {0: 1}, {2 + i: 1}))      # sum = limb

    # sponge rounds; u = state + limb (absorb) or previous t; each round:
    #   y2 = u*u ; y4 = y2*y2 ; t = y4*u
    state_lin = {}           # linear combo representing current state
    round_vars = var
    for i in range(LIMBS):
        # absorb: u0 = state + limb_i  (linear, no constraint needed)
        carry = dict(state_lin)
        carry[2 + i] = (carry.get(2 + i, 0) + 1) % R
        for r_i, c in enumerate(CONSTANTS):
            u = dict(carry)
            u[0] = (u.get(0, 0) + c) % R
            y2, y4, t = var, var + 1, var + 2
            var += 3
            constraints.append((u, u, {y2: 1}))
            constraints.append(({y2: 1}, {y2: 1}, {y4: 1}))
            if i == LIMBS - 1 and r_i == ROUNDS - 1:
                # final round output IS the public hash variable
                constraints.append(({y4: 1}, u, {1: 1}))
                var -= 1     # t unused
            else:
                constraints.append(({y4: 1}, u, {t: 1}))
                carry = {t: 1}
        state_lin = carry
    r1cs = R1CS(num_vars=var, num_pub=1, constraints=constraints)
    layout = {"h": 1, "limbs": list(range(2, 2 + LIMBS)),
              "bit_vars": bit_vars, "round_vars": round_vars}
    return r1cs, layout


def wrap_witness(limbs: list[int], r1cs: R1CS, layout) -> list[int]:
    """Assign every variable for a digest."""
    limbs = [int(v) % R for v in limbs]
    if any(v >= (1 << LIMB_BITS) for v in limbs):
        raise ValueError("digest limbs exceed 31 bits")
    z = [0] * r1cs.num_vars
    z[0] = 1
    z[1] = wrap_hash(limbs)
    for i, v in enumerate(limbs):
        z[2 + i] = v
    for i, bits in enumerate(layout["bit_vars"]):
        for j, b in enumerate(bits):
            z[b] = (limbs[i] >> j) & 1
    var = layout["round_vars"]
    state = 0
    for i in range(LIMBS):
        u_val = (state + limbs[i]) % R
        for r_i, c in enumerate(CONSTANTS):
            u = (u_val + c) % R
            y2 = u * u % R
            y4 = y2 * y2 % R
            t = y4 * u % R
            z[var] = y2
            z[var + 1] = y4
            var += 2
            if i == LIMBS - 1 and r_i == ROUNDS - 1:
                pass          # t is the public hash (already assigned)
            else:
                z[var] = t
                var += 1
            u_val = t
        state = u_val
    assert r1cs.is_satisfied(z), "internal witness bug"
    return z


_CACHE: dict = {}


def wrap_keys(seed: bytes = b"ethrex-tpu/groth16-wrap/dev-ceremony/v1"):
    """Build (and cache) the circuit + keys — setup takes a little while
    (thousands of fixed-base scalar muls), so share per process."""
    got = _CACHE.get(seed)
    if got is None:
        r1cs, layout = build_wrap_r1cs()
        pk, vk = groth16.setup(r1cs, seed=seed)
        got = (r1cs, layout, pk, vk)
        _CACHE[seed] = got
    return got


def wrap_prove(limbs: list[int], rnd: bytes = b"") -> dict:
    """Digest limbs -> {"hash": h, "proof": groth16 proof}."""
    r1cs, layout, pk, _vk = wrap_keys()
    z = wrap_witness(limbs, r1cs, layout)
    proof = groth16.prove(pk, r1cs, z, rnd=rnd)
    return {"hash": z[1], "proof": proof}


def proof_to_json(wrapped: dict) -> dict:
    """Wire form: hex strings (arbitrary-size ints survive any JSON impl)."""
    a, b, c = (wrapped["proof"][k] for k in ("a", "b", "c"))
    return {
        "hash": hex(wrapped["hash"]),
        "a": [hex(a[0]), hex(a[1])],
        "b": [[hex(b[0].c0), hex(b[0].c1)], [hex(b[1].c0), hex(b[1].c1)]],
        "c": [hex(c[0]), hex(c[1])],
    }


def proof_from_json(d: dict) -> dict:
    from ..crypto import bn254

    def h(v):
        return int(v, 16)

    return {
        "hash": h(d["hash"]),
        "proof": {
            "a": (h(d["a"][0]), h(d["a"][1])),
            "b": (bn254.Fp2(h(d["b"][0][0]), h(d["b"][0][1])),
                  bn254.Fp2(h(d["b"][1][0]), h(d["b"][1][1]))),
            "c": (h(d["c"][0]), h(d["c"][1])),
        },
    }


def wrap_verify(wrapped: dict, limbs: list[int]) -> bool:
    """Check the SNARK and that its public hash matches the digest."""
    _r1cs, _layout, _pk, vk = wrap_keys()
    if int(wrapped.get("hash", -1)) != wrap_hash(limbs):
        return False
    return groth16.verify(vk, wrapped["proof"], [wrapped["hash"]])
