"""Prover <-> coordinator wire protocol: newline-delimited JSON over TCP
(parity with the reference's ProofData<I> protocol,
crates/common/types/prover.rs:119-159 — the plugin seam the TPU prover
slots into; message names kept equivalent).
"""

from __future__ import annotations

import json
import socket

# message types (the reference's ProofData variants)
INPUT_REQUEST = "InputRequest"          # {commit_hash, prover_type}
INPUT_RESPONSE = "InputResponse"        # {batch_id, input, format}
VERSION_MISMATCH = "VersionMismatch"    # {expected}
TYPE_NOT_NEEDED = "ProverTypeNotNeeded"
PROOF_SUBMIT = "ProofSubmit"            # {batch_id, prover_type, proof}
SUBMIT_ACK = "ProofSubmitACK"           # {batch_id}
ERROR = "Error"                         # {message}

# proof formats (reference: ProofFormat — Compressed STARK vs Groth16 wrap)
FORMAT_STARK = "stark"            # the two batch STARKs as-is
FORMAT_COMPRESSED = "compressed"  # + recursion: FRI query work aggregated
#                                   into one outer STARK, path data dropped
FORMAT_GROTH16 = "groth16"        # compressed + BN254 MiMC wrap of the
#                                   aggregate digest (one pairing on L1)

# prover types (reference: ProverType {Exec, SP1, RISC0, ...} + TPU)
PROVER_EXEC = "exec"
PROVER_TPU = "tpu"

PROTOCOL_VERSION = "ethrex-tpu/prover/v1"


def send_msg(sock: socket.socket, msg: dict):
    data = json.dumps(msg, separators=(",", ":")).encode() + b"\n"
    sock.sendall(data)


def recv_msg(sock: socket.socket, max_size: int = 256 * 1024 * 1024) -> dict:
    buf = bytearray()
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            if not buf:
                raise ConnectionError("peer closed")
            break
        buf.extend(chunk)
        if buf.endswith(b"\n"):
            break
        if len(buf) > max_size:
            raise ConnectionError("message too large")
    return json.loads(buf.decode())


def recv_msg_file(rfile, max_size: int = 256 * 1024 * 1024) -> dict | None:
    line = rfile.readline(max_size)
    if not line:
        return None
    return json.loads(line.decode())
