"""Prover <-> coordinator wire protocol: newline-delimited JSON over TCP
(parity with the reference's ProofData<I> protocol,
crates/common/types/prover.rs:119-159 — the plugin seam the TPU prover
slots into; message names kept equivalent).
"""

from __future__ import annotations

import json
import socket

from ..utils import faults

# message types (the reference's ProofData variants). The wire carries no
# AUTHENTICATED prover identity, so InputResponse issues a per-assignment
# lease_token; Heartbeat and ProofSubmit must echo it — lease mutations
# only ever act on behalf of the prover the lease was granted to.  A
# prover MAY volunteer a stable `prover_id` string on InputRequest and
# ProofSubmit: it is advisory only (never a capability — the token stays
# the sole authority), feeding the coordinator's fleet scheduler with
# per-prover throughput stats for size-aware placement, work stealing,
# and hedged re-assignment (docs/AGGREGATION.md).  InputRequest MAY also
# carry a boolean `warm`: whether this prover's AOT kernels are already
# hydrated (from the on-disk executable cache, utils/exec_cache) so its
# next proof runs at steady-state wall rather than paying a cold
# compile.  Like prover_id it is advisory — the scheduler uses it only
# to prefer warm provers for the first batches after a restart and to
# keep a cold prover's compile-inclusive first wall out of its EWMA; a
# lying prover gains nothing but a worse placement.  ProofSubmit and
# Heartbeat MAY additionally carry a `spans` object — the prover's
# completed span subtree for the batch's trace, produced by
# tracing.export_wire (bounded + size-capped + version-tagged) and
# merged by the coordinator with tracing.TRACER.ingest so one batch
# renders as one cross-process trace.  Also advisory and
# version-tolerant in both directions: old coordinators ignore the
# field, new coordinators ignore unknown payload versions, and
# ingestion never raises into lease handling
# (docs/OBSERVABILITY.md "Distributed tracing").  The heartbeat copy is
# cumulative — a prover that dies mid-prove still leaves its partial
# subtree from the last beat; the coordinator deduplicates by span ID.
# Heartbeat MAY further carry the prover runtime's advisory state
# (docs/PROVER_RESILIENCE.md "Runtime failures"): `phase` (the job-
# qualified in-flight phase, e.g. "state_proof.quotient") and
# `phase_started` (the prover's wall clock) — the coordinator re-anchors
# its hedging deadline on every observed phase TRANSITION using its own
# clock, so a proof making phase progress is never hedged as a
# straggler; `degraded` ({from, to} mesh labels) — the degradation
# ladder demoted this prover, the scheduler steers heavy batches away
# until restart; and `poison` ({phase, detail}) — the batch produced
# non-finite/out-of-field outputs in the named phase, the coordinator
# quarantines it immediately (token-gated like every lease mutation)
# instead of burning its failure budget on doomed retries.
INPUT_REQUEST = "InputRequest"          # {commit_hash, prover_type
#                                          [, prover_id] [, warm]}
INPUT_RESPONSE = "InputResponse"        # {batch_id, input, format,
#                                          lease_token}
VERSION_MISMATCH = "VersionMismatch"    # {expected}
TYPE_NOT_NEEDED = "ProverTypeNotNeeded"
PROOF_SUBMIT = "ProofSubmit"            # {batch_id, prover_type, proof,
#                                          lease_token [, prover_id]
#                                          [, spans]}
SUBMIT_ACK = "ProofSubmitACK"           # {batch_id}
ERROR = "Error"                         # {message}
# lease keep-alive: a prover mid-way through a long TPU proof extends its
# assignment instead of relying on one fixed coordinator-side timeout
HEARTBEAT = "Heartbeat"                 # {batch_id, prover_type,
#                                          lease_token [, prover_id]
#                                          [, spans] [, phase]
#                                          [, phase_started] [, degraded]
#                                          [, poison]}
HEARTBEAT_ACK = "HeartbeatAck"          # {batch_id, ok}

# proof formats (reference: ProofFormat — Compressed STARK vs Groth16 wrap)
FORMAT_STARK = "stark"            # the two batch STARKs as-is
FORMAT_COMPRESSED = "compressed"  # + recursion: FRI query work aggregated
#                                   into one outer STARK, path data dropped
FORMAT_GROTH16 = "groth16"        # compressed + BN254 MiMC wrap of the
#                                   aggregate digest (one pairing on L1)

# prover types (reference: ProverType {Exec, SP1, RISC0, ...} + TPU)
PROVER_EXEC = "exec"
PROVER_TPU = "tpu"

PROTOCOL_VERSION = "ethrex-tpu/prover/v1"


class ProtocolError(ConnectionError):
    """A frame that cannot be trusted: oversized, truncated, or not JSON.
    Subclasses ConnectionError so every existing handler that drops a bad
    connection drops a bad frame the same way."""


def _decode_frame(buf: bytes) -> dict:
    try:
        msg = json.loads(buf.decode())
    except (UnicodeDecodeError, ValueError) as e:
        raise ProtocolError(f"malformed frame: {e}") from e
    if not isinstance(msg, dict):
        raise ProtocolError("malformed frame: not a JSON object")
    return msg


def send_msg(sock: socket.socket, msg: dict):
    data = json.dumps(msg, separators=(",", ":")).encode() + b"\n"
    data = faults.inject("proto.send", data)
    sock.sendall(data)


def recv_msg(sock: socket.socket, max_size: int = 256 * 1024 * 1024) -> dict:
    buf = bytearray()
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            if not buf:
                raise ConnectionError("peer closed")
            break
        buf.extend(chunk)
        if buf.endswith(b"\n"):
            break
        if len(buf) > max_size:
            raise ProtocolError("message too large")
    data = faults.inject("proto.recv", bytes(buf))
    if not data.endswith(b"\n"):
        raise ProtocolError("truncated frame")
    return _decode_frame(data)


def recv_msg_file(rfile, max_size: int = 256 * 1024 * 1024) -> dict | None:
    line = rfile.readline(max_size)
    if not line:
        return None
    line = faults.inject("proto.recv", line)
    if not line.endswith(b"\n"):
        # readline(max_size) silently returns a partial line when the
        # frame exceeds the cap; a partial line at EOF is a peer that died
        # mid-frame — neither may reach json.loads as if it were complete
        if len(line) >= max_size:
            raise ProtocolError("message too large")
        raise ProtocolError("truncated frame")
    return _decode_frame(line)
