"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-2 scope — the proof now covers the STATE TRANSITION, not just the
output bytes.  `prove` emits two DEEP-FRI STARKs over the same TPU prover
(stark/prover.py):

  1. the STATE proof (models/state_update_air.StateUpdateAir): in-circuit
     verification that applying the batch's write log, entry by entry with
     Merkle openings, transforms the touched-state commitment r_pre into
     r_post — public inputs (r_pre, r_post, log_digest);
  2. the BINDING proof (models/poseidon2_air.Poseidon2SpongeAir): the
     claimed ProgramOutput bytes plus (r_pre, r_post, log_digest) hashed
     in-circuit to one digest, chaining the state proof's publics to the
     batch output the L1 consumes.

`verify` checks both STARKs with the independent host verifier, recomputes
log_digest / r_pre / r_post from the proof-carried write log, and — when
given the ProverInput — audits the log against the witness MPT with trie
operations only (guest/access_log.replay_log_against_witness): every old
value, every storage root, and the final keccak state root, with NO EVM
execution on the verifying side.

Remaining trust gap (the future VM AIR): that the log's NEW values are
what EVM semantics dictate.  The reference closes this by running the
whole guest in a zkVM (crates/prover/src/backend/sp1.rs:145-163); our
equivalent is arithmetizing the EVM's effects on top of this state
circuit.
"""

from __future__ import annotations

from ..guest import access_log
from ..guest.execution import ProgramInput, execution_program
from ..models import poseidon2_air as pair
from ..models import state_update_air as sua
from ..ops import babybear as bb
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from . import protocol
from .backend import ProverBackend

PARAMS = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 24-bit BabyBear limbs (raw byte slices —
    the full output is absorbed by the sponge, no pre-compression)."""
    padded = output_bytes + b"\x00" * ((-len(output_bytes)) % 3)
    limbs = [int.from_bytes(padded[i:i + 3], "big")
             for i in range(0, len(padded), 3)]
    limbs.append(len(output_bytes))  # length limb: no padding ambiguity
    return limbs


def binding_limbs(output_bytes: bytes, r_pre: list[int], r_post: list[int],
                  digest: list[int]) -> list[int]:
    """Message of the binding sponge: output bytes then the state proof's
    24 public limbs, one padded stream."""
    limbs = output_to_limbs(output_bytes) + list(r_pre) + list(r_post) \
        + list(digest)
    return pair.pad_message_limbs(limbs)


def _schedule_for(depth: int) -> int:
    """seg_periods for a tree depth (smallest power of two fitting the
    3-leaf + depth-fold + tail schedule; >= 8)."""
    need = depth + 5
    return max(8, 1 << (need - 1).bit_length())


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        blocks_log: list = []
        output = execution_program(program_input, write_log=blocks_log)
        encoded = output.encode()

        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        air = sua.StateUpdateAir(depth, seg_periods=S)
        trace = sua.generate_state_update_trace(records, r_pre, depth, S)
        pub = sua.state_update_public_inputs(records, r_pre, r_post, S)
        state_proof = stark_prover.prove(air, trace, pub, PARAMS)
        digest = pub[16:24]

        limbs = binding_limbs(encoded, r_pre, r_post, digest)
        bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
        bind_trace = pair.generate_sponge_trace(limbs)
        bind_pub = pair.sponge_public_inputs(limbs)
        bind_proof = stark_prover.prove(bind_air, bind_trace, bind_pub,
                                        PARAMS)
        proof = {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "write_log": access_log.raw_log_to_json(blocks_log),
            "depth": depth,
            "seg_periods": S,
            "state_proof": state_proof,
            "proof": bind_proof,
        }
        if proof_format in (protocol.FORMAT_COMPRESSED,
                            protocol.FORMAT_GROTH16):
            # recursion: one outer STARK proves both proofs' FRI query
            # openings; their Merkle path data is dropped from the wire
            from ..stark import aggregate as agg_mod

            agg = agg_mod.aggregate([air, bind_air],
                                    [state_proof, bind_proof], PARAMS)
            proof["state_proof"], proof["proof"] = agg.inners
            proof["aggregate"] = {
                "outer": agg.outer, "max_depth": agg.max_depth,
                "seg_periods": agg.seg_periods,
            }
            if proof_format == protocol.FORMAT_GROTH16:
                from . import groth16_wrap

                wrapped = groth16_wrap.wrap_prove(
                    [int(v) for v in agg.outer["pub_inputs"]],
                    rnd=encoded[:32])
                proof["groth16"] = groth16_wrap.proof_to_json(wrapped)
        return proof

    # -- verification -------------------------------------------------------

    def _check(self, proof: dict):
        """Shared verification core; returns the parsed raw log + claimed
        output bytes, or raises."""
        if proof.get("backend") != self.prover_type:
            raise ValueError("wrong backend tag")
        encoded = bytes.fromhex(proof["output"][2:])
        if sum(len(b) for b in proof["write_log"]) > 1_000_000:
            raise ValueError("write log too large")
        blocks_log = access_log.raw_log_from_json(proof["write_log"])

        # recompute the flat commitments from the claimed log; the tree
        # shape is fully determined by the log, so the proof's claimed
        # depth/seg_periods get no attacker freedom (a huge claimed depth
        # would otherwise allocate 2^depth leaves before any AIR check)
        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        if int(proof["depth"]) != depth or int(proof["seg_periods"]) != S:
            raise ValueError("claimed tree shape does not match the log")
        segments = sua.segment_count(len(records))
        digest = sua.log_digest(records, S, segments)

        state = proof["state_proof"]
        claimed_pub = [int(v) % bb.P for v in state["pub_inputs"]]
        if claimed_pub != r_pre + r_post + digest:
            raise ValueError("state proof publics do not match the log")
        air = sua.StateUpdateAir(depth, seg_periods=S)

        limbs = binding_limbs(encoded, r_pre, r_post, digest)
        bind = proof["proof"]
        if [int(v) for v in bind["pub_inputs"][:len(limbs)]] != limbs:
            raise ValueError("binding proof does not bind this statement")
        bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)

        agg_info = proof.get("aggregate")
        if agg_info is not None:
            # compressed/groth16: both proofs verified through the outer
            # recursion STARK (their FRI paths are gone from the wire)
            from ..stark import aggregate as agg_mod

            agg = agg_mod.AggregateProof(
                inners=[state, bind], outer=agg_info["outer"],
                max_depth=int(agg_info["max_depth"]),
                seg_periods=int(agg_info["seg_periods"]))
            agg_mod.verify_aggregated([air, bind_air], agg, PARAMS)
            wrapped = proof.get("groth16")
            if wrapped is not None:
                from . import groth16_wrap

                if not groth16_wrap.wrap_verify(
                        groth16_wrap.proof_from_json(wrapped),
                        [int(v) for v in agg.outer["pub_inputs"]]):
                    raise ValueError("groth16 wrap rejected")
        else:
            if not stark_verifier.verify(air, state, PARAMS):
                raise ValueError("state proof rejected")
            if not stark_verifier.verify(bind_air, bind, PARAMS):
                raise ValueError("binding proof rejected")
        return blocks_log, encoded

    def verify(self, proof: dict) -> bool:
        try:
            self._check(proof)
            return True
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False

    def verify_with_input(self, proof: dict,
                          program_input: ProgramInput) -> bool:
        """Full audit: both STARKs + the witness MPT replay (trie ops
        only, no EVM) against the claimed initial/final state roots."""
        from ..guest.execution import ProgramOutput

        try:
            blocks_log, encoded = self._check(proof)
            output = ProgramOutput.decode(encoded)
            access_log.replay_log_against_witness(
                blocks_log, program_input.witness.nodes,
                output.initial_state_root, output.final_state_root)
            return True
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False
