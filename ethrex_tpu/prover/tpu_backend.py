"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-1 scope: the guest program runs natively on the host, and the TPU
produces an **output-binding STARK** — a real DEEP-FRI proof (device LDE +
Poseidon2 Merkle + FRI) over a Mixer trace seeded with the ProgramOutput
digest, verified by the independent host verifier.  This exercises the full
coordinator -> TPU -> proof-store pipeline with real TPU proving work.

What it does NOT yet prove: the EVM execution itself.  That requires the VM
AIR (the reference delegates this to its zkVM SDKs; our equivalent is the
round-2+ arithmetization of guest/execution.py).  The proof here binds the
claimed ProgramOutput into a verified STARK via public inputs — equivalent
trust to the reference's exec backend, plus end-to-end TPU kernels.
"""

from __future__ import annotations

import numpy as np

from ..crypto.keccak import keccak256
from ..guest.execution import ProgramInput
from ..models.mixer import MixerAir
from ..ops import babybear as bb
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from . import protocol
from .backend import ProverBackend

TRACE_ROWS = 256
WIDTH = 16
PARAMS = StarkParams(log_blowup=2, num_queries=40, log_final_size=5)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 16 BabyBear limbs via keccak expansion."""
    h1 = keccak256(b"ethrex-tpu/output-binding/1" + output_bytes)
    h2 = keccak256(b"ethrex-tpu/output-binding/2" + output_bytes)
    limbs = []
    for h in (h1, h2):
        for i in range(8):
            limbs.append(int.from_bytes(h[4 * i:4 * i + 3], "big"))  # 24-bit
    return limbs


def _binding_trace(seed_limbs: list[int]) -> np.ndarray:
    trace = np.zeros((TRACE_ROWS, WIDTH), dtype=np.uint64)
    trace[0] = seed_limbs
    for i in range(1, TRACE_ROWS):
        prev = trace[i - 1]
        trace[i] = (prev * prev + np.roll(prev, -1)) % bb.P
    return trace.astype(np.uint32)


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def __init__(self):
        self.air = MixerAir(width=WIDTH)

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        output = self.execute(program_input)
        encoded = output.encode()
        limbs = output_to_limbs(encoded)
        trace = _binding_trace(limbs)
        pub = limbs + [int(trace[-1, 0])]
        stark = stark_prover.prove(self.air, trace, pub, PARAMS)
        return {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "proof": stark,
        }

    def verify(self, proof: dict) -> bool:
        if proof.get("backend") != self.prover_type:
            return False
        try:
            encoded = bytes.fromhex(proof["output"][2:])
            stark = proof["proof"]
            limbs = output_to_limbs(encoded)
            # the proof's public inputs must match the claimed output
            if stark["pub_inputs"][:WIDTH] != limbs:
                return False
            return stark_verifier.verify(self.air, stark, PARAMS)
        except (KeyError, ValueError, TypeError,
                stark_verifier.VerificationError):
            return False
