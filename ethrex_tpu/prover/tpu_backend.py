"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-1 scope: the guest program runs natively on the host, and the TPU
produces an **output-binding STARK** — a real DEEP-FRI proof (device LDE +
Poseidon2 Merkle + FRI) that the claimed ProgramOutput bytes hash, limb by
limb **in-circuit through the Poseidon2 sponge**
(models/poseidon2_air.Poseidon2SpongeAir = exactly ops/poseidon2.hash_leaves,
the framework's Merkle leaf hash), to the digest in the proof's public
inputs.  Verified by the independent host verifier.

What it does NOT yet prove: the EVM execution itself.  That requires the VM
AIR (the reference delegates this to its zkVM SDKs; our equivalent is the
arithmetization of guest/execution.py — the sponge AIR here is its hash
building block).  Until then the execution-trust level matches the
reference's exec backend, with real TPU proving work end to end.
"""

from __future__ import annotations

from ..guest.execution import ProgramInput
from ..models import poseidon2_air as pair
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from . import protocol
from .backend import ProverBackend

PARAMS = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 24-bit BabyBear limbs (raw byte slices —
    the full output is absorbed by the sponge, no pre-compression)."""
    padded = output_bytes + b"\x00" * ((-len(output_bytes)) % 3)
    limbs = [int.from_bytes(padded[i:i + 3], "big")
             for i in range(0, len(padded), 3)]
    limbs.append(len(output_bytes))  # length limb: no padding ambiguity
    return pair.pad_message_limbs(limbs)


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        output = self.execute(program_input)
        encoded = output.encode()
        limbs = output_to_limbs(encoded)
        air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
        trace = pair.generate_sponge_trace(limbs)
        pub = pair.sponge_public_inputs(limbs)
        stark = stark_prover.prove(air, trace, pub, PARAMS)
        return {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "proof": stark,
        }

    def verify(self, proof: dict) -> bool:
        if proof.get("backend") != self.prover_type:
            return False
        try:
            encoded = bytes.fromhex(proof["output"][2:])
            stark = proof["proof"]
            limbs = output_to_limbs(encoded)
            air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
            # the proof's public inputs must bind the claimed output limbs
            if [int(v) for v in stark["pub_inputs"][:len(limbs)]] != limbs:
                return False
            return stark_verifier.verify(air, stark, PARAMS)
        except (KeyError, ValueError, TypeError,
                stark_verifier.VerificationError):
            return False
