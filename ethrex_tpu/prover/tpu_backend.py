"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-2 scope — the proof now covers the STATE TRANSITION, not just the
output bytes.  `prove` emits two DEEP-FRI STARKs over the same TPU prover
(stark/prover.py):

  1. the STATE proof (models/state_update_air.StateUpdateAir): in-circuit
     verification that applying the batch's write log, entry by entry with
     Merkle openings, transforms the touched-state commitment r_pre into
     r_post — public inputs (r_pre, r_post, log_digest);
  2. the BINDING proof (models/poseidon2_air.Poseidon2SpongeAir): the
     claimed ProgramOutput bytes plus (r_pre, r_post, log_digest) hashed
     in-circuit to one digest, chaining the state proof's publics to the
     batch output the L1 consumes.

`verify` checks both STARKs with the independent host verifier, recomputes
log_digest / r_pre / r_post from the proof-carried write log, and — when
given the ProverInput — audits the log against the witness MPT with trie
operations only (guest/access_log.replay_log_against_witness): every old
value, every storage root, and the final keccak state root, with NO EVM
execution on the verifying side.

Round-3: the VM AIR (transfer scope).  When every transaction in the
batch is a plain ETH transfer, `prove` swaps the executor's per-block
write log for a per-tx fine log (guest/transfer_log.py) and emits a THIRD
STARK (models/transfer_air.TransferAir) proving that every account entry
in that log follows EVM transfer semantics — nonce + 1, sender debit of
value + fee, recipient credit, per-tx coinbase tip — over in-circuit
Poseidon2 recomputation of the flat keys and field digests.  `verify`
recomputes the circuit's public digest from the SAME claimed log that
drives the state proof's commitments, so tampering any transfer amount in
the log leaves NO satisfiable proof: the reference's equivalent guarantee
comes from executing the guest inside the zkVM
(crates/prover/src/backend/sp1.rs:145-163).

Residual trust gaps in vm mode, all closed natively by
`verify_with_input` and documented here for the wire verifier:
  * tx-list authenticity (the claimed senders/values vs the signed txs in
    the committed blocks) — the circuit binds the claimed list, the
    witness check compares it against the batch's blocks;
  * fee/tip vs base fee: verify checks fee - tip == 21000 * base_fee on
    the claimed per-block base fee; the base fee's link to the header is
    witness-checked;
  * batches with storage writes / contract calls still use the claimed-
    log mode (state proof + binding only) — the next arithmetization
    stage.
"""

from __future__ import annotations

from ..guest import access_log
from ..guest.execution import ProgramInput, execution_program
from ..models import poseidon2_air as pair
from ..models import state_update_air as sua
from ..ops import babybear as bb
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from . import protocol
from .backend import ProverBackend

PARAMS = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 24-bit BabyBear limbs (raw byte slices —
    the full output is absorbed by the sponge, no pre-compression)."""
    padded = output_bytes + b"\x00" * ((-len(output_bytes)) % 3)
    limbs = [int.from_bytes(padded[i:i + 3], "big")
             for i in range(0, len(padded), 3)]
    limbs.append(len(output_bytes))  # length limb: no padding ambiguity
    return limbs


def binding_limbs(output_bytes: bytes, r_pre: list[int], r_post: list[int],
                  digest: list[int],
                  vmdigest: list[int] | None = None) -> list[int]:
    """Message of the binding sponge: output bytes, the state proof's 24
    public limbs, then a mode limb + the VM statement digest (zeroed in
    claimed-log mode) — one padded stream."""
    limbs = output_to_limbs(output_bytes) + list(r_pre) + list(r_post) \
        + list(digest)
    if vmdigest is None:
        limbs += [0] * 9
    else:
        limbs += [1] + list(vmdigest)
    return pair.pad_message_limbs(limbs)


def _schedule_for(depth: int) -> int:
    """seg_periods for a tree depth (smallest power of two fitting the
    3-leaf + depth-fold + tail schedule; >= 8)."""
    need = depth + 5
    return max(8, 1 << (need - 1).bit_length())


def _vm_meta_json(vm_batch) -> dict:
    return {
        "mode": "transfer",
        "blocks": [{
            "coinbase": b.coinbase.hex(),
            "base_fee": b.base_fee,
            "txs": [{"sender": t.sender.hex(), "to": t.recipient.hex(),
                     "value": t.value, "fee": t.fee, "tip": t.tip}
                    for t in b.txs],
        } for b in vm_batch.blocks],
    }


def _vm_stream_from_claims(vm_meta: dict, blocks_log: list) -> list:
    """Build the VM digest stream a verifier recomputes from the claimed
    tx list + the claimed write log; performs the native structural and
    fee-relation checks of vm mode.  Raises ValueError on any mismatch."""
    from ..guest import flat_model
    from ..models import transfer_air as ta

    if vm_meta.get("mode") != "transfer":
        raise ValueError("unknown vm mode")
    blocks = vm_meta["blocks"]
    if len(blocks) != len(blocks_log):
        raise ValueError("vm block count does not match the log")

    def acct_digests(entry, want_addr: bytes):
        if entry[0] != "acct":
            raise ValueError("vm log entry is not an account write")
        _, addr, _, old_rlp, new_rlp, cleared = entry
        if addr != want_addr or cleared:
            raise ValueError("vm log entry address mismatch")
        old = [0] * 8 if not old_rlp else flat_model.account_value_digest(
            flat_model.AccountState.decode(old_rlp))
        new = [0] * 8 if not new_rlp else flat_model.account_value_digest(
            flat_model.AccountState.decode(new_rlp))
        return flat_model.account_key_digest(addr), old, new

    items = []
    for bmeta, rows in zip(blocks, blocks_log):
        coinbase = bytes.fromhex(bmeta["coinbase"])
        base_fee = int(bmeta["base_fee"])
        cursor = 0
        for txm in bmeta["txs"]:
            value = int(txm["value"])
            fee = int(txm["fee"])
            tip = int(txm["tip"])
            if not (0 <= value < 1 << 256 and 0 <= tip <= fee < 1 << 256):
                raise ValueError("vm tx amounts out of range")
            if fee - tip != 21000 * base_fee:
                raise ValueError("vm fee does not match the base fee")
            sender = bytes.fromhex(txm["sender"])
            to = bytes.fromhex(txm["to"])
            ks, os_, ns = acct_digests(rows[cursor], sender)
            cursor += 1
            if value == 0:
                # no-op credit: no log row; the circuit's NOP segment
                # absorbs zero digests and pins the amount to zero
                kr = flat_model.account_key_digest(to)
                orr = nr = [0] * 8
            else:
                kr, orr, nr = acct_digests(rows[cursor], to)
                cursor += 1
            if tip == 0:
                kc = flat_model.account_key_digest(coinbase)
                oc = nc = [0] * 8
            else:
                kc, oc, nc = acct_digests(rows[cursor], coinbase)
                cursor += 1
            txf = (ta._limbs11(value), ta._limbs11(fee), ta._limbs11(tip))
            items.append(("tx", txf, (ks, os_, ns, kr, orr, nr)))
            items.append(("cb", None, (kc, oc, nc)))
        if cursor != len(rows):
            raise ValueError("vm log shape mismatch")
    return items


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        from ..guest import transfer_log as tl_mod
        from ..models import transfer_air as ta

        blocks_log: list = []
        output = execution_program(program_input, write_log=blocks_log)
        encoded = output.encode()

        vm_batch = None
        try:
            vm_batch = tl_mod.build_transfer_batch(program_input.blocks,
                                                   blocks_log)
            blocks_log = vm_batch.blocks_log
        except tl_mod.NotTransferBatch:
            pass

        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        air = sua.StateUpdateAir(depth, seg_periods=S)
        trace = sua.generate_state_update_trace(records, r_pre, depth, S)
        pub = sua.state_update_public_inputs(records, r_pre, r_post, S)
        state_proof = stark_prover.prove(air, trace, pub, PARAMS)
        digest = pub[16:24]

        vm_pub = None
        vm_proof = None
        vm_air = None
        if vm_batch is not None:
            vm_air = ta.TransferAir()
            vm_trace = ta.generate_transfer_trace(vm_batch.segs)
            vm_pub = ta.transfer_public_inputs(vm_batch.segs)
            vm_proof = stark_prover.prove(vm_air, vm_trace, vm_pub, PARAMS)

        limbs = binding_limbs(encoded, r_pre, r_post, digest, vm_pub)
        bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
        bind_trace = pair.generate_sponge_trace(limbs)
        bind_pub = pair.sponge_public_inputs(limbs)
        bind_proof = stark_prover.prove(bind_air, bind_trace, bind_pub,
                                        PARAMS)
        proof = {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "write_log": access_log.raw_log_to_json(blocks_log),
            "depth": depth,
            "seg_periods": S,
            "state_proof": state_proof,
            "proof": bind_proof,
        }
        if vm_batch is not None:
            proof["vm"] = _vm_meta_json(vm_batch)
            proof["vm_proof"] = vm_proof
        if proof_format in (protocol.FORMAT_COMPRESSED,
                            protocol.FORMAT_GROTH16):
            # recursion: one outer STARK proves every inner proof's FRI
            # query openings; their Merkle path data leaves the wire
            from ..stark import aggregate as agg_mod

            airs = [air, bind_air]
            proofs = [state_proof, bind_proof]
            if vm_batch is not None:
                airs.append(vm_air)
                proofs.append(vm_proof)
            agg = agg_mod.aggregate(airs, proofs, PARAMS)
            proof["state_proof"], proof["proof"] = agg.inners[:2]
            if vm_batch is not None:
                proof["vm_proof"] = agg.inners[2]
            proof["aggregate"] = {
                "outer": agg.outer, "max_depth": agg.max_depth,
                "seg_periods": agg.seg_periods,
            }
            if proof_format == protocol.FORMAT_GROTH16:
                from . import groth16_wrap

                wrapped = groth16_wrap.wrap_prove(
                    [int(v) for v in agg.outer["pub_inputs"]],
                    rnd=encoded[:32])
                proof["groth16"] = groth16_wrap.proof_to_json(wrapped)
        return proof

    # -- verification -------------------------------------------------------

    def _check(self, proof: dict):
        """Shared verification core; returns the parsed raw log + claimed
        output bytes, or raises."""
        if proof.get("backend") != self.prover_type:
            raise ValueError("wrong backend tag")
        encoded = bytes.fromhex(proof["output"][2:])
        if sum(len(b) for b in proof["write_log"]) > 1_000_000:
            raise ValueError("write log too large")
        blocks_log = access_log.raw_log_from_json(proof["write_log"])

        # recompute the flat commitments from the claimed log; the tree
        # shape is fully determined by the log, so the proof's claimed
        # depth/seg_periods get no attacker freedom (a huge claimed depth
        # would otherwise allocate 2^depth leaves before any AIR check)
        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        if int(proof["depth"]) != depth or int(proof["seg_periods"]) != S:
            raise ValueError("claimed tree shape does not match the log")
        segments = sua.segment_count(len(records))
        digest = sua.log_digest(records, S, segments)

        state = proof["state_proof"]
        claimed_pub = [int(v) % bb.P for v in state["pub_inputs"]]
        if claimed_pub != r_pre + r_post + digest:
            raise ValueError("state proof publics do not match the log")
        air = sua.StateUpdateAir(depth, seg_periods=S)

        # vm mode: the transfer circuit's public digest is recomputed from
        # the SAME claimed log (plus the claimed tx list), so the write
        # log's account values are constrained by EVM transfer semantics
        vm_meta = proof.get("vm")
        vm_air = None
        vm_proof = None
        vm_pub = None
        if vm_meta is not None:
            from ..models import transfer_air as ta

            items = _vm_stream_from_claims(vm_meta, blocks_log)
            vm_pub = ta.vm_digest_stream(items)
            vm_proof = proof["vm_proof"]
            if [int(v) % bb.P for v in vm_proof["pub_inputs"]] != vm_pub:
                raise ValueError("vm proof does not bind this log")
            vm_air = ta.TransferAir()

        limbs = binding_limbs(encoded, r_pre, r_post, digest, vm_pub)
        bind = proof["proof"]
        if [int(v) for v in bind["pub_inputs"][:len(limbs)]] != limbs:
            raise ValueError("binding proof does not bind this statement")
        bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)

        airs = [air, bind_air]
        proofs = [state, bind]
        if vm_air is not None:
            airs.append(vm_air)
            proofs.append(vm_proof)

        agg_info = proof.get("aggregate")
        if agg_info is not None:
            # compressed/groth16: every proof verified through the outer
            # recursion STARK (their FRI paths are gone from the wire)
            from ..stark import aggregate as agg_mod

            agg = agg_mod.AggregateProof(
                inners=proofs, outer=agg_info["outer"],
                max_depth=int(agg_info["max_depth"]),
                seg_periods=int(agg_info["seg_periods"]))
            agg_mod.verify_aggregated(airs, agg, PARAMS)
            wrapped = proof.get("groth16")
            if wrapped is not None:
                from . import groth16_wrap

                if not groth16_wrap.wrap_verify(
                        groth16_wrap.proof_from_json(wrapped),
                        [int(v) for v in agg.outer["pub_inputs"]]):
                    raise ValueError("groth16 wrap rejected")
        else:
            for a, p in zip(airs, proofs):
                if not stark_verifier.verify(a, p, PARAMS):
                    raise ValueError("proof rejected")
        return blocks_log, encoded

    def verify(self, proof: dict) -> bool:
        try:
            self._check(proof)
            return True
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False

    def verify_with_input(self, proof: dict,
                          program_input: ProgramInput) -> bool:
        """Full audit: every STARK + the witness MPT replay (trie ops
        only, no EVM) against the claimed initial/final state roots; in
        vm mode, also the claimed tx list against the batch's signed txs
        (closing the wire-verifier's documented authenticity gap), and a
        downgrade check: an all-transfer batch must carry the vm proof."""
        from ..guest.execution import ProgramOutput
        from ..guest.transfer_log import TRANSFER_GAS, is_plain_transfer

        try:
            blocks_log, encoded = self._check(proof)
            output = ProgramOutput.decode(encoded)
            access_log.replay_log_against_witness(
                blocks_log, program_input.witness.nodes,
                output.initial_state_root, output.final_state_root)
            vm_meta = proof.get("vm")
            if vm_meta is None:
                # downgrade check: a batch the transfer circuit covers
                # must carry the vm proof.  The static predicate over-
                # approximates the circuit's scope (e.g. a plain call to
                # a contract address), so on ambiguity re-derive
                # applicability exactly as the prover would.
                if not all(is_plain_transfer(tx)
                           for blk in program_input.blocks
                           for tx in blk.body.transactions):
                    return True
                from ..guest.transfer_log import (NotTransferBatch,
                                                  build_transfer_batch)

                try:
                    coarse: list = []
                    execution_program(program_input, write_log=coarse)
                    build_transfer_batch(program_input.blocks, coarse)
                except NotTransferBatch:
                    return True
                return False
            blocks = vm_meta["blocks"]
            if len(blocks) != len(program_input.blocks):
                return False
            for bmeta, blk in zip(blocks, program_input.blocks):
                base_fee = blk.header.base_fee_per_gas or 0
                if bytes.fromhex(bmeta["coinbase"]) != blk.header.coinbase \
                        or int(bmeta["base_fee"]) != base_fee:
                    return False
                txs = blk.body.transactions
                if len(bmeta["txs"]) != len(txs):
                    return False
                for txm, tx in zip(bmeta["txs"], txs):
                    price = tx.effective_gas_price(base_fee)
                    if (bytes.fromhex(txm["sender"]) != tx.sender()
                            or bytes.fromhex(txm["to"]) != tx.to
                            or int(txm["value"]) != tx.value
                            or price is None
                            or int(txm["fee"]) != TRANSFER_GAS * price):
                        return False
            return True
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False
