"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-2 scope — the proof now covers the STATE TRANSITION, not just the
output bytes.  `prove` emits two DEEP-FRI STARKs over the same TPU prover
(stark/prover.py):

  1. the STATE proof (models/state_update_air.StateUpdateAir): in-circuit
     verification that applying the batch's write log, entry by entry with
     Merkle openings, transforms the touched-state commitment r_pre into
     r_post — public inputs (r_pre, r_post, log_digest);
  2. the BINDING proof (models/poseidon2_air.Poseidon2SpongeAir): the
     claimed ProgramOutput bytes plus (r_pre, r_post, log_digest) hashed
     in-circuit to one digest, chaining the state proof's publics to the
     batch output the L1 consumes.

`verify` checks both STARKs with the independent host verifier, recomputes
log_digest / r_pre / r_post from the proof-carried write log, and — when
given the ProverInput — audits the log against the witness MPT with trie
operations only (guest/access_log.replay_log_against_witness): every old
value, every storage root, and the final keccak state root, with NO EVM
execution on the verifying side.

Round-3: the VM AIR (transfer scope).  When every transaction in the
batch is a plain ETH transfer, `prove` swaps the executor's per-block
write log for a per-tx fine log (guest/transfer_log.py) and emits a THIRD
STARK (models/transfer_air.TransferAir) proving that every account entry
in that log follows EVM transfer semantics — nonce + 1, sender debit of
value + fee, recipient credit, per-tx coinbase tip — over in-circuit
Poseidon2 recomputation of the flat keys and field digests.  `verify`
recomputes the circuit's public digest from the SAME claimed log that
drives the state proof's commitments, so tampering any transfer amount in
the log leaves NO satisfiable proof: the reference's equivalent guarantee
comes from executing the guest inside the zkVM
(crates/prover/src/backend/sp1.rs:145-163).

Round-4: the token/storage AIR (SLOAD/SSTORE/CALL scope).  Batches may
also contain calls to the canonical token template
(guest/token_template.py): each such call enters the transfer stream as
a value-0 fee/nonce tx AND contributes a segment to a FOURTH STARK
(models/token_air.TokenAir) proving the two balance-slot writes follow
the template's transfer semantics (debit with no underflow, credit with
no wrap).  The verifier recomputes the token digest from the claimed
log's slot rows + the claimed calldata (slot keys re-derived by keccak
from the claimed sender/dst), so tampering any storage slot's NEW value
in the write log leaves no satisfiable proof either.

Round-5: the generic bytecode AIR.  Transactions calling ARBITRARY
bytecode are provable when the executed trace stays inside the supported
opcode subset and machine envelope (guest/bytecode_vm.py): each such
call gets its own STARK (models/bytecode_air.py) proving every step's
stack/memory/storage/control-flow semantics, with the step records
absorbed into a public digest the verifier recomputes from the claimed
step list — checking opcodes/immediates against the claimed code
(pinned by keccak to the code_hash inside the contract's account row,
which r_pre commits), calldata/env values against the claimed tx, and
storage records against the SAME write-log rows the state circuit
applies.  Reads enter the fine log as no-op rows so r_pre commits them
and the witness replay audits them.

Residual trust gaps in vm mode, all closed natively by
`verify_with_input` and documented here for the wire verifier:
  * tx-list authenticity (the claimed senders/values/calldata vs the
    signed txs in the committed blocks) — the circuit binds the claimed
    list, the witness check compares it against the batch's blocks;
  * fee/tip vs base fee: for transfers verify checks fee - tip ==
    21000 * base_fee on the claimed per-block base fee; for token and
    generic calls fee = g*price is checked against the CLAIMED per-tx
    gas g (bounded below by 21000), whose truth is witness-checked (a
    wrong g shifts balances and breaks the replayed state root);
  * gas/refund accounting inside generic calls is NOT in-circuit (the
    executed path's semantics are gas-independent once the receipt says
    it succeeded; the receipt itself is bound by the receipts root);
  * the contract account rows may change only their storage_root
    (natively checked); the root's VALUE is MPT work left to the witness
    replay;
  * batches outside the transfer/token/generic-subset class still use
    the claimed-log mode (state proof + binding only).
"""

from __future__ import annotations

from ..guest import access_log
from ..guest.execution import ProgramInput, execution_program
from ..models import poseidon2_air as pair
from ..models import state_update_air as sua
from ..ops import babybear as bb
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from ..utils import faults, tracing
from . import checkpoint as ckpt_mod
from . import protocol
from . import runtime_errors as rt
from .backend import ProverBackend

PARAMS = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 24-bit BabyBear limbs (raw byte slices —
    the full output is absorbed by the sponge, no pre-compression)."""
    padded = output_bytes + b"\x00" * ((-len(output_bytes)) % 3)
    limbs = [int.from_bytes(padded[i:i + 3], "big")
             for i in range(0, len(padded), 3)]
    limbs.append(len(output_bytes))  # length limb: no padding ambiguity
    return limbs


def binding_limbs(output_bytes: bytes, r_pre: list[int], r_post: list[int],
                  digest: list[int],
                  vmdigest: list[int] | None = None,
                  tokdigest: list[int] | None = None,
                  bcdigests: list | None = None) -> list[int]:
    """Message of the binding sponge: output bytes, the state proof's 24
    public limbs, a mode limb + statement digest for each VM circuit
    (zeroed in claimed-log mode), then the generic-call digests prefixed
    by their count — one padded stream."""
    limbs = output_to_limbs(output_bytes) + list(r_pre) + list(r_post) \
        + list(digest)
    for d in (vmdigest, tokdigest):
        limbs += [0] * 9 if d is None else [1] + list(d)
    bcdigests = bcdigests or []
    limbs += [len(bcdigests)]
    for d in bcdigests:
        limbs += list(d)
    return pair.pad_message_limbs(limbs)


def _schedule_for(depth: int) -> int:
    """seg_periods for a tree depth (smallest power of two fitting the
    3-leaf + depth-fold + tail schedule; >= 8)."""
    need = depth + 5
    return max(8, 1 << (need - 1).bit_length())


def _mode_of(vm_batch) -> str:
    """The single classifier both the prover's metadata and the
    committer's expected_vm_mode derive from — one definition, because
    check_coverage demands strict equality between the two."""
    return "generic" if vm_batch.bc_calls else (
        "token" if vm_batch.tok_segs else "transfer")


def _vm_meta_json(vm_batch) -> dict:
    blocks = []
    codes: dict[str, str] = {}   # contract addr -> bytecode (one per
    for b in vm_batch.blocks:    # contract, however many calls hit it)
        txs = []
        for t in b.txs:
            row = {"sender": t.sender.hex(), "to": t.recipient.hex(),
                   "value": t.value, "fee": t.fee, "tip": t.tip}
            if t.kind == "tok":
                row.update({"kind": "tok", "gas": t.gas,
                            "dst": t.dst.hex(), "amount": t.amount})
            elif t.kind == "gen":
                row.update({"kind": "gen", "gas": t.gas,
                            "data": t.data.hex(),
                            "steps": [s.to_json() for s in t.steps]})
                codes[t.recipient.hex()] = t.code.hex()
            txs.append(row)
        blocks.append({"coinbase": b.coinbase.hex(),
                       "base_fee": b.base_fee, "txs": txs})
    out = {"mode": _mode_of(vm_batch), "blocks": blocks}
    if codes:
        out["codes"] = codes
    return out


def _vm_stream_from_claims(vm_meta: dict, blocks_log: list):
    """Build the VM digest streams a verifier recomputes from the claimed
    tx list + the claimed write log; performs the native structural and
    fee-relation checks of vm mode.  Returns (transfer_items, tok_items,
    bc_pubs) where bc_pubs holds one 8-limb digest per generic call (the
    claimed step lists are pinned to the claimed code/calldata/log by
    guest/bytecode_vm.check_steps — data indexing, no EVM execution).
    Raises ValueError on any mismatch."""
    from ..guest import bytecode_vm as bv
    from ..guest import flat_model
    from ..guest import token_template as tmpl
    from ..models import bytecode_air as bca
    from ..models import transfer_air as ta

    mode = vm_meta.get("mode")
    if mode not in ("transfer", "token", "generic"):
        raise ValueError("unknown vm mode")
    blocks = vm_meta["blocks"]
    if len(blocks) != len(blocks_log):
        raise ValueError("vm block count does not match the log")

    def acct_digests(entry, want_addr: bytes):
        if entry[0] != "acct":
            raise ValueError("vm log entry is not an account write")
        _, addr, _, old_rlp, new_rlp, cleared = entry
        if addr != want_addr or cleared:
            raise ValueError("vm log entry address mismatch")
        old = [0] * 8 if not old_rlp else flat_model.account_value_digest(
            flat_model.AccountState.decode(old_rlp))
        new = [0] * 8 if not new_rlp else flat_model.account_value_digest(
            flat_model.AccountState.decode(new_rlp))
        return flat_model.account_key_digest(addr), old, new

    def slot_row(entry, want_addr: bytes, want_slot: int):
        if entry[0] != "slot":
            raise ValueError("vm log entry is not a storage write")
        _, addr, slot, old_v, new_v = entry
        if addr != want_addr or int(slot) != want_slot:
            raise ValueError("vm slot row does not match the claimed call")
        old_v, new_v = int(old_v), int(new_v)
        if not (0 <= old_v < 1 << 256 and 0 <= new_v < 1 << 256):
            raise ValueError("vm slot value out of range")
        return old_v, new_v

    # untrusted-size guards, mirroring the 1MB write_log cap in _check
    claimed_codes = vm_meta.get("codes", {})
    if len(claimed_codes) > 1024 or any(
            len(c) > 2 * 0x40000 for c in claimed_codes.values()):
        raise ValueError("vm code claims too large")

    items = []
    tok_items = []
    bc_pubs: list = []
    for bmeta, rows in zip(blocks, blocks_log):
        coinbase = bytes.fromhex(bmeta["coinbase"])
        base_fee = int(bmeta["base_fee"])
        cursor = 0
        touched_contracts: list[bytes] = []
        gen_codes: dict[bytes, bytes] = {}
        for txm in bmeta["txs"]:
            value = int(txm["value"])
            fee = int(txm["fee"])
            tip = int(txm["tip"])
            kind = txm.get("kind", "xfer")
            if not (0 <= value < 1 << 256 and 0 <= tip <= fee < 1 << 256):
                raise ValueError("vm tx amounts out of range")
            sender = bytes.fromhex(txm["sender"])
            to = bytes.fromhex(txm["to"])
            if kind == "tok":
                if mode not in ("token", "generic"):
                    raise ValueError("token tx outside token mode")
                if value != 0:
                    raise ValueError("token call with value")
                g = int(txm["gas"])
                # fee = g*price, tip = g*(price - base_fee): g divides
                # both and their difference is g*base_fee; g's own truth
                # is witness-checked via the replayed balances
                if g < 21000 or fee - tip != g * base_fee \
                        or fee % g or tip % g:
                    raise ValueError("vm token fee out of model")
            elif kind == "gen":
                if mode != "generic":
                    raise ValueError("generic tx outside generic mode")
                if value != 0:
                    raise ValueError("generic call with value")
                g = int(txm["gas"])
                if g < 21000 or fee - tip != g * base_fee \
                        or fee % g or tip % g:
                    raise ValueError("vm generic fee out of model")
            elif fee - tip != 21000 * base_fee:
                raise ValueError("vm fee does not match the base fee")
            ks, os_, ns = acct_digests(rows[cursor], sender)
            cursor += 1
            if kind == "gen":
                code_hex = claimed_codes.get(txm["to"])
                if code_hex is None:
                    raise ValueError("vm generic call without code claim")
                if len(txm["steps"]) > bv.MAX_STEPS \
                        or len(txm["data"]) > 2_000_000:
                    raise ValueError("vm generic claims too large")
                code = bytes.fromhex(code_hex)
                data = bytes.fromhex(txm["data"])
                steps = [bv.StepRec.from_json(s) for s in txm["steps"]]
                touched: list[int] = []
                seen: set[int] = set()
                for st in steps:
                    if st.op in (bv.OP_SLOAD, bv.OP_SSTORE) \
                            and st.a not in seen:
                        seen.add(st.a)
                        touched.append(st.a)
                slot_rows = []
                for slot in touched:
                    old_v, new_v = slot_row(rows[cursor], to, slot)
                    cursor += 1
                    slot_rows.append((slot, old_v, new_v))
                try:
                    bv.check_steps(code, data, sender, 0, steps,
                                   slot_rows, address=to)
                except bv.StepCheckError as e:
                    raise ValueError(f"vm generic steps: {e}")
                bc_pubs.append(bca.bc_digest_stream(steps))
                if to not in touched_contracts:
                    touched_contracts.append(to)
                if gen_codes.setdefault(to, code) != code:
                    raise ValueError("vm generic code claim inconsistent")
                kr = flat_model.account_key_digest(to)
                orr = nr = [0] * 8
            elif kind == "tok":
                amount = int(txm["amount"])
                dst = bytes.fromhex(txm["dst"])
                if not (0 <= amount < 1 << 256):
                    raise ValueError("vm token amount out of range")
                if amount == 0:
                    tok_items.append((0, 0, 0, 0, 0, 0, 0, True))
                else:
                    kf = tmpl.balance_slot(sender)
                    kt = tmpl.balance_slot(dst)
                    fold, fnew = slot_row(rows[cursor], to, kf)
                    cursor += 1
                    told, tnew = slot_row(rows[cursor], to, kt)
                    cursor += 1
                    if to not in touched_contracts:
                        touched_contracts.append(to)
                    tok_items.append((amount, kf, fold, fnew,
                                      kt, told, tnew, False))
                kr = flat_model.account_key_digest(to)
                orr = nr = [0] * 8
            elif value == 0:
                # no-op credit: no log row; the circuit's NOP segment
                # absorbs zero digests and pins the amount to zero
                kr = flat_model.account_key_digest(to)
                orr = nr = [0] * 8
            else:
                kr, orr, nr = acct_digests(rows[cursor], to)
                cursor += 1
            if tip == 0:
                kc = flat_model.account_key_digest(coinbase)
                oc = nc = [0] * 8
            else:
                kc, oc, nc = acct_digests(rows[cursor], coinbase)
                cursor += 1
            txf = (ta._limbs11(value), ta._limbs11(fee), ta._limbs11(tip))
            items.append(("tx", txf, (ks, os_, ns, kr, orr, nr)))
            items.append(("cb", None, (kc, oc, nc)))
        # each touched token contract: ONE account row at block end whose
        # fields other than storage_root are unchanged (the storage_root
        # transition itself is MPT work the witness replay audits)
        for caddr in touched_contracts:
            entry = rows[cursor]
            cursor += 1
            if entry[0] != "acct" or entry[1] != caddr or entry[5]:
                raise ValueError("vm contract row mismatch")
            old_rlp, new_rlp = entry[3], entry[4]
            if not old_rlp or not new_rlp:
                raise ValueError("vm contract lifecycle change")
            o = flat_model.AccountState.decode(old_rlp)
            n = flat_model.AccountState.decode(new_rlp)
            if (o.nonce, o.balance, o.code_hash) != \
                    (n.nonce, n.balance, n.code_hash):
                raise ValueError("vm contract fields changed")
            code = gen_codes.get(caddr)
            if code is not None:
                # pin the claimed bytecode to the account row r_pre binds
                from ..crypto.keccak import keccak256
                from ..primitives.account import EMPTY_CODE_HASH

                want = EMPTY_CODE_HASH if not code else keccak256(code)
                if o.code_hash != want:
                    raise ValueError("vm generic code hash mismatch")
        if cursor != len(rows):
            raise ValueError("vm log shape mismatch")
    if mode == "token" and not tok_items:
        raise ValueError("token mode without token txs")
    if mode == "generic" and not bc_pubs:
        raise ValueError("generic mode without generic txs")
    return items, tok_items, bc_pubs


def vm_mode_from_artifacts(blocks, coarse_log, receipts, witness,
                           initial_root: bytes) -> str:
    """The VM-circuit coverage an honest prover reaches on this batch,
    classified from execution artifacts already in hand (the committer
    captures them during witness generation — no extra execution)."""
    from ..guest import transfer_log as tl_mod
    from ..guest.witness_oracles import WitnessOracles

    try:
        oracles = WitnessOracles(witness, initial_root)
        vb = tl_mod.build_vm_batch(blocks, coarse_log, receipts,
                                   oracles=oracles)
    except tl_mod.NotTransferBatch:
        return "claimed"
    return _mode_of(vb)


def expected_vm_mode(program_input: ProgramInput) -> str:
    """The classifier over a bare ProgramInput (stateless re-execution;
    committers with live artifacts use vm_mode_from_artifacts)."""
    blocks_log: list = []
    receipts: list = []
    output = execution_program(program_input, write_log=blocks_log,
                               receipts_out=receipts)
    return vm_mode_from_artifacts(program_input.blocks, blocks_log,
                                  receipts, program_input.witness,
                                  output.initial_state_root)


def _run_proof_jobs(jobs: list, mesh) -> dict:
    """Run independent STARK proving jobs, concurrently when the mesh
    has devices to split.

    `jobs` is a list of ``(name, group, builder)``; ``builder(job_mesh)``
    generates its trace and returns a proof dict.  With no mesh or a
    1-device mesh jobs run serially on the caller's thread, VM-circuit
    jobs wrapped in the pre-existing ``vm_circuits`` stage span with one
    ``vm_circuits/<air>`` child span each.  Otherwise the mesh is split
    into min(len(jobs), n_devices) disjoint contiguous slices
    (parallel/mesh.py split policy) and one worker thread per slice runs
    its round-robin share of jobs serially, re-entering the caller's
    trace so per-job spans land in the same trace tree; the aggregate
    ``vm_circuits`` wall (first VM start to last VM finish, overlap
    collapsed) is fed to prover_stage_seconds directly.  Proofs are
    bit-identical to the serial path — slicing only changes placement.
    Returns results keyed by job name; a worker exception propagates.
    """
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    from ..parallel import mesh as mesh_lib
    from ..utils import metrics as metrics_mod

    ndev = 1 if mesh is None else int(mesh.devices.size)
    try:
        metrics_mod.record_mesh_devices(ndev)
    except Exception:
        pass

    ckpt_ctx = ckpt_mod.current_context()

    def _run_one(name, group, build, job_mesh, lane=0, lane_devices=1):
        stage = name if group == "vm_circuits" else group
        # the job name scopes this job's phase checkpoints; activate()
        # also re-binds the batch context on pool worker threads
        # (threading.local does not cross ThreadPoolExecutor).  The
        # deviceLane attr routes the span onto its mesh slice's lane in
        # the Perfetto export (tracing.to_trace_events).
        with ckpt_mod.activate(ckpt_ctx, job=name):
            with tracing.span(f"prove.{name}", stage=stage,
                              deviceLane=lane, laneDevices=lane_devices):
                return build(job_mesh)

    def _record_occupancy(lane_timings, lane_devices):
        # occupancy telemetry (perf/occupancy.py): busy intervals per
        # mesh-slice lane, weighted by slice size, against the full
        # ndev mesh — never-raise
        try:
            from ..perf import occupancy as occ_mod

            lanes = {str(i): {"intervals": ivs,
                              "devices": lane_devices.get(i, 1)}
                     for i, ivs in lane_timings.items() if ivs}
            if lanes:
                occ_mod.record_prove(lanes, devices=ndev)
        except Exception:
            pass

    results: dict = {}
    vm_jobs = [j for j in jobs if j[1] == "vm_circuits"]
    if ndev == 1 or len(jobs) == 1:
        try:
            metrics_mod.record_vm_parallelism(1)
        except Exception:
            pass
        serial_ivs: list = []
        for name, group, build in jobs:
            if group != "vm_circuits":
                t0 = _time.perf_counter()
                results[name] = _run_one(name, group, build, mesh,
                                         lane=0, lane_devices=ndev)
                serial_ivs.append((t0, _time.perf_counter()))
        if vm_jobs:
            with tracing.span("prove.vm_proofs", stage="vm_circuits"):
                for name, group, build in vm_jobs:
                    t0 = _time.perf_counter()
                    results[name] = _run_one(name, group, build, mesh,
                                             lane=0, lane_devices=ndev)
                    serial_ivs.append((t0, _time.perf_counter()))
        # one lane carrying the whole mesh: a single-job prove on an
        # N-device mesh still keeps all N devices (weight = ndev, so
        # occupancy reflects mesh-sharded, not sliced, execution)
        _record_occupancy({0: serial_ivs}, {0: ndev})
        return results

    slices = mesh_lib.split_mesh(mesh, len(jobs))
    assigned: list[list] = [[] for _ in slices]
    vm_slices = set()
    for i, job in enumerate(jobs):
        assigned[i % len(slices)].append(job)
        if job[1] == "vm_circuits":
            vm_slices.add(i % len(slices))
    try:
        metrics_mod.record_vm_parallelism(max(1, len(vm_slices)))
    except Exception:
        pass

    cur = tracing.current()
    tid, pid = cur if cur else (None, None)
    timings: dict = {}
    lane_timings: dict = {i: [] for i in range(len(slices))}
    lane_devices = {}
    for i, s in enumerate(slices):
        try:
            lane_devices[i] = max(1, int(s.devices.size))
        except Exception:
            lane_devices[i] = 1

    def _worker(lane, slice_mesh, slice_jobs):
        # re-enter the prove's trace on this thread so every job span
        # (and its stark child spans) joins the same subtree
        with tracing.trace_context(tid, pid):
            for name, group, build in slice_jobs:
                t0 = _time.perf_counter()
                results[name] = _run_one(
                    name, group, build, slice_mesh, lane=lane,
                    lane_devices=lane_devices.get(lane, 1))
                t1 = _time.perf_counter()
                timings[name] = (t0, t1)
                lane_timings[lane].append((t0, t1))

    with ThreadPoolExecutor(max_workers=len(slices)) as pool:
        futs = [pool.submit(_worker, i, s, a)
                for i, (s, a) in enumerate(zip(slices, assigned)) if a]
        for f in futs:
            f.result()

    vm_times = [timings[name] for name, group, _ in jobs
                if group == "vm_circuits" and name in timings]
    if vm_times:
        wall = max(t1 for _, t1 in vm_times) - min(t0 for t0, _ in vm_times)
        try:
            metrics_mod.observe_prover_stage("vm_circuits", wall)
        except Exception:
            pass
    _record_occupancy(lane_timings, lane_devices)
    return results


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def __init__(self, mesh=None):
        # optional jax.sharding.Mesh: every STARK's device phases run
        # sharded across it (stark/prover.py threads the constraints;
        # XLA inserts the collectives).  Proofs are bit-identical to
        # single-chip runs, so verification is unchanged.
        self.mesh = mesh

    def prewarm(self) -> int:
        """Restore phase programs from the on-disk executable cache
        (utils/exec_cache) so the first post-restart proof runs at
        steady-state wall.  Hydration only — this never compiles; shapes
        not yet on disk stay cold until first use, where the per-kernel
        disk lookup still serves them in deserialize time.  Sub-mesh
        entries (split_mesh slices) are not pre-installed here — they
        hydrate from disk inside _aot_phases on first use."""
        from ..stark.prover import hydrate_phase_cache

        count = hydrate_phase_cache(None)
        if self.mesh is not None:
            count += hydrate_phase_cache(self.mesh)
        return count

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        import time as _time

        from ..perf import profiler as perf_profiler

        # one root span per prove so per-stage child spans form a single
        # subtree even when no caller opened a trace (e.g. bench); the
        # profiler.capture is a no-op unless --profile-dir opted in to
        # device tracing
        t0 = _time.perf_counter()
        with tracing.span("backend.prove", format=proof_format):
            with perf_profiler.capture("prove"):
                out = self._prove_impl(program_input, proof_format)
        try:
            from ..utils.metrics import record_proof_wall

            record_proof_wall(_time.perf_counter() - t0)
        except Exception:
            pass
        # refresh device-memory / live-array gauges while the runtime
        # still holds this proof's peak allocations (never raises)
        from ..utils.jax_cache import update_metrics_gauges

        update_metrics_gauges()
        return out

    def _prove_impl(self, program_input: ProgramInput,
                    proof_format: str) -> dict:
        from ..guest import transfer_log as tl_mod
        from ..guest.witness_oracles import WitnessOracles
        from ..models import token_air as tka
        from ..models import transfer_air as ta

        # -- execute phase, checkpointed.  The envelope stores the
        # execution artifacts (output bytes, coarse write log, receipts)
        # so a restarted prover skips the EVM re-execution; the VM-batch
        # classification below is cheap host work recomputed either way.
        ckpt_ctx = ckpt_mod.current_context()
        exe_parts = {"kind": "proof_ckpt", "job": "backend",
                     "phase": "execute", "format": proof_format}
        blocks_log: list = []
        receipts: list = []
        exe_pay = (ckpt_mod.load(ckpt_ctx.batch_id, exe_parts)
                   if ckpt_ctx is not None else None)
        if exe_pay is not None:
            rt.note_resume("execute")
            with tracing.span("prove.execute", stage="execute",
                              resumed=True):
                encoded = exe_pay["encoded"]
                blocks_log = exe_pay["blocks_log"]
                receipts = exe_pay["receipts"]
                initial_root = exe_pay["initial_root"]
        else:
            with tracing.span("prove.execute", stage="execute"):
                output = rt.guard_phase(
                    "execute", "-",
                    lambda: execution_program(program_input,
                                              write_log=blocks_log,
                                              receipts_out=receipts))
                encoded = output.encode()
                initial_root = output.initial_state_root
            if ckpt_ctx is not None:
                ckpt_mod.store(ckpt_ctx.batch_id, exe_parts,
                               {"encoded": encoded,
                                "blocks_log": blocks_log,
                                "receipts": receipts,
                                "initial_root": initial_root},
                               meta={"lease_token": ckpt_ctx.lease_token})
            faults.inject("backend.phase", None, kinds=("drop",))

        vm_batch = None
        try:
            oracles = WitnessOracles(program_input.witness, initial_root)
            vm_batch = tl_mod.build_vm_batch(program_input.blocks,
                                             blocks_log, receipts,
                                             oracles=oracles)
            blocks_log = vm_batch.blocks_log
        except tl_mod.NotTransferBatch:
            pass

        # -- independent STARK jobs: state_proof + the VM-mode circuits.
        # Each job is (name, stage, builder) where builder(mesh) generates
        # its trace and proves on the mesh slice it is handed.  With a
        # multi-device mesh the jobs run CONCURRENTLY on disjoint
        # sub-meshes (parallel/mesh.py split_mesh policy: min(jobs,
        # devices) contiguous slices, every device used, extra jobs
        # round-robined and proven serially within their slice); with no
        # mesh or 1 device they run serially on the main thread.  Proofs
        # are bit-identical either way — sharding and slicing only move
        # layout, never values.
        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        air = sua.StateUpdateAir(depth, seg_periods=S)
        pub = sua.state_update_public_inputs(records, r_pre, r_post, S)

        def _state_job(job_mesh):
            trace = sua.generate_state_update_trace(records, r_pre,
                                                    depth, S)
            return stark_prover.prove(air, trace, pub, PARAMS,
                                      mesh=job_mesh)

        jobs = [("state_proof", "state_proof", _state_job)]

        vm_pub = None
        vm_proof = None
        vm_air = None
        tok_pub = None
        tok_proof = None
        tok_air = None
        bc_pubs: list = []
        bc_proofs: list = []
        bc_airs: list = []
        if vm_batch is not None:
            vm_air = ta.TransferAir()
            vm_pub = ta.transfer_public_inputs(vm_batch.segs)

            def _transfer_job(job_mesh):
                trace = ta.generate_transfer_trace(vm_batch.segs)
                return stark_prover.prove(vm_air, trace, vm_pub,
                                          PARAMS, mesh=job_mesh)

            jobs.append(("vm_circuits/TransferAir", "vm_circuits",
                         _transfer_job))
            if vm_batch.tok_segs:
                tok_air = tka.TokenAir()
                tok_pub = tka.token_public_inputs(vm_batch.tok_segs)

                def _token_job(job_mesh):
                    trace = tka.generate_token_trace(vm_batch.tok_segs)
                    return stark_prover.prove(tok_air, trace, tok_pub,
                                              PARAMS, mesh=job_mesh)

                jobs.append(("vm_circuits/TokenAir", "vm_circuits",
                             _token_job))
            if vm_batch.bc_calls:
                from ..models import bytecode_air as bca

                for idx, call in enumerate(vm_batch.bc_calls):
                    air_bc = bca.BytecodeAir()
                    pub_bc = bca.bytecode_public_inputs(call.steps)
                    bc_airs.append(air_bc)
                    bc_pubs.append(pub_bc)

                    def _bc_job(job_mesh, _air=air_bc, _call=call,
                                _pub=pub_bc):
                        trace = bca.generate_bytecode_trace(
                            _call.steps, _call.snaps)
                        return stark_prover.prove(_air, trace, _pub,
                                                  PARAMS, mesh=job_mesh)

                    jobs.append((f"vm_circuits/BytecodeAir{idx}",
                                 "vm_circuits", _bc_job))

        results = _run_proof_jobs(jobs, self.mesh)
        state_proof = results["state_proof"]
        if vm_batch is not None:
            vm_proof = results["vm_circuits/TransferAir"]
            if vm_batch.tok_segs:
                tok_proof = results["vm_circuits/TokenAir"]
            bc_proofs = [results[f"vm_circuits/BytecodeAir{i}"]
                         for i in range(len(bc_airs))]
        digest = pub[16:24]

        with tracing.span("prove.binding", stage="binding"), \
                ckpt_mod.job_scope("binding"):
            limbs = binding_limbs(encoded, r_pre, r_post, digest, vm_pub,
                                  tok_pub, bc_pubs)
            bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)
            bind_trace = pair.generate_sponge_trace(limbs)
            bind_pub = pair.sponge_public_inputs(limbs)
            bind_proof = stark_prover.prove(bind_air, bind_trace,
                                            bind_pub, PARAMS,
                                            mesh=self.mesh)
        proof = {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "write_log": access_log.raw_log_to_json(blocks_log),
            "depth": depth,
            "seg_periods": S,
            "state_proof": state_proof,
            "proof": bind_proof,
        }
        if vm_batch is not None:
            proof["vm"] = _vm_meta_json(vm_batch)
            proof["vm_proof"] = vm_proof
            if tok_proof is not None:
                proof["tok_proof"] = tok_proof
            if bc_proofs:
                proof["bc_proofs"] = bc_proofs
        if proof_format in (protocol.FORMAT_COMPRESSED,
                            protocol.FORMAT_GROTH16):
            # recursion: one outer STARK proves every inner proof's FRI
            # query openings; their Merkle path data leaves the wire
            from ..stark import aggregate as agg_mod

            airs = [air, bind_air]
            proofs = [state_proof, bind_proof]
            if vm_batch is not None:
                airs.append(vm_air)
                proofs.append(vm_proof)
            if tok_proof is not None:
                airs.append(tok_air)
                proofs.append(tok_proof)
            airs.extend(bc_airs)
            proofs.extend(bc_proofs)
            with tracing.span("prove.aggregate", stage="aggregate"), \
                    ckpt_mod.job_scope("aggregate"):
                agg = agg_mod.aggregate(airs, proofs, PARAMS,
                                        mesh=self.mesh)
            proof["state_proof"], proof["proof"] = agg.inners[:2]
            cursor = 2
            if vm_batch is not None:
                proof["vm_proof"] = agg.inners[cursor]
                cursor += 1
            if tok_proof is not None:
                proof["tok_proof"] = agg.inners[cursor]
                cursor += 1
            if bc_proofs:
                proof["bc_proofs"] = agg.inners[cursor:cursor
                                                + len(bc_proofs)]
            proof["aggregate"] = {
                "outer": agg.outer, "max_depth": agg.max_depth,
                "seg_periods": agg.seg_periods,
            }
            if proof_format == protocol.FORMAT_GROTH16:
                from . import groth16_wrap

                # proof_to_json stays inside the span: it is what forces
                # any still-in-flight device work to the host
                with tracing.span("prove.groth16_wrap",
                                  stage="groth16_wrap"):
                    wrapped = groth16_wrap.wrap_prove(
                        [int(v) for v in agg.outer["pub_inputs"]],
                        rnd=encoded[:32])
                    proof["groth16"] = groth16_wrap.proof_to_json(wrapped)
        return proof

    # -- verification -------------------------------------------------------

    def _reconstruct(self, proof: dict):
        """Rebuild the AIRs and collect the inner STARKs of one batch
        proof, enforcing every public-input binding against the claimed
        log along the way (no STARK verification happens here).  Returns
        (airs, proofs, blocks_log, encoded); shared by `_check` and by
        `stark_components` (the cross-batch aggregation path)."""
        if proof.get("backend") != self.prover_type:
            raise ValueError("wrong backend tag")
        encoded = bytes.fromhex(proof["output"][2:])
        if sum(len(b) for b in proof["write_log"]) > 1_000_000:
            raise ValueError("write log too large")
        blocks_log = access_log.raw_log_from_json(proof["write_log"])

        # recompute the flat commitments from the claimed log; the tree
        # shape is fully determined by the log, so the proof's claimed
        # depth/seg_periods get no attacker freedom (a huge claimed depth
        # would otherwise allocate 2^depth leaves before any AIR check)
        entries = access_log.flatten_entries(blocks_log)
        records, r_pre, r_post, depth = \
            access_log.build_access_records(entries)
        S = _schedule_for(depth)
        if int(proof["depth"]) != depth or int(proof["seg_periods"]) != S:
            raise ValueError("claimed tree shape does not match the log")
        segments = sua.segment_count(len(records))
        digest = sua.log_digest(records, S, segments)

        state = proof["state_proof"]
        claimed_pub = [int(v) % bb.P for v in state["pub_inputs"]]
        if claimed_pub != r_pre + r_post + digest:
            raise ValueError("state proof publics do not match the log")
        air = sua.StateUpdateAir(depth, seg_periods=S)

        # vm mode: the circuits' public digests are recomputed from the
        # SAME claimed log (plus the claimed tx list), so the write log's
        # account values are constrained by EVM transfer semantics and
        # its storage slots by the token-template semantics
        vm_meta = proof.get("vm")
        vm_air = None
        vm_proof = None
        vm_pub = None
        tok_air = None
        tok_proof = None
        tok_pub = None
        bc_pubs: list = []
        bc_proofs: list = []
        bc_airs: list = []
        if vm_meta is not None:
            from ..models import token_air as tka
            from ..models import transfer_air as ta

            items, tok_items, bc_pubs = _vm_stream_from_claims(vm_meta,
                                                               blocks_log)
            vm_pub = ta.vm_digest_stream(items)
            vm_proof = proof["vm_proof"]
            if [int(v) % bb.P for v in vm_proof["pub_inputs"]] != vm_pub:
                raise ValueError("vm proof does not bind this log")
            vm_air = ta.TransferAir()
            if tok_items:
                tok_pub = tka.tok_digest_stream(tok_items)
                tok_proof = proof["tok_proof"]
                if [int(v) % bb.P for v in tok_proof["pub_inputs"]] != \
                        tok_pub:
                    raise ValueError("token proof does not bind this log")
                tok_air = tka.TokenAir()
            if bc_pubs:
                from ..models import bytecode_air as bca

                bc_proofs = proof.get("bc_proofs") or []
                if len(bc_proofs) != len(bc_pubs):
                    raise ValueError("generic proof count mismatch")
                for p, pub in zip(bc_proofs, bc_pubs):
                    if [int(v) % bb.P for v in p["pub_inputs"]] != pub:
                        raise ValueError(
                            "generic proof does not bind its steps")
                    bc_airs.append(bca.BytecodeAir())

        limbs = binding_limbs(encoded, r_pre, r_post, digest, vm_pub,
                              tok_pub, bc_pubs)
        bind = proof["proof"]
        if [int(v) for v in bind["pub_inputs"][:len(limbs)]] != limbs:
            raise ValueError("binding proof does not bind this statement")
        bind_air = pair.Poseidon2SpongeAir(num_chunks=len(limbs) // 8)

        airs = [air, bind_air]
        proofs = [state, bind]
        if vm_air is not None:
            airs.append(vm_air)
            proofs.append(vm_proof)
        if tok_air is not None:
            airs.append(tok_air)
            proofs.append(tok_proof)
        airs.extend(bc_airs)
        proofs.extend(bc_proofs)
        return airs, proofs, blocks_log, encoded

    def stark_components(self, proof: dict):
        """The (airs, inner STARK proofs) of a FORMAT_STARK batch proof,
        FRI paths intact, publics validated against the claimed log —
        the raw material l2/aggregator.py feeds into
        stark.aggregate.aggregate_groups for cross-batch recursion."""
        if proof.get("aggregate") is not None:
            raise ValueError("proof is already aggregated: its inner FRI "
                             "paths are gone and cannot be re-aggregated")
        airs, proofs, _, _ = self._reconstruct(proof)
        return airs, proofs

    def _check(self, proof: dict):
        """Shared verification core; returns the parsed raw log + claimed
        output bytes, or raises."""
        airs, proofs, blocks_log, encoded = self._reconstruct(proof)

        agg_info = proof.get("aggregate")
        if agg_info is not None:
            # compressed/groth16: every proof verified through the outer
            # recursion STARK (their FRI paths are gone from the wire)
            from ..stark import aggregate as agg_mod

            agg = agg_mod.AggregateProof(
                inners=proofs, outer=agg_info["outer"],
                max_depth=int(agg_info["max_depth"]),
                seg_periods=int(agg_info["seg_periods"]))
            agg_mod.verify_aggregated(airs, agg, PARAMS)
            wrapped = proof.get("groth16")
            if wrapped is not None:
                from . import groth16_wrap

                if not groth16_wrap.wrap_verify(
                        groth16_wrap.proof_from_json(wrapped),
                        [int(v) for v in agg.outer["pub_inputs"]]):
                    raise ValueError("groth16 wrap rejected")
        else:
            for a, p in zip(airs, proofs):
                if not stark_verifier.verify(a, p, PARAMS):
                    raise ValueError("proof rejected")
        return blocks_log, encoded

    def verify(self, proof: dict) -> bool:
        try:
            self._check(proof)
            return True
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False

    def verify_submission(self, proof: dict) -> bool:
        """Structural gate only: the full STARK audit is expensive and
        stays in send_proofs (verify_with_input); at submit time the
        coordinator just needs enough shape to reject wire corruption and
        free the assignment slot for honest provers."""
        try:
            bytes.fromhex(proof["output"][2:])
            return (proof.get("backend") == self.prover_type
                    and isinstance(proof.get("proof"), dict)
                    and isinstance(proof.get("state_proof"), dict)
                    and isinstance(proof.get("write_log"), list))
        except (KeyError, TypeError, ValueError):
            return False

    def check_coverage(self, proof: dict, expected_mode: str) -> bool:
        """Reject mode downgrades WITHOUT the witness: the committer
        derived `expected_mode` by running the same deterministic
        classifier the honest prover runs, so any other mode on the wire
        is a forgery attempt (most importantly claimed-log for a batch
        the circuits cover)."""
        if not expected_mode:
            return True    # pre-metadata batches: no constraint
        vm = proof.get("vm")
        actual = vm.get("mode") if isinstance(vm, dict) else "claimed"
        return actual == expected_mode

    def verify_with_input(self, proof: dict,
                          program_input: ProgramInput) -> bool:
        """Full audit: every STARK + the witness MPT replay (trie ops
        only, no EVM) against the claimed initial/final state roots; in
        vm mode, the claimed tx metadata is REBUILT from the batch's
        signed txs + a re-execution (closing the wire-verifier's
        authenticity gaps: tx list, per-tx gas, and the token template's
        code hash, which build_vm_batch pins against the real pre-state);
        plus a downgrade check: a batch the circuits cover must carry
        the vm proofs."""
        from ..guest.execution import ProgramOutput
        from ..guest.transfer_log import (NotTransferBatch, build_vm_batch,
                                          is_generic_call_shape,
                                          is_plain_transfer,
                                          is_token_call_shape)
        from ..guest.witness_oracles import WitnessOracles

        try:
            blocks_log, encoded = self._check(proof)
            output = ProgramOutput.decode(encoded)
            access_log.replay_log_against_witness(
                blocks_log, program_input.witness.nodes,
                output.initial_state_root, output.final_state_root)
            oracles = WitnessOracles(program_input.witness,
                                     output.initial_state_root)
            vm_meta = proof.get("vm")
            if vm_meta is None:
                # downgrade check: a batch the circuits cover must carry
                # the vm proofs.  The static predicate over-approximates
                # the circuits' scope (a generic-shape call may still
                # leave the executed subset), so on ambiguity re-derive
                # applicability exactly as the prover would.
                if not all(is_plain_transfer(tx) or is_token_call_shape(tx)
                           or is_generic_call_shape(tx)
                           for blk in program_input.blocks
                           for tx in blk.body.transactions):
                    return True
                try:
                    coarse: list = []
                    receipts: list = []
                    execution_program(program_input, write_log=coarse,
                                      receipts_out=receipts)
                    build_vm_batch(program_input.blocks, coarse, receipts,
                                   oracles=oracles)
                except NotTransferBatch:
                    return True
                return False
            # rebuild the vm metadata from the real signed txs and a
            # re-execution; claimed metadata must match it exactly
            try:
                coarse = []
                receipts = []
                execution_program(program_input, write_log=coarse,
                                  receipts_out=receipts)
                rebuilt = build_vm_batch(program_input.blocks, coarse,
                                         receipts, oracles=oracles)
            except NotTransferBatch:
                return False
            return _vm_meta_json(rebuilt) == vm_meta
        except (KeyError, ValueError, TypeError, IndexError,
                access_log.LogAuditError,
                stark_verifier.VerificationError):
            return False
