"""TPU prover backend: the `--prover tpu` seam (SURVEY.md north star).

Round-1 scope: the guest program runs natively on the host, and the TPU
produces an **output-binding STARK** — a real DEEP-FRI proof (device LDE +
Poseidon2 Merkle + FRI) of the in-circuit **Poseidon2 compression** of the
ProgramOutput digest (models/poseidon2_air.py), verified by the independent
host verifier.  The bound digest uses the same Poseidon2 as the framework's
Merkle commitments, so the statement is "I know the 16-limb encoding of the
claimed batch output whose Poseidon2 compression is this digest".

What it does NOT yet prove: the EVM execution itself.  That requires the VM
AIR (the reference delegates this to its zkVM SDKs; our equivalent is the
arithmetization of guest/execution.py — the Poseidon2 AIR here is its first
building block).  Until then the execution-trust level matches the
reference's exec backend, with real TPU proving work end to end.
"""

from __future__ import annotations

from ..crypto.keccak import keccak256
from ..guest.execution import ProgramInput
from ..models import poseidon2_air as pair
from ..stark import prover as stark_prover
from ..stark import verifier as stark_verifier
from ..stark.prover import StarkParams
from . import protocol
from .backend import ProverBackend

PARAMS = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)


def output_to_limbs(output_bytes: bytes) -> list[int]:
    """ProgramOutput.encode() -> 16 BabyBear limbs via keccak expansion."""
    h1 = keccak256(b"ethrex-tpu/output-binding/1" + output_bytes)
    h2 = keccak256(b"ethrex-tpu/output-binding/2" + output_bytes)
    limbs = []
    for h in (h1, h2):
        for i in range(8):
            limbs.append(int.from_bytes(h[4 * i:4 * i + 3], "big"))  # 24-bit
    return limbs


class TpuBackend(ProverBackend):
    prover_type = protocol.PROVER_TPU

    def __init__(self):
        self.air = pair.Poseidon2Air()

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        output = self.execute(program_input)
        encoded = output.encode()
        limbs = output_to_limbs(encoded)
        trace = pair.generate_trace(limbs)
        pub = pair.public_inputs(limbs)
        stark = stark_prover.prove(self.air, trace, pub, PARAMS)
        return {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + encoded.hex(),
            "proof": stark,
        }

    def verify(self, proof: dict) -> bool:
        if proof.get("backend") != self.prover_type:
            return False
        try:
            encoded = bytes.fromhex(proof["output"][2:])
            stark = proof["proof"]
            limbs = output_to_limbs(encoded)
            # the proof's public inputs must bind the claimed output limbs
            if [int(v) for v in stark["pub_inputs"][:16]] != limbs:
                return False
            return stark_verifier.verify(self.air, stark, PARAMS)
        except (KeyError, ValueError, TypeError,
                stark_verifier.VerificationError):
            return False
