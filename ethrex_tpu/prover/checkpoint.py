"""Phase-level proof checkpoints: crash-only proving for `TpuBackend`.

A prove is a sequence of device phases (execute -> per-AIR
commit/quotient/open/fri -> binding/aggregate) stitched together by a
host Fiat-Shamir transcript.  Each completed phase persists ONE
content-addressed envelope here — the phase's host-visible artifacts,
numpy copies of the device intermediates the later phases consume, and
a snapshot of the transcript sponge — so a restarted `ProverClient`
holding a *fresh lease for the same batch* replays the transcript from
the last completed phase instead of re-proving from scratch.  Bounded
loss is <= 1 phase (the one in flight when the process died) and the
resumed proof is byte-identical: all arithmetic is exact u32 and the
sponge snapshot pins every later challenge.

Key schema (docs/PROVER_RESILIENCE.md "Runtime failures"): an entry's
filename is the SHA-256 over the JSON-canonical key parts — batch id,
job name, AIR cache key, trace shape, STARK params, phase — joined
with the environment half (code fingerprint, jax/jaxlib versions,
shared with utils/exec_cache).  The *mesh layout* and *lease token*
are deliberately recorded as envelope metadata, NOT key material:
proofs are bit-identical across mesh layouts, so the degradation
ladder (prover/runtime_errors) must be able to resume a phase prefix
written at mesh=2x4 on a single device, and a restarted client always
holds a fresh token for the same batch.

Records are written atomically (tempfile + os.replace) and framed as
MAGIC | crc32 | length | pickle-blob; a torn, truncated or garbage
blob fails the frame check and is discarded for a clean fresh prove
(`proof_ckpt_discards_total`) — the loader never raises.

Env knobs (documented in docs/PROVER_RESILIENCE.md):
  ETHREX_PROOF_CKPT_DIR  checkpoint directory (default
                         /tmp/ethrex_tpu_proof_ckpt_<host fingerprint>)
  ETHREX_PROOF_CKPT_OFF  "1" disables checkpoint stores and loads
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
import zlib

_SCHEMA = 1
_MAGIC = b"ETPC"
_SUFFIX = ".ckpt"

_LOCK = threading.Lock()
_CONFIGURED_DIR: str | None = None
STATS = {"stores": 0, "loads": 0, "discards": 0}

# The per-thread prove context: ProverClient activates one around
# backend.prove; TpuBackend re-activates it on its job worker threads
# (threading.local does not inherit across ThreadPoolExecutor workers,
# same re-entry discipline as tracing.trace_context).
_TLS = threading.local()


class BatchContext:
    """Mutable per-batch prove state shared between the prove thread(s)
    and the heartbeat thread: identity (batch id + the lease token that
    granted this attempt), the in-flight phase for heartbeat stamping,
    and any mesh downgrade the degradation ladder applied."""

    def __init__(self, batch_id, lease_token=None):
        self.batch_id = batch_id
        self.lease_token = lease_token
        self.lock = threading.Lock()
        self.phase: str | None = None
        self.phase_started: float | None = None
        self.degraded: dict | None = None
        self.resumes = 0

    def set_phase(self, phase: str | None) -> None:
        with self.lock:
            if phase != self.phase:
                self.phase = phase
                self.phase_started = time.time()

    def note_degraded(self, frm: str, to: str) -> None:
        with self.lock:
            if self.degraded is None:
                self.degraded = {"from": frm, "to": to}
            else:
                # ladder walked further down: keep the original rung as
                # the origin, report the latest rung as the floor
                self.degraded = {"from": self.degraded["from"], "to": to}

    def snapshot(self) -> dict:
        """Heartbeat-safe copy of the advisory fields."""
        with self.lock:
            out = {"phase": self.phase, "phase_started": self.phase_started}
            if self.degraded is not None:
                out["degraded"] = dict(self.degraded)
            return out


def current_context() -> BatchContext | None:
    return getattr(_TLS, "ctx", None)


def current_job() -> str | None:
    return getattr(_TLS, "job", None)


@contextlib.contextmanager
def activate(ctx: BatchContext | None, job: str | None = None):
    """Bind a batch context (and optionally a job name) to this thread.
    `batch_context` uses it on the client thread; TpuBackend's job
    workers re-enter with the parent's context."""
    prev_ctx = getattr(_TLS, "ctx", None)
    prev_job = getattr(_TLS, "job", None)
    _TLS.ctx = ctx
    if job is not None:
        _TLS.job = job
    try:
        yield ctx
    finally:
        _TLS.ctx = prev_ctx
        _TLS.job = prev_job


@contextlib.contextmanager
def batch_context(batch_id, lease_token=None):
    """Open (or reopen, after a restart) the checkpointed prove of one
    batch.  The yielded context carries the advisory state the
    heartbeat thread reports (in-flight phase, degradation)."""
    ctx = BatchContext(batch_id, lease_token=lease_token)
    with activate(ctx):
        yield ctx


@contextlib.contextmanager
def job_scope(job: str):
    """Name the prove job (state_proof / vm_circuits/TransferAir /
    binding / ...) for every checkpoint written under it."""
    prev = getattr(_TLS, "job", None)
    _TLS.job = job
    try:
        yield
    finally:
        _TLS.job = prev


# -- store layout -----------------------------------------------------------

def set_checkpoint_dir(path: str | None) -> None:
    """Explicit directory override (tests); beats the env knob."""
    global _CONFIGURED_DIR
    with _LOCK:
        _CONFIGURED_DIR = path


def checkpoint_dir() -> str:
    with _LOCK:
        configured = _CONFIGURED_DIR
    if configured:
        return configured
    env = os.environ.get("ETHREX_PROOF_CKPT_DIR")
    if env:
        return env
    from ..utils.jax_cache import cache_dir as _fingerprinted

    return _fingerprinted(prefix="/tmp/ethrex_tpu_proof_ckpt")


def enabled() -> bool:
    return os.environ.get("ETHREX_PROOF_CKPT_OFF") != "1"


def record_ckpt_store() -> None:
    from ..utils.metrics import METRICS

    METRICS.inc("proof_ckpt_stores_total", 1,
                "Proof phase checkpoints persisted: completed prove "
                "phases a restarted prover can resume from")


def record_ckpt_load() -> None:
    from ..utils.metrics import METRICS

    METRICS.inc("proof_ckpt_loads_total", 1,
                "Proof phase checkpoints loaded on resume: phases "
                "skipped instead of re-proven after a restart")


def record_ckpt_discard() -> None:
    from ..utils.metrics import METRICS

    METRICS.inc("proof_ckpt_discards_total", 1,
                "Proof phase checkpoints discarded as torn, truncated "
                "or garbage: the prove falls back to a fresh run")


def _batch_dir(batch_id) -> str:
    tag = hashlib.sha256(repr(batch_id).encode()).hexdigest()[:16]
    return os.path.join(checkpoint_dir(), f"batch_{tag}")


def _entry_path(batch_id, parts: dict) -> str:
    from ..utils import exec_cache

    key = {"schema": _SCHEMA, "parts": parts,
           "env": {"code": exec_cache._code_fingerprint(),
                   **{k: v for k, v in exec_cache._env_parts().items()
                      if k in ("jax", "jaxlib")}}}
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"),
                      default=str)
    digest = hashlib.sha256(blob.encode()).hexdigest()
    return os.path.join(_batch_dir(batch_id), digest + _SUFFIX)


def store(batch_id, parts: dict, payload, meta: dict | None = None) -> bool:
    """Persist one phase envelope; atomic and never raises.  Returns
    True when the record landed."""
    if not enabled():
        return False
    try:
        blob = pickle.dumps({"schema": _SCHEMA, "parts": parts,
                             "meta": dict(meta or {}), "payload": payload},
                            protocol=pickle.HIGHEST_PROTOCOL)
        frame = (_MAGIC + zlib.crc32(blob).to_bytes(4, "big")
                 + len(blob).to_bytes(8, "big") + blob)
        path = _entry_path(batch_id, parts)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(frame)
            os.replace(tmp, path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        with _LOCK:
            STATS["stores"] += 1
        record_ckpt_store()
        return True
    except Exception:
        return False


def load(batch_id, parts: dict):
    """Load one phase envelope's payload, or None.  A torn/garbage blob
    is unlinked and counted (`proof_ckpt_discards_total`) — the caller
    simply re-proves the phase; this never raises."""
    if not enabled():
        return None
    path = _entry_path(batch_id, parts)
    try:
        with open(path, "rb") as f:
            frame = f.read()
    except OSError:
        return None
    try:
        if frame[:4] != _MAGIC or len(frame) < 16:
            raise ValueError("bad magic")
        crc = int.from_bytes(frame[4:8], "big")
        length = int.from_bytes(frame[8:16], "big")
        blob = frame[16:]
        if len(blob) != length or zlib.crc32(blob) != crc:
            raise ValueError("torn record")
        rec = pickle.loads(blob)
        if rec.get("schema") != _SCHEMA or rec.get("parts") != parts:
            raise ValueError("key mismatch")
        with _LOCK:
            STATS["loads"] += 1
        record_ckpt_load()
        return rec["payload"]
    except Exception:
        with contextlib.suppress(OSError):
            os.unlink(path)
        with _LOCK:
            STATS["discards"] += 1
        record_ckpt_discard()
        return None


def complete(batch_id) -> None:
    """Drop every checkpoint of a settled batch (proof accepted): the
    envelope is recovery state, not an artifact."""
    bdir = _batch_dir(batch_id)
    try:
        names = os.listdir(bdir)
    except OSError:
        return
    for name in names:
        with contextlib.suppress(OSError):
            os.unlink(os.path.join(bdir, name))
    with contextlib.suppress(OSError):
        os.rmdir(bdir)


def runtime_stats() -> dict:
    """Live view for ethrex_health (l2.prover.runtime.checkpoints)."""
    with _LOCK:
        out = dict(STATS)
    out["enabled"] = enabled()
    try:
        out["batches"] = sum(
            1 for n in os.listdir(checkpoint_dir())
            if n.startswith("batch_"))
    except OSError:
        out["batches"] = 0
    return out


class PhaseStore:
    """Checkpoint handle for one job's phase sequence: fixes the
    identity parts (batch, job, air, shape, params) so the prover only
    names the phase.  `meta` (lease token, mesh label) is recorded on
    every envelope for forensics but never addresses it."""

    def __init__(self, ctx: BatchContext, job: str, air_key, log_n: int,
                 params_key, mesh_label: str):
        self.ctx = ctx
        self.batch_id = ctx.batch_id
        self.base = {"kind": "proof_ckpt", "job": job,
                     "air": repr(air_key), "log_n": int(log_n),
                     "params": repr(params_key)}
        self.meta = {"lease_token": ctx.lease_token, "mesh": mesh_label}

    def _parts(self, phase: str) -> dict:
        parts = dict(self.base)
        parts["phase"] = phase
        return parts

    def load(self, phase: str):
        return load(self.batch_id, self._parts(phase))

    def store(self, phase: str, payload, mesh_label: str | None = None):
        meta = dict(self.meta)
        if mesh_label is not None:
            meta["mesh"] = mesh_label
        return store(self.batch_id, self._parts(phase), payload, meta=meta)


def phase_store(air_key, log_n: int, params_key,
                mesh_label: str = "none") -> PhaseStore | None:
    """The stark prover's entry point: a PhaseStore bound to the active
    batch context and job scope, or None when checkpointing is off or
    the prove runs outside a batch (bench, direct API use)."""
    if not enabled():
        return None
    ctx = current_context()
    if ctx is None:
        return None
    job = current_job() or "-"
    return PhaseStore(ctx, job, air_key, log_n, params_key, mesh_label)
