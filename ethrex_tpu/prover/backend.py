"""ProverBackend interface + registry (parity with the reference's
ProverBackend trait, crates/prover/src/backend/mod.rs:81-147 — prover_type /
execute / prove / verify / to_proof_bytes)."""

from __future__ import annotations

from ..guest.execution import ProgramInput, ProgramOutput, execution_program
from . import protocol


class ProverBackend:
    prover_type: str = ""

    def execute(self, program_input: ProgramInput) -> ProgramOutput:
        """Run the guest program natively (no proof)."""
        return execution_program(program_input)

    def prewarm(self) -> int:
        """Hydrate whatever compiled artifacts this backend can restore
        from the on-disk executable cache (utils/exec_cache) before its
        first assignment; returns how many kernel groups came back.
        Backends with no AOT-compiled programs have nothing to restore."""
        return 0

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        raise NotImplementedError

    def verify(self, proof: dict) -> bool:
        raise NotImplementedError

    def check_coverage(self, proof: dict, expected_mode: str) -> bool:
        """Anti-downgrade hook: does this proof carry the VM-circuit
        coverage the batch's committer derived?  Backends without VM
        modes accept everything."""
        return True

    def verify_submission(self, proof: dict) -> bool:
        """Coordinator-side gate at ProofSubmit time: reject a corrupt
        proof immediately so the batch is re-assignable instead of
        stalling until send_proofs' full audit.  Must be cheap — backends
        whose verify() is expensive override with a structural check."""
        try:
            return self.verify(proof)
        except Exception:  # noqa: BLE001 — any crash on a submit is a no
            return False   # (the proof came off the wire untrusted)

    def to_proof_bytes(self, proof: dict) -> bytes:
        import json

        return json.dumps(proof, separators=(",", ":")).encode()


class ExecBackend(ProverBackend):
    """The 'fake prover': executes natively, returns an empty proof —
    unblocks full-pipeline integration exactly like the reference's exec
    backend (crates/prover/src/backend/exec.rs)."""

    prover_type = protocol.PROVER_EXEC

    def prove(self, program_input: ProgramInput, proof_format: str) -> dict:
        from ..utils import tracing

        # a stage span even on the exec path: an exec-backed fleet's
        # shipped span subtree still carries per-stage attribution for
        # the merged batch trace (docs/OBSERVABILITY.md)
        with tracing.span("prover.execute", stage="execute"):
            output = self.execute(program_input)
        return {
            "backend": self.prover_type,
            "format": proof_format,
            "output": "0x" + output.encode().hex(),
            "proof": None,
        }

    def verify(self, proof: dict) -> bool:
        if proof.get("backend") != self.prover_type:
            return False
        try:
            from ..guest.execution import ProgramOutput

            ProgramOutput.decode(bytes.fromhex(proof["output"][2:]))
            return True
        except (KeyError, TypeError, ValueError):
            return False


def get_backend(name: str) -> ProverBackend:
    from .tpu_backend import TpuBackend

    backends = {
        protocol.PROVER_EXEC: ExecBackend,
        protocol.PROVER_TPU: TpuBackend,
    }
    cls = backends.get(name)
    if cls is None:
        raise ValueError(f"unknown prover backend {name!r}")
    return cls()
