"""Runtime-error taxonomy and the degraded-mesh fallback ladder.

A crash inside the 86-96s `TpuBackend.prove` wall used to be
indistinguishable from a poison batch: any exception burned the
coordinator's quarantine budget and could downgrade a perfectly
provable batch to the exec fallback.  This module classifies what the
accelerator runtime actually threw and routes each class differently:

    oom          XLA RESOURCE_EXHAUSTED / allocation failure — the
                 batch does not fit the current mesh.  Transient:
                 retry the failed phase down the degradation ladder
                 (mesh/2 -> single device -> forced CPU); never burns
                 quarantine budget.
    device_lost  a device or slice dropped out (connection to the
                 accelerator lost, slice health check failed, or the
                 injected `device.lost` fault).  Transient: same
                 ladder.
    nan_poison   a phase produced non-finite or out-of-field outputs —
                 the trace itself is poisoned, retrying cannot help.
                 Quarantined immediately with the offending phase
                 named; zero retries.
    unknown      everything else propagates unchanged (a genuine bug
                 should fail loudly, not hide behind a retry loop).

The ladder reuses the existing machinery end to end: rungs are built
with `parallel.mesh` device slicing, phase programs for a fallback
layout hydrate through the same `stark/prover._phases` path (PR-12
exec-cache hydration applies), and completed-phase checkpoints
(prover/checkpoint) carry across rungs because proofs are
bit-identical on any layout.  A `memory_gate` consults the AOT
roofline bytes (`perf/roofline`, captured at compile time) against
live device memory (`utils/jax_cache.runtime_telemetry`) to walk the
same ladder BEFORE an OOM instead of after.

Env knobs (documented in docs/PROVER_RESILIENCE.md):
  ETHREX_MESH_DEGRADE_OFF    "1" disables the ladder and the memory
                             gate (transient errors propagate)
  ETHREX_MEM_GATE_HEADROOM   fraction of free device memory the
                             estimated working set may fill before the
                             gate shrinks the mesh (default 0.8)
"""

from __future__ import annotations

import os
import threading

from ..utils import faults

try:  # jax.errors.JaxRuntimeError IS jaxlib's XlaRuntimeError
    from jax.errors import JaxRuntimeError as XlaRuntimeError
except Exception:  # pragma: no cover - jax always present in-tree
    class XlaRuntimeError(RuntimeError):
        """Stand-in when jax is unavailable (doc builds, lint)."""


_LOCK = threading.Lock()
STATS = {"oom_retries": 0, "device_lost_retries": 0, "nan_poisons": 0,
         "degradations": 0, "memory_gate_shrinks": 0, "phase_resumes": 0}
_LAST_DEGRADATION: dict | None = None

_OOM_MARKERS = ("resource_exhausted", "out of memory", "out_of_memory",
                "failed to allocate", "allocation failure", "oom")
_DEVICE_LOST_MARKERS = ("device.lost", "device lost", "device_lost",
                        "device failed", "device halted", "data loss",
                        "dataloss", "tpu slice", "slice health",
                        "ici failure", "lost connection to the device")


class NanPoisonError(RuntimeError):
    """A phase emitted non-finite / out-of-field values: the batch is
    poisoned, not the runtime.  Carries the offending phase so the
    quarantine reason names it."""

    def __init__(self, phase: str, detail: str = ""):
        self.phase = phase
        self.detail = detail
        super().__init__(
            f"non-finite/out-of-field output in phase {phase!r}"
            + (f": {detail}" if detail else ""))


class TransientPhaseError(RuntimeError):
    """Internal routing signal: a phase failed with a transient class
    (`oom` / `device_lost`); the prove loop retries it down the
    degradation ladder instead of failing the lease."""

    def __init__(self, kind: str, phase: str, cause: BaseException):
        self.kind = kind
        self.phase = phase
        self.cause = cause
        super().__init__(f"{kind} in phase {phase!r}: {cause}")


def classify(exc: BaseException) -> str:
    """Map an exception from a device phase onto the taxonomy."""
    if isinstance(exc, NanPoisonError):
        return "nan_poison"
    if isinstance(exc, TransientPhaseError):
        return exc.kind
    msg = str(exc).lower()
    for marker in _OOM_MARKERS:
        if marker in msg:
            return "oom"
    for marker in _DEVICE_LOST_MARKERS:
        if marker in msg:
            return "device_lost"
    if isinstance(exc, MemoryError):
        return "oom"
    return "unknown"


def _walk_values(value):
    """Yield every scalar reachable in a phase-artifact structure."""
    import numpy as np

    if isinstance(value, dict):
        for v in value.values():
            yield from _walk_values(v)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _walk_values(v)
    elif isinstance(value, np.ndarray):
        yield value
    elif isinstance(value, (int, float, np.integer, np.floating)):
        yield value


def check_phase_outputs(phase: str, arts) -> None:
    """Validate the host-visible artifacts of a completed phase: every
    field element canonical-range (< BabyBear P), every float finite.
    A violation is a poisoned batch, raised as NanPoisonError."""
    import numpy as np

    from ..ops import babybear as bb

    if isinstance(arts, dict) and arts.get("__corrupt__"):
        _note_nan_poison(phase)
        raise NanPoisonError(phase, "corrupted artifact envelope")
    for v in _walk_values(arts):
        if isinstance(v, np.ndarray):
            if np.issubdtype(v.dtype, np.floating):
                if not np.all(np.isfinite(v)):
                    _note_nan_poison(phase)
                    raise NanPoisonError(phase, "non-finite array value")
            elif np.issubdtype(v.dtype, np.integer):
                if v.size and int(v.max(initial=0)) >= bb.P:
                    _note_nan_poison(phase)
                    raise NanPoisonError(phase, "out-of-field array value")
        elif isinstance(v, float):
            if v != v or v in (float("inf"), float("-inf")):
                _note_nan_poison(phase)
                raise NanPoisonError(phase, "non-finite value")
        else:
            if not 0 <= int(v) < bb.P:
                _note_nan_poison(phase)
                raise NanPoisonError(phase, "out-of-field value")


def guard_phase(phase: str, air_name: str, fn):
    """Run one device phase under the fault legs and the taxonomy.

    Fires the `backend.phase` error/delay legs and the `device.lost`
    site on entry (an error rule there simulates a slice dropping out
    mid-phase), then classifies anything `fn` raises: transient
    classes re-raise as TransientPhaseError for the ladder, poison and
    unknown classes propagate.  Stamps the in-flight phase on the
    active batch context so heartbeats report it (and the hedging
    deadline re-anchors on every transition)."""
    from . import checkpoint

    ctx = checkpoint.current_context()
    if ctx is not None:
        job = checkpoint.current_job()
        ctx.set_phase(f"{job}.{phase}" if job else phase)
    try:
        faults.inject("backend.phase", {"phase": phase, "air": air_name},
                      kinds=("error", "delay"))
        faults.inject("device.lost")
        return fn()
    except (NanPoisonError, TransientPhaseError):
        raise
    except Exception as exc:
        kind = classify(exc)
        if kind in ("oom", "device_lost"):
            raise TransientPhaseError(kind, phase, exc) from exc
        raise


def screen_outputs(phase: str, arts):
    """The nan/corrupt leg: offer the phase's host artifacts to the
    `backend.phase` corrupt rules, then range-check what (possibly
    mangled) came back.  Returns the artifacts for downstream use."""
    arts = faults.inject("backend.phase", arts, kinds=("corrupt", "torn"))
    check_phase_outputs(phase, arts)
    return arts


# -- degradation ladder -----------------------------------------------------

def ladder_enabled() -> bool:
    return os.environ.get("ETHREX_MESH_DEGRADE_OFF") != "1"


def _mesh_identity(mesh):
    if mesh is None:
        return None
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(getattr(d, "platform", "?") for d in mesh.devices.flat))


def degradation_ladder(mesh) -> list:
    """The fallback rungs below `mesh`, best first: half the devices,
    a single device, then forced CPU.  Rungs equal to the current
    layout are dropped; an empty list means nowhere left to fall."""
    if not ladder_enabled():
        return []
    import numpy as np

    from jax.sharding import Mesh

    from ..parallel import mesh as mesh_lib

    rungs, seen = [], {_mesh_identity(mesh)}

    def push(m):
        key = _mesh_identity(m)
        if key not in seen:
            seen.add(key)
            rungs.append(m)

    if mesh is not None:
        devs = list(mesh.devices.flat)
        if len(devs) >= 4:
            push(Mesh(np.array(devs[: len(devs) // 2]), (mesh_lib.AXIS,)))
        if len(devs) >= 2:
            push(Mesh(np.array(devs[:1]), (mesh_lib.AXIS,)))
    try:  # forced-CPU floor: host cores always exist and never OOM first
        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        push(Mesh(np.array([cpu]), (mesh_lib.AXIS,)))
    except Exception:
        if mesh is not None:
            push(None)
    return rungs


def note_resume(phase: str) -> None:
    """One completed phase skipped on restart (loaded from checkpoint)."""
    with _LOCK:
        STATS["phase_resumes"] += 1
    from ..utils.metrics import record_phase_resume

    record_phase_resume(phase)
    from . import checkpoint

    ctx = checkpoint.current_context()
    if ctx is not None:
        with ctx.lock:
            ctx.resumes += 1


def note_transient_retry(kind: str, phase: str) -> None:
    with _LOCK:
        key = "oom_retries" if kind == "oom" else "device_lost_retries"
        STATS[key] += 1
    from ..utils.metrics import record_oom_retry

    record_oom_retry(phase)


def note_degradation(frm_label: str, to_label: str,
                     reason: str = "ladder") -> None:
    global _LAST_DEGRADATION
    with _LOCK:
        STATS["degradations"] += 1
        if reason == "memory_gate":
            STATS["memory_gate_shrinks"] += 1
        _LAST_DEGRADATION = {"from": frm_label, "to": to_label,
                             "reason": reason}
    from ..utils.metrics import record_mesh_degradation

    record_mesh_degradation(frm_label, to_label)
    from . import checkpoint

    ctx = checkpoint.current_context()
    if ctx is not None:
        ctx.note_degraded(frm_label, to_label)


def _note_nan_poison(phase: str) -> None:
    with _LOCK:
        STATS["nan_poisons"] += 1
    from ..utils.metrics import record_nan_poison

    record_nan_poison(phase)


# -- pre-prove memory gate --------------------------------------------------

def _estimated_bytes(air_name: str):
    """Peak per-phase bytes for this AIR from the AOT roofline records
    (cost_analysis captured at compile time); None without data."""
    try:
        from ..perf import roofline

        best = None
        for cell in roofline.report().get("kernels", []):
            if cell.get("air") != air_name:
                continue
            b = cell.get("bytes")
            if b and (best is None or b > best):
                best = float(b)
        return best
    except Exception:
        return None


def _available_bytes(mesh):
    """Free accelerator memory across the layout's devices from live
    telemetry; None when the backend does not report limits (CPU)."""
    try:
        from ..utils.jax_cache import runtime_telemetry

        ids = (None if mesh is None
               else {int(d.id) for d in mesh.devices.flat})
        total = 0
        saw = False
        for dev in runtime_telemetry().get("devices", []):
            if ids is not None and dev.get("id") not in ids:
                continue
            memory = dev.get("memory") or {}
            limit = memory.get("bytes_limit")
            if not limit:
                continue
            total += max(0, int(limit) - int(memory.get("bytes_in_use", 0)))
            saw = True
        return total if saw else None
    except Exception:
        return None


def memory_gate(air_name: str, mesh, est_bytes=None, avail_fn=None):
    """Shrink the mesh BEFORE an OOM: if the AIR's estimated working
    set exceeds the headroom share of free device memory on the
    current layout, walk the degradation ladder until a rung fits (a
    rung with unreported limits — CPU — always fits).  Returns the
    layout to prove on; identical to `mesh` when data is missing or
    everything fits."""
    if not ladder_enabled():
        return mesh
    est = est_bytes if est_bytes is not None else _estimated_bytes(air_name)
    if est is None:
        return mesh
    try:
        headroom = float(os.environ.get("ETHREX_MEM_GATE_HEADROOM", "0.8"))
    except ValueError:
        headroom = 0.8
    avail_of = avail_fn or _available_bytes
    from ..parallel import mesh as mesh_lib

    cur = mesh
    avail = avail_of(cur)
    if avail is None or est <= headroom * avail:
        return cur
    for rung in degradation_ladder(cur):
        avail = avail_of(rung)
        fits = avail is None or est <= headroom * avail
        note_degradation(mesh_lib.shape_label(cur),
                         mesh_lib.shape_label(rung), reason="memory_gate")
        cur = rung
        if fits:
            return cur
    return cur


def runtime_stats() -> dict:
    """Live taxonomy/ladder counters for ethrex_health
    (l2.prover.runtime) and the monitor panel."""
    with _LOCK:
        out = {"oomRetries": STATS["oom_retries"],
               "deviceLostRetries": STATS["device_lost_retries"],
               "nanPoisons": STATS["nan_poisons"],
               "degradations": STATS["degradations"],
               "memoryGateShrinks": STATS["memory_gate_shrinks"],
               "phaseResumes": STATS["phase_resumes"]}
        if _LAST_DEGRADATION is not None:
            out["lastDegradation"] = dict(_LAST_DEGRADATION)
    try:
        from . import checkpoint

        out["checkpoints"] = checkpoint.runtime_stats()
    except Exception:
        pass
    return out


def reset_stats() -> None:
    """Test hook: zero the module counters."""
    global _LAST_DEGRADATION
    with _LOCK:
        for key in STATS:
            STATS[key] = 0
        _LAST_DEGRADATION = None
