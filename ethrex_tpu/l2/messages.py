"""L2 -> L1 messages (withdrawals): burn-to-bridge on L2, claim on L1 with
a Merkle inclusion proof against the batch's message root (parity target:
the reference's crates/l2/common/src/{messages,merkle_tree}.rs and the
CommonBridge withdrawal claim flow).
"""

from __future__ import annotations

import dataclasses

from ..crypto.keccak import keccak256
from ..primitives.transaction import TYPE_PRIVILEGED

# the L2 bridge predeploy: value sent here is burned on L2 and becomes
# claimable on L1 once the batch is verified
BRIDGE_ADDRESS = b"\xff" * 19 + b"\xfe"


@dataclasses.dataclass(frozen=True)
class L2Message:
    from_addr: bytes     # L2 sender == L1 claimant
    value: int
    tx_hash: bytes       # uniquifies repeated identical withdrawals

    def leaf(self) -> bytes:
        return keccak256(b"ethrex-tpu/l2-message/v1" + self.from_addr
                         + self.value.to_bytes(32, "big") + self.tx_hash)


def collect_messages(blocks, receipts_per_block=None) -> list[L2Message]:
    """Withdrawal messages from a batch: successful value transfers to the
    bridge address.  When receipts are not provided (host committer path),
    tx success is determined by re-derived receipts passed alongside."""
    out = []
    for bi, block in enumerate(blocks):
        receipts = receipts_per_block[bi] if receipts_per_block else None
        for ti, tx in enumerate(block.body.transactions):
            if tx.to != BRIDGE_ADDRESS or tx.value == 0:
                continue
            if tx.tx_type == TYPE_PRIVILEGED:
                continue  # deposits cannot round-trip as withdrawals
            if receipts is not None and not receipts[ti].succeeded:
                continue
            out.append(L2Message(from_addr=tx.sender() or b"\x00" * 20,
                                 value=tx.value, tx_hash=tx.hash))
    return out


# ---------------------------------------------------------------------------
# binary keccak Merkle tree over message leaves
# ---------------------------------------------------------------------------

def message_root(messages) -> bytes:
    leaves = [m.leaf() for m in messages]
    if not leaves:
        return b"\x00" * 32
    level = leaves
    while len(level) > 1:
        if len(level) % 2:
            level = level + [level[-1]]  # duplicate-last padding
        level = [keccak256(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
    return level[0]


def message_proof(messages, index: int) -> list[bytes]:
    leaves = [m.leaf() for m in messages]
    if index >= len(leaves):
        raise IndexError("message index out of range")
    proof = []
    level = leaves
    idx = index
    while len(level) > 1:
        if len(level) % 2:
            level = level + [level[-1]]
        proof.append(level[idx ^ 1])
        level = [keccak256(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
        idx >>= 1
    return proof


def verify_message_proof(root: bytes, leaf: bytes, index: int,
                         proof: list[bytes]) -> bool:
    cur = leaf
    idx = index
    for sib in proof:
        if idx & 1:
            cur = keccak256(sib + cur)
        else:
            cur = keccak256(cur + sib)
        idx >>= 1
    return cur == root
