"""Outbound HTTP JSON-RPC EthClient with retry + exponential gas bumping.

Parity target: the reference sequencer's EthClient
(crates/networking/rpc/clients/eth — retrying transport,
send_tx_bump_gas_exponential_backoff used by the L1 committer,
l1_committer.rs:42).  Speaks to any execution JSON-RPC endpoint —
dogfooded against this repo's own node in the L2 tests.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request

from ..crypto import secp256k1
from ..primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

log = logging.getLogger("ethrex_tpu.l2.eth_client")


class RpcError(Exception):
    """JSON-RPC level error (the node answered with an error object)."""

    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code
        self.message = message


class TransportError(Exception):
    """Network/transport failure (retriable)."""


# Error classification for the sequencer's actor loops: a transient error
# (network flake, injected connection drop, timeout) is expected during an
# L1 outage and gets a far larger failure budget than a deterministic one
# (L1Error, logic bugs), which fails fast.  ConnectionError covers
# faults.InjectedFault; OSError covers raw socket errors.
TRANSIENT_ERRORS = (TransportError, ConnectionError, TimeoutError, OSError)


def is_transient(exc: BaseException) -> bool:
    return isinstance(exc, TRANSIENT_ERRORS)


class EthClient:
    def __init__(self, url: str, timeout: float = 10.0, retries: int = 3,
                 retry_backoff: float = 0.5):
        self.url = url
        self.timeout = timeout
        self.retries = retries
        self.retry_backoff = retry_backoff
        self._id = 0

    # ---------------- transport ----------------
    def call(self, method: str, params: list):
        """One JSON-RPC call with transport-level retries (rpc errors are
        NOT retried — the node answered authoritatively)."""
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method, "params": params}).encode()
        last = None
        for attempt in range(self.retries):
            if attempt:
                time.sleep(self.retry_backoff * (2 ** (attempt - 1)))
            try:
                req = urllib.request.Request(
                    self.url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as resp:
                    obj = json.loads(resp.read())
                if "error" in obj and obj["error"] is not None:
                    err = obj["error"]
                    raise RpcError(err.get("code", -1),
                                   err.get("message", ""))
                return obj.get("result")
            except (urllib.error.URLError, OSError, TimeoutError,
                    json.JSONDecodeError) as e:
                last = e
                log.warning("rpc transport failure (%d/%d): %s",
                            attempt + 1, self.retries, e)
        raise TransportError(f"{self.url}: {last}")

    # ---------------- reads ----------------
    def block_number(self) -> int:
        return int(self.call("eth_blockNumber", []), 16)

    def chain_id(self) -> int:
        return int(self.call("eth_chainId", []), 16)

    def gas_price(self) -> int:
        return int(self.call("eth_gasPrice", []), 16)

    def get_nonce(self, address: bytes, tag: str = "pending") -> int:
        return int(self.call("eth_getTransactionCount",
                             ["0x" + address.hex(), tag]), 16)

    def get_balance(self, address: bytes) -> int:
        return int(self.call("eth_getBalance",
                             ["0x" + address.hex(), "latest"]), 16)

    def eth_call(self, to: bytes, data: bytes, tag: str = "latest") -> bytes:
        out = self.call("eth_call", [{"to": "0x" + to.hex(),
                                      "data": "0x" + data.hex()}, tag])
        return bytes.fromhex(out[2:]) if out and out != "0x" else b""

    def get_receipt(self, tx_hash: bytes):
        return self.call("eth_getTransactionReceipt",
                         ["0x" + tx_hash.hex()])

    def get_logs(self, address: bytes, from_block: int,
                 to_block: int | str = "latest", topics=None) -> list:
        flt = {"address": "0x" + address.hex(), "fromBlock": hex(from_block),
               "toBlock": to_block if isinstance(to_block, str)
               else hex(to_block)}
        if topics:
            flt["topics"] = ["0x" + t.hex() for t in topics]
        return self.call("eth_getLogs", [flt]) or []

    # ---------------- transaction path ----------------
    def send_raw(self, raw: bytes) -> bytes:
        out = self.call("eth_sendRawTransaction", ["0x" + raw.hex()])
        return bytes.fromhex(out[2:])

    def send_tx_bump_gas_exponential_backoff(
            self, secret: int, to: bytes | None, data: bytes = b"",
            value: int = 0, gas_limit: int = 500_000,
            max_attempts: int = 6, receipt_timeout: float = 15.0,
            poll_interval: float = 0.25) -> dict:
        """The committer's send seam (reference l1_committer.rs:42):
        sign with the current pending nonce, submit, wait for the
        receipt; on underpriced/replacement rejections or a stuck
        mempool, bump fees exponentially and resubmit with the SAME
        nonce.  Returns the receipt; raises on definitive failure."""
        sender = secp256k1.pubkey_to_address(
            secp256k1.pubkey_from_secret(secret))
        chain_id = self.chain_id()
        nonce = self.get_nonce(sender)
        max_fee = max(self.gas_price(), 8)
        tip = 1
        last_err: Exception | None = None
        attempted: list[bytes] = []  # every hash sent under this nonce

        def any_receipt():
            # earlier same-nonce attempts can mine after we bumped —
            # a receipt for ANY of them is success
            for h in reversed(attempted):
                rec = self.get_receipt(h)
                if rec is not None:
                    return rec
            return None

        for attempt in range(max_attempts):
            tx = Transaction(
                tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce,
                max_priority_fee_per_gas=tip, max_fee_per_gas=max_fee,
                gas_limit=gas_limit, to=to or b"", value=value, data=data,
            ).sign(secret)
            attempted.append(tx.hash)
            try:
                self.send_raw(tx.encode_canonical())
            except RpcError as e:
                # underpriced / replacement-underpriced / fee-too-low:
                # bump and retry with the same nonce; anything else that
                # is not "already known" is definitive
                msg = e.message.lower()
                if "nonce too low" in msg:
                    rec = any_receipt()
                    if rec is not None:
                        return rec
                elif "underpriced" in msg or "fee" in msg \
                        or "replacement" in msg:
                    last_err = e
                    max_fee *= 2
                    tip *= 2
                    log.info("gas bump (attempt %d): max_fee=%d",
                             attempt + 1, max_fee)
                    continue
                elif "already known" not in msg:
                    raise
            deadline = time.time() + receipt_timeout
            while time.time() < deadline:
                rec = any_receipt()
                if rec is not None:
                    return rec
                time.sleep(poll_interval)
            # receipt never appeared: bump fees, same nonce
            last_err = TransportError("tx not mined before timeout")
            max_fee *= 2
            tip *= 2
            log.info("tx stuck; gas bump (attempt %d): max_fee=%d",
                     attempt + 1, max_fee)
        rec = any_receipt()
        if rec is not None:
            return rec
        raise TransportError(f"transaction never mined: {last_err}")
