"""Proof coordinator: TCP server assigning batches to pull-based provers
(parity with the reference's ProofCoordinator actor,
crates/l2/sequencer/proof_coordinator.rs — per-(batch, prover_type)
assignment map with timeout reassignment, version gating, duplicate-proof
no-op storage).
"""

from __future__ import annotations

import socketserver
import threading
import time

from ..prover import protocol
from .rollup_store import RollupStore

ASSIGNMENT_TIMEOUT = 600.0  # seconds, like the reference's 10 minutes


class ProofCoordinator:
    def __init__(self, rollup_store: RollupStore,
                 needed_types: list[str] | None = None,
                 commit_hash: str = protocol.PROTOCOL_VERSION,
                 host: str = "127.0.0.1", port: int = 0,
                 proof_format: str = protocol.FORMAT_STARK):
        self.rollup = rollup_store
        self.needed_types = needed_types or [protocol.PROVER_TPU]
        self.commit_hash = commit_hash
        self.proof_format = proof_format
        # (batch_number, prover_type) -> assignment deadline
        self.assignments: dict[tuple[int, str], float] = {}
        # (batch_number, prover_type) -> first-assignment time (metrics)
        self.assigned_at: dict[tuple[int, str], float] = {}
        self.lock = threading.RLock()
        self.host = host
        self.port = port
        self._server: socketserver.ThreadingTCPServer | None = None

    # ------------------------------------------------------------------
    def next_batch_to_assign(self, prover_type: str) -> int | None:
        """Lowest batch with a stored prover input, no proof of this type,
        and no live assignment (reference: next_batch_to_assign:149-215)."""
        if prover_type not in self.needed_types:
            return None
        now = time.monotonic()
        with self.lock:
            candidates = sorted({
                num for (num, ver) in self.rollup.prover_inputs
                if ver == self.commit_hash
            })
            for num in candidates:
                if self.rollup.get_proof(num, prover_type) is not None:
                    continue
                deadline = self.assignments.get((num, prover_type))
                if deadline is not None and deadline > now:
                    continue
                self.assignments[(num, prover_type)] = \
                    now + ASSIGNMENT_TIMEOUT
                self.assigned_at[(num, prover_type)] = now
                return num
        return None

    def handle_request(self, msg: dict) -> dict:
        mtype = msg.get("type")
        if mtype == protocol.INPUT_REQUEST:
            if msg.get("commit_hash") != self.commit_hash:
                return {"type": protocol.VERSION_MISMATCH,
                        "expected": self.commit_hash}
            prover_type = msg.get("prover_type")
            if prover_type not in self.needed_types:
                return {"type": protocol.TYPE_NOT_NEEDED}
            batch = self.next_batch_to_assign(prover_type)
            if batch is None:
                return {"type": protocol.TYPE_NOT_NEEDED}
            program_input = self.rollup.get_prover_input(
                batch, self.commit_hash)
            return {"type": protocol.INPUT_RESPONSE, "batch_id": batch,
                    "input": program_input, "format": self.proof_format}
        if mtype == protocol.PROOF_SUBMIT:
            batch = msg.get("batch_id")
            prover_type = msg.get("prover_type")
            proof = msg.get("proof")
            if not isinstance(batch, int) or \
                    prover_type not in self.needed_types \
                    or not isinstance(proof, dict):
                return {"type": protocol.ERROR, "message": "bad submit"}
            self.rollup.store_proof(batch, prover_type, proof)
            with self.lock:
                self.assignments.pop((batch, prover_type), None)
                started = self.assigned_at.pop((batch, prover_type), None)
            if started is not None:
                # proving-time metric (reference: set_batch_proving_time,
                # proof_coordinator.rs:286-296)
                from ..utils.metrics import record_batch

                record_batch(batch, time.monotonic() - started)
            return {"type": protocol.SUBMIT_ACK, "batch_id": batch}
        return {"type": protocol.ERROR, "message": f"unknown type {mtype}"}

    # ------------------------------------------------------------------
    def start(self):
        coordinator = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = protocol.recv_msg_file(self.rfile)
                    except (ValueError, ConnectionError):
                        break
                    if msg is None:
                        break
                    resp = coordinator.handle_request(msg)
                    protocol.send_msg(self.connection, resp)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        if self._server is not None:
            return self    # idempotent: Sequencer.start() re-enters here
        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server.server_close()
