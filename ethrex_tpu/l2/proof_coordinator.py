"""Proof coordinator: TCP server assigning batches to pull-based provers
(parity with the reference's ProofCoordinator actor,
crates/l2/sequencer/proof_coordinator.rs — per-(batch, prover_type)
assignment map with timeout reassignment, version gating, duplicate-proof
no-op storage), extended with the resilience layer:

  * leases instead of a fixed timeout — Heartbeat messages from a prover
    mid-proof extend its assignment deadline, so a slow TPU proof is not
    reassigned out from under a live prover;
  * per-batch failure tracking — every lease expiry and every rejected
    submit counts against the (batch, prover_type) pair;
  * poison-batch quarantine — a batch that keeps failing on its primary
    prover type is handed to the fallback backend (the reference's
    multi-prover model as graceful degradation) and surfaced via metrics
    and the health endpoint;
  * submit-time proof validation — a corrupt proof frees the assignment
    slot immediately instead of poisoning the stored-proof map until the
    proof sender's full audit;
  * lease tokens — every assignment carries an unguessable token that
    Heartbeat and ProofSubmit must echo; the wire protocol carries no
    prover identity, so the token is what ties lease mutations (extension,
    invalid-proof eviction, failure accounting) to the prover that was
    actually granted the lease instead of to any connection that names the
    right (batch, prover_type) pair;
  * a bounded lease lifetime — heartbeats extend a lease only up to
    `max_lease_lifetime` past first assignment, so a prover whose prove
    call hangs (rather than crashes) is still eventually reassigned and
    counted as a failure instead of pinning the batch forever;

and, on top of the lease substrate, a **fleet scheduler**
(docs/AGGREGATION.md) replacing the original FCFS scan:

  * per-prover throughput tracking — provers may volunteer a stable
    `prover_id` on the wire; the coordinator keeps an EWMA of each
    prover's proving wall-clock and its live-lease count;
  * batch-size-aware placement — the fastest provers are steered toward
    the heaviest unleased batches and the slowest toward the lightest
    (with no stats the scan degrades to the FCFS order, and
    `scheduler_policy="fcfs"` pins the original behavior outright);
  * speculative hedged re-assignment — once every candidate batch is
    leased, a requester can be granted a *hedge lease* on a straggler
    whose elapsed time exceeds a p99-derived deadline ("The Tail at
    Scale", Dean & Barroso, CACM 2013).  First result wins: the hedge
    carries its own token, either holder's valid submit settles the
    batch, and the loser's later submit is deduplicated into a no-op
    SUBMIT_ACK without touching lease or quarantine state;
  * work stealing — an idle prover may likewise be granted a hedge on a
    batch held by a prover sitting on a deep backlog of live leases
    (Blumofe & Leiserson's steal-from-the-loaded rule, run as a race
    rather than a revocation so the existing token safety applies);
  * warm-aware handoff — provers may report an advisory `warm` flag on
    InputRequest (their AOT kernels hydrated from the on-disk executable
    cache, docs/PERFORMANCE.md "Cold start").  A cold prover is asked to
    sit out a bounded number of polls while recently-seen warm provers
    can absorb the queue, so the first post-restart batches land on
    provers that prove at steady-state wall; and a batch assigned to a
    cold prover is excluded from the duration samples and that prover's
    EWMA, so one compile-inclusive first proof cannot poison the
    placement and hedging signals.
"""

from __future__ import annotations

import collections
import logging
import secrets
import socketserver
import threading
import time

from ..prover import protocol
from ..utils import faults, tracing
from .rollup_store import RollupStore

log = logging.getLogger("ethrex_tpu.l2.proof_coordinator")

ASSIGNMENT_TIMEOUT = 600.0  # default lease, like the reference's 10 minutes
QUARANTINE_THRESHOLD = 3    # failed assignments before exec fallback
LEASE_LIFETIME_FACTOR = 6   # max heartbeat-extended lifetime, in leases
HEDGE_MIN_SAMPLES = 8       # completed proofs before p99 hedging arms
HEDGE_FACTOR = 1.5          # hedge once elapsed > p99 * factor
STEAL_THRESHOLD = 4         # live leases that mark a prover "overloaded"
EWMA_ALPHA = 0.3            # per-prover proving-time smoothing
WARM_PEER_WINDOW = 60.0     # a warm prover seen this recently can absorb
COLD_DEFERRAL_CAP = 3       # polls a cold prover sits out before it's fed


class ProofCoordinator:
    def __init__(self, rollup_store: RollupStore,
                 needed_types: list[str] | None = None,
                 commit_hash: str = protocol.PROTOCOL_VERSION,
                 host: str = "127.0.0.1", port: int = 0,
                 proof_format: str = protocol.FORMAT_STARK,
                 lease_timeout: float = ASSIGNMENT_TIMEOUT,
                 quarantine_threshold: int = QUARANTINE_THRESHOLD,
                 fallback_type: str = protocol.PROVER_EXEC,
                 verify_submissions: bool = True,
                 max_lease_lifetime: float | None = None,
                 scheduler_policy: str = "fleet",
                 hedge_min_samples: int = HEDGE_MIN_SAMPLES,
                 hedge_factor: float = HEDGE_FACTOR,
                 steal_threshold: int = STEAL_THRESHOLD):
        if scheduler_policy not in ("fleet", "fcfs"):
            raise ValueError(
                f"unknown scheduler policy {scheduler_policy!r}")
        self.rollup = rollup_store
        self.needed_types = needed_types or [protocol.PROVER_TPU]
        self.commit_hash = commit_hash
        self.proof_format = proof_format
        self.lease_timeout = lease_timeout
        self.quarantine_threshold = quarantine_threshold
        self.fallback_type = fallback_type
        self.verify_submissions = verify_submissions
        # total lifetime a lease may be heartbeat-extended to, measured
        # from first assignment; a hung (not crashed) prover is reassigned
        # once this is spent
        self.max_lease_lifetime = (
            max_lease_lifetime if max_lease_lifetime is not None
            else LEASE_LIFETIME_FACTOR * lease_timeout)
        # (batch_number, prover_type) -> lease deadline; an expired entry
        # stays until reassignment so a late-but-finished proof still lands
        self.assignments: dict[tuple[int, str], float] = {}
        # (batch_number, prover_type) -> first-assignment time (metrics +
        # the max_lease_lifetime anchor)
        self.assigned_at: dict[tuple[int, str], float] = {}
        # (batch_number, prover_type) -> token of the current lease holder;
        # Heartbeat/ProofSubmit must echo it to mutate lease state
        self.lease_tokens: dict[tuple[int, str], str] = {}
        # (batch_number, prover_type) -> failed assignments (expiry/reject)
        self.failures: dict[tuple[int, str], int] = {}
        # batch_number -> trace ID; one trace follows the batch through
        # assign -> prove -> submit -> verify -> settle (docs/OBSERVABILITY.md)
        self.batch_traces: dict[int, str] = {}
        self.quarantined: set[int] = set()
        self.reassignments_total = 0
        self.heartbeats_total = 0
        self.rejected_submits_total = 0
        self.unsolicited_submits_total = 0
        self.stale_submits_total = 0
        # -- fleet scheduler state -------------------------------------
        self.scheduler_policy = scheduler_policy
        self.hedge_min_samples = max(1, hedge_min_samples)
        self.hedge_factor = hedge_factor
        self.steal_threshold = max(1, steal_threshold)
        # (batch, prover_type) -> hedge lease racing the primary holder:
        # {token, assigned_at, expires, prover_id, reason}; its token is
        # accepted by Heartbeat/ProofSubmit exactly like the primary's
        self.hedges: dict[tuple[int, str], dict] = {}
        # (batch, prover_type) -> prover_id of the primary holder (None
        # for provers that do not volunteer an identity)
        self.lease_holders: dict[tuple[int, str], str | None] = {}
        # prover_id -> {completed, ewma, last_seen, warm, cold_deferrals};
        # fed by assigns and successful submits that carry a prover_id
        self.prover_stats: dict[str, dict] = {}
        # (batch, prover_type) -> the holder's warm flag at grant time
        # (None for provers that did not report one); a cold-assigned
        # batch's proving wall includes compile time, so _handle_submit
        # keeps it out of the durations deque and the holder's EWMA
        self.lease_warm: dict[tuple[int, str], bool | None] = {}
        # (batch, prover_type) -> (in-flight phase, transition time on
        # THIS clock) from heartbeats; the hedging deadline re-anchors on
        # every phase transition so a proof making phase progress is
        # never hedged as a straggler (the prover's own phase_started is
        # advisory/observability only — clock skew never feeds hedging)
        self.lease_phase: dict[tuple[int, str], tuple[str, float]] = {}
        self.poison_reports_total = 0
        self.cold_deferrals_total = 0
        # recent completed proving wall-clocks, the p99 hedging source
        self.durations: collections.deque = collections.deque(maxlen=256)
        self.hedged_assignments_total = 0
        self.duplicate_submits_total = 0
        self.queue_depth = 0
        self.lock = threading.RLock()
        self.host = host
        self.port = port
        self._server: socketserver.ThreadingTCPServer | None = None
        # requests currently inside handle_request; stop() waits for
        # them so an in-flight proof submit lands before the drain
        # proceeds (a submit that misses the window leases back on
        # restart via normal lease expiry)
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        # bounded ring of recent lease events (assign/expire/reject/
        # quarantine/proof) for the flight recorder: the raw counters say
        # HOW MANY leases churned, this says WHICH and WHEN
        self.events: collections.deque = collections.deque(maxlen=64)
        # batch -> critical-path summary of its settled lifecycle trace,
        # written by the sequencer after verify/settle and surfaced in
        # ethrex_health (`l2.lifecycle`) and the monitor timeline
        self.batch_lifecycles: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()

    def note_lifecycle(self, batch: int, summary: dict) -> None:
        """Record one settled batch's critical-path summary (bounded;
        telemetry, so it never raises into settlement)."""
        try:
            with self.lock:
                self.batch_lifecycles[batch] = summary
                self.batch_lifecycles.move_to_end(batch)
                while len(self.batch_lifecycles) > 16:
                    self.batch_lifecycles.popitem(last=False)
        except Exception:
            pass

    def lifecycles_json(self) -> list:
        """Recent settled batches' lifecycle timeline, oldest first."""
        with self.lock:
            return [dict(v) for v in self.batch_lifecycles.values()]

    def _note_event(self, event: str, batch: int, prover_type: str,
                    detail: str | None = None):
        """Caller holds self.lock (or accepts best-effort ordering)."""
        entry = {"ts": time.time(), "event": event, "batch": batch,
                 "proverType": prover_type}
        if detail:
            entry["detail"] = detail
        self.events.append(entry)

    @staticmethod
    def _now() -> float:
        """Lease clock; an instance attribute in tests to fake expiry."""
        return time.monotonic()

    # ------------------------------------------------------------------
    # failure accounting + quarantine
    # ------------------------------------------------------------------
    def _record_failure(self, batch: int, prover_type: str, reason: str):
        """Caller holds self.lock."""
        from ..utils.metrics import record_quarantine, record_reassignment

        key = (batch, prover_type)
        self.failures[key] = self.failures.get(key, 0) + 1
        self.reassignments_total += 1
        record_reassignment(batch, prover_type)
        self._note_event("lease-failure", batch, prover_type, reason)
        log.warning("batch %d assignment to %s failed (%s), %d/%d before "
                    "quarantine", batch, prover_type, reason,
                    self.failures[key], self.quarantine_threshold)
        if (prover_type != self.fallback_type
                and self.failures[key] >= self.quarantine_threshold
                and batch not in self.quarantined):
            self.quarantined.add(batch)
            record_quarantine(len(self.quarantined))
            self._note_event("quarantine", batch, prover_type)
            log.error("batch %d quarantined off %r after %d failed "
                      "assignments; falling back to %r", batch,
                      prover_type, self.failures[key], self.fallback_type)

    def _allowed_types(self) -> set[str]:
        """Prover types this coordinator currently serves: the configured
        set, plus the fallback backend while any batch is quarantined."""
        allowed = set(self.needed_types)
        if self.quarantined:
            allowed.add(self.fallback_type)
        return allowed

    def effective_needed_types(self, batch_number: int,
                               base: list[str] | None = None) -> list[str]:
        """The prover types that actually settle this batch: quarantined
        batches substitute the fallback type for every primary type
        (graceful degradation — the proof sender and L1 path consume
        this, so settlement keeps moving on the fallback proof)."""
        types = list(base if base is not None else self.needed_types)
        if batch_number in self.quarantined:
            types = [self.fallback_type for _ in types]
        return list(dict.fromkeys(types))

    # ------------------------------------------------------------------
    # fleet scheduler
    # ------------------------------------------------------------------
    def _batch_weight(self, num: int) -> int:
        """Rough batch size for placement: block/tx counts out of the
        stored prover input.  Opaque inputs weigh 1, which collapses the
        size-aware pick back to the FCFS order."""
        inp = self.rollup.get_prover_input(num, self.commit_hash)
        if not isinstance(inp, dict):
            return 1
        blocks = inp.get("blocks")
        if not isinstance(blocks, list):
            return 1
        weight = 0
        for b in blocks:
            weight += 1
            if isinstance(b, dict):
                txs = b.get("transactions")
                if isinstance(txs, list):
                    weight += len(txs)
        return max(1, weight)

    def _hedge_deadline(self) -> float | None:
        """p99 of recent proving wall-clocks times `hedge_factor`; None
        until `hedge_min_samples` proofs have completed (hedging stays
        disarmed while the fleet has no latency signal).  Caller holds
        self.lock."""
        if len(self.durations) < self.hedge_min_samples:
            return None
        ordered = sorted(self.durations)
        p99 = ordered[min(len(ordered) - 1,
                          int(0.99 * (len(ordered) - 1) + 0.5))]
        return p99 * self.hedge_factor

    def _live_leases_held(self, prover_id: str, now: float) -> int:
        """Caller holds self.lock."""
        return sum(1 for key, deadline in self.assignments.items()
                   if deadline > now
                   and self.lease_holders.get(key) == prover_id)

    def _pick_unleased(self, unleased: list[int],
                       prover_id: str | None) -> int:
        """Batch-size-aware placement: relative to the rest of the
        fleet's EWMA proving times, a fastest prover takes the heaviest
        waiting batch and a slowest takes the lightest; everyone else —
        and every prover without stats — takes the oldest (FCFS)."""
        if self.scheduler_policy != "fleet" or prover_id is None \
                or len(unleased) == 1:
            return unleased[0]
        st = self.prover_stats.get(prover_id)
        if st is not None and st.get("degraded") is not None:
            # runtime-degraded prover (OOM/device-loss demoted its mesh):
            # steer it to the lightest waiting batch regardless of EWMA —
            # its historical speed no longer predicts its capacity
            weights = {num: self._batch_weight(num) for num in unleased}
            return min(unleased, key=lambda n: (weights[n], n))
        ewma = st.get("ewma") if st else None
        others = [s["ewma"] for pid, s in self.prover_stats.items()
                  if pid != prover_id and s.get("ewma") is not None]
        if ewma is None or not others:
            return unleased[0]
        weights = {num: self._batch_weight(num) for num in unleased}
        if len(set(weights.values())) == 1:
            return unleased[0]
        if ewma <= min(others):
            # ties break toward the oldest batch, keeping settlement
            # (which walks batches in order) fed
            return max(unleased, key=lambda n: (weights[n], -n))
        if ewma >= max(others):
            return min(unleased, key=lambda n: (weights[n], n))
        return unleased[0]

    def next_batch_to_assign(self, prover_type: str,
                             prover_id: str | None = None) -> int | None:
        """Back-compat wrapper over `assign` (the original FCFS scan's
        signature); callers that need the granted lease token — a hedge
        grant carries its own — use `assign` directly."""
        return self.assign(prover_type, prover_id)[0]

    def assign(self, prover_type: str, prover_id: str | None = None,
               warm: bool | None = None
               ) -> tuple[int | None, str | None]:
        """One scheduling decision: returns (batch, lease_token) or
        (None, None).

        Scans batches with a stored prover input and no proof of this
        type (reference: next_batch_to_assign:149-215).  Expired leases
        are counted as failed assignments — enough of them quarantines
        the batch onto the fallback backend.  Unleased work is placed
        size-aware under the fleet policy (FCFS under `fcfs`); a
        requester that reports itself cold (`warm=False`) may first be
        deferred while recently-seen warm provers can absorb the queue
        (bounded by COLD_DEFERRAL_CAP so a warm-less fleet never
        starves); when everything is leased, the fleet policy may grant
        a *hedge* on a straggler past the p99-derived deadline or steal
        from an overloaded holder — a second lease racing the first,
        dedup'd at submit time."""
        faults.inject("coordinator.schedule")
        if prover_type not in self._allowed_types():
            return None, None
        now = self._now()
        with self.lock:
            if prover_id is not None:
                st = self.prover_stats.setdefault(
                    prover_id, {"completed": 0, "ewma": None,
                                "last_seen": now})
                st["last_seen"] = now
                if warm is not None:
                    st["warm"] = warm
                    if warm:
                        st["cold_deferrals"] = 0
            candidates = sorted({
                num for (num, ver) in self.rollup.prover_inputs
                if ver == self.commit_hash
            })
            unleased: list[int] = []
            leased: list[int] = []
            for num in candidates:
                if num in self.quarantined:
                    # quarantined batches go only to the fallback backend
                    if prover_type != self.fallback_type:
                        continue
                elif prover_type not in self.needed_types:
                    continue  # fallback prover: nothing else for it here
                if self.rollup.get_proof(num, prover_type) is not None:
                    continue
                key = (num, prover_type)
                deadline = self.assignments.get(key)
                if deadline is not None:
                    if deadline > now:
                        leased.append(num)
                        continue  # live lease elsewhere
                    # lease expired: the holder crashed or stalled
                    self._clear_lease(key)
                    self._record_failure(num, prover_type, "lease expired")
                    if num in self.quarantined and \
                            prover_type != self.fallback_type:
                        continue  # this expiry tipped it into quarantine
                unleased.append(num)
            self.queue_depth = len(unleased)
            if unleased:
                if self._defer_cold(prover_id, warm, len(unleased), now):
                    self._report_queue_depth()
                    return None, None
                num = self._pick_unleased(unleased, prover_id)
                token = self._grant(num, prover_type, prover_id, now,
                                    warm)
                self.queue_depth -= 1   # the grant is no longer waiting
                self._report_queue_depth()
                return num, token
            granted = self._maybe_hedge(leased, prover_type, prover_id,
                                        now, warm)
            self._report_queue_depth()
            return granted

    def _defer_cold(self, prover_id: str | None, warm: bool | None,
                    queue_len: int, now: float) -> bool:
        """Warm-aware handoff: should this requester sit out the poll?
        Only a prover that EXPLICITLY reports warm=False is deferred
        (warm=None — an older client — is never penalized), only while
        enough recently-seen warm peers exist to absorb the whole queue,
        and only COLD_DEFERRAL_CAP times in a row — so the first batches
        after a restart land on provers that prove at steady-state wall,
        without ever starving a fleet that has no warm capacity.  The
        deferred prover keeps polling (and hydrating in the background);
        its next InputRequest is a fresh decision.  Caller holds
        self.lock."""
        from ..utils.metrics import record_cold_deferral

        if self.scheduler_policy != "fleet" or warm is not False \
                or prover_id is None:
            return False
        st = self.prover_stats.get(prover_id)
        deferrals = st.get("cold_deferrals", 0) if st else 0
        if deferrals >= COLD_DEFERRAL_CAP:
            return False
        warm_peers = sum(
            1 for pid, s in self.prover_stats.items()
            if pid != prover_id and s.get("warm")
            and now - s.get("last_seen", 0.0) <= WARM_PEER_WINDOW)
        if warm_peers == 0 or queue_len > warm_peers:
            return False    # not enough warm capacity; feed the cold one
        if st is not None:
            st["cold_deferrals"] = deferrals + 1
        self.cold_deferrals_total += 1
        record_cold_deferral()
        log.info("deferring cold prover %s (%d/%d): %d warm peer(s) can "
                 "absorb the %d-batch queue", prover_id, deferrals + 1,
                 COLD_DEFERRAL_CAP, warm_peers, queue_len)
        return True

    def _grant(self, num: int, prover_type: str, prover_id: str | None,
               now: float, warm: bool | None = None) -> str:
        """Issue the primary lease. Caller holds self.lock."""
        key = (num, prover_type)
        token = secrets.token_hex(16)
        self.assignments[key] = now + self.lease_timeout
        self.assigned_at[key] = now
        self.lease_tokens[key] = token
        self.lease_holders[key] = prover_id
        self.lease_warm[key] = warm
        return token

    def _maybe_hedge(self, leased: list[int], prover_type: str,
                     prover_id: str | None, now: float,
                     warm: bool | None = None
                     ) -> tuple[int | None, str | None]:
        """Every candidate batch is leased: under the fleet policy, grant
        a hedge lease on a straggler past the p99 deadline, or steal from
        a holder with a deep live backlog when this requester is idle.
        Caller holds self.lock."""
        from ..utils.metrics import record_hedged_assignment

        if self.scheduler_policy != "fleet":
            return None, None
        deadline = self._hedge_deadline()
        requester_idle = (prover_id is not None
                          and self._live_leases_held(prover_id, now) == 0)
        for num in leased:
            key = (num, prover_type)
            hedge = self.hedges.get(key)
            if hedge is not None:
                if hedge["expires"] > now:
                    continue  # one hedge at a time per batch
                self.hedges.pop(key, None)  # hedge holder crashed too
            if prover_id is not None \
                    and self.lease_holders.get(key) == prover_id:
                continue  # never hedge a prover against itself
            reason = None
            # straggler clock anchors on the LAST phase transition the
            # holder reported (stamped with this coordinator's clock at
            # heartbeat ingestion), not first assignment: a prover
            # resuming from checkpoints or grinding through a long FRI
            # phase is making progress, and hedging it would only burn a
            # second prover on work the first will finish
            anchor = self.assigned_at.get(key, now)
            phase_info = self.lease_phase.get(key)
            if phase_info is not None:
                anchor = max(anchor, phase_info[1])
            if deadline is not None and now - anchor > deadline:
                reason = "straggler"
            elif requester_idle:
                holder = self.lease_holders.get(key)
                if holder is not None and holder != prover_id \
                        and self._live_leases_held(holder, now) \
                        >= self.steal_threshold:
                    reason = "steal"
            if reason is None:
                continue
            token = secrets.token_hex(16)
            self.hedges[key] = {
                "token": token, "assigned_at": now,
                "expires": now + self.lease_timeout,
                "prover_id": prover_id, "reason": reason,
                "warm": warm,
            }
            self.hedged_assignments_total += 1
            record_hedged_assignment()
            self._note_event("hedge", num, prover_type, reason)
            log.info("hedged batch %d/%s to %s (%s): first result wins",
                     num, prover_type, prover_id or "<anon>", reason)
            return num, token
        return None, None

    def _report_queue_depth(self):
        from ..utils.metrics import record_scheduler_queue_depth

        record_scheduler_queue_depth(self.queue_depth)

    def _clear_lease(self, key: tuple[int, str]) -> float | None:
        """Drop a lease and its token; returns the first-assignment time
        (None if it was never live). Caller holds self.lock."""
        self.assignments.pop(key, None)
        self.lease_tokens.pop(key, None)
        self.lease_holders.pop(key, None)
        self.lease_warm.pop(key, None)
        self.lease_phase.pop(key, None)
        return self.assigned_at.pop(key, None)

    def trace_for_batch(self, batch: int) -> str:
        """The trace ID following this batch's proving lifecycle (created
        on first assignment, reused on reassignment so retries land in
        the same trace)."""
        with self.lock:
            tid = self.batch_traces.get(batch)
            if tid is None:
                tid = tracing.new_trace_id()
                self.batch_traces[batch] = tid
                if len(self.batch_traces) > 4096:
                    for old in sorted(self.batch_traces)[:1024]:
                        del self.batch_traces[old]
            return tid

    def lease_token(self, batch: int, prover_type: str) -> str | None:
        """Token of the current lease holder for (batch, prover_type)."""
        with self.lock:
            return self.lease_tokens.get((batch, prover_type))

    # ------------------------------------------------------------------
    def _handle_heartbeat(self, msg: dict) -> dict:
        from ..utils.metrics import record_heartbeat

        # merge any piggybacked span subtree BEFORE lease logic: even a
        # beat whose lease already lapsed leaves its partial spans, so a
        # prover that later dies mid-prove still renders in the batch's
        # merged trace (never raises, deduped, capped per source)
        tracing.TRACER.ingest(msg.get("spans"),
                              source=msg.get("prover_id"))
        batch = msg.get("batch_id")
        prover_type = msg.get("prover_type")
        token = msg.get("lease_token")
        ok = False
        with self.lock:
            key = (batch, prover_type)
            deadline = self.assignments.get(key)
            now = self._now()
            if (deadline is not None and deadline > now
                    and token is not None
                    and token == self.lease_tokens.get(key)):
                # only the granted holder may extend, and only up to
                # max_lease_lifetime past first assignment — a hung prover
                # cannot keep a batch pinned forever
                hard = self.assigned_at.get(key, now) \
                    + self.max_lease_lifetime
                if now < hard:
                    self.assignments[key] = \
                        min(now + self.lease_timeout, hard)
                    self.heartbeats_total += 1
                    ok = True
                # else: lifetime spent; the lease lapses at its current
                # deadline, expiry reassigns and counts the failure
            else:
                # a hedge holder extends its own lease with its own
                # token, under the same hard-lifetime clamp
                hedge = self.hedges.get(key)
                if (hedge is not None and hedge["expires"] > now
                        and token is not None
                        and token == hedge["token"]):
                    hard = hedge["assigned_at"] + self.max_lease_lifetime
                    if now < hard:
                        hedge["expires"] = \
                            min(now + self.lease_timeout, hard)
                        self.heartbeats_total += 1
                        ok = True
            if ok:
                self._ingest_runtime_advisory(key, msg, now)
        if ok:
            record_heartbeat()
        return {"type": protocol.HEARTBEAT_ACK, "batch_id": batch, "ok": ok}

    def _ingest_runtime_advisory(self, key: tuple[int, str], msg: dict,
                                 now: float) -> None:
        """Consume a token-validated heartbeat's runtime fields: the
        in-flight phase (stamped with THIS clock on transition — the
        hedging re-anchor), any mesh downgrade (scheduler steering), and
        a poison report (immediate quarantine naming the phase).  Caller
        holds self.lock."""
        batch, prover_type = key
        phase = msg.get("phase")
        if isinstance(phase, str) and phase:
            prev = self.lease_phase.get(key)
            if prev is None or prev[0] != phase:
                self.lease_phase[key] = (phase, now)
        prover_id = msg.get("prover_id")
        degraded = msg.get("degraded")
        if prover_id is not None and isinstance(degraded, dict):
            st = self.prover_stats.setdefault(
                prover_id, {"completed": 0, "ewma": None,
                            "last_seen": now})
            st["degraded"] = {"from": str(degraded.get("from")),
                              "to": str(degraded.get("to"))}
        poison = msg.get("poison")
        if isinstance(poison, dict):
            from ..utils.metrics import record_quarantine

            self.poison_reports_total += 1
            detail = f"nan_poison in phase {poison.get('phase')!r}"
            self._clear_lease(key)
            self._note_event("poison-report", batch, prover_type, detail)
            log.error("batch %d reported poisoned by its %s prover (%s)",
                      batch, prover_type, detail)
            if prover_type != self.fallback_type \
                    and batch not in self.quarantined:
                # a poisoned batch cannot be proven by ANY amount of
                # retrying on this backend: quarantine on the FIRST
                # report instead of burning the failure budget
                self.quarantined.add(batch)
                record_quarantine(len(self.quarantined))
                self._note_event("quarantine", batch, prover_type, detail)
                log.error("batch %d quarantined off %r on first poison "
                          "report; falling back to %r", batch,
                          prover_type, self.fallback_type)

    def _handle_submit(self, msg: dict) -> dict:
        # merge the shipped span subtree FIRST: a duplicate submit is the
        # losing leg of a hedged race, and its subtree still belongs in
        # the batch's merged trace (two prover subtrees under one trace);
        # ingestion never raises and is deduped + capped per source
        tracing.TRACER.ingest(msg.get("spans"),
                              source=msg.get("prover_id"))
        batch = msg.get("batch_id")
        prover_type = msg.get("prover_type")
        proof = msg.get("proof")
        token = msg.get("lease_token")
        with self.lock:
            allowed = self._allowed_types()
            if batch in self.quarantined:
                allowed.add(self.fallback_type)
        if not isinstance(batch, int) or prover_type not in allowed \
                or not isinstance(proof, dict):
            return {"type": protocol.ERROR, "message": "bad submit"}
        key = (batch, prover_type)
        with self.lock:
            duplicate = self.rollup.get_proof(batch, prover_type) \
                is not None
            if duplicate:
                self.duplicate_submits_total += 1
                self._note_event("duplicate-submit", batch, prover_type)
        if duplicate:
            # duplicate submit -> no-op ACK (reference parity: the store
            # keeps the first proof; the prover moves on).  This is also
            # the losing leg of a hedged assignment — first result wins,
            # and the loser's work is acknowledged without touching
            # lease, failure, or quarantine state.
            faults.inject("submit.duplicate", proof)
            return {"type": protocol.SUBMIT_ACK, "batch_id": batch}
        with self.lock:
            hedge = self.hedges.get(key)
            if key not in self.assignments and hedge is None:
                # unsolicited: never assigned (or already settled and
                # cleaned up) — do not let an arbitrary connection write
                # into the proof store
                self.unsolicited_submits_total += 1
                return {"type": protocol.ERROR,
                        "message": f"no assignment for batch {batch}"}
            # the wire protocol carries no prover identity — the lease
            # token is what distinguishes the granted holder (primary or
            # hedge) from a stale evicted prover or an arbitrary third
            # party
            holds_primary = (token is not None
                             and token == self.lease_tokens.get(key))
            holds_hedge = (token is not None and hedge is not None
                           and token == hedge["token"])
            holds_lease = holds_primary or holds_hedge
        if self.verify_submissions:
            from ..prover.backend import get_backend

            try:
                ok = get_backend(prover_type).verify_submission(proof)
            except Exception:  # noqa: BLE001 — untrusted wire input
                ok = False
            if not ok:
                with self.lock:
                    # re-check under the lock: verification ran outside
                    # it, and the lease may have expired and been
                    # re-granted to a new holder in the meantime
                    hedge = self.hedges.get(key)
                    holds_primary = (token is not None and
                                     token == self.lease_tokens.get(key))
                    holds_hedge = (token is not None and hedge is not None
                                   and token == hedge["token"])
                    holds_lease = holds_primary or holds_hedge
                    if holds_primary:
                        self._clear_lease(key)
                        self.rejected_submits_total += 1
                        self._record_failure(batch, prover_type,
                                             "invalid proof")
                    elif holds_hedge:
                        # the hedge loses its lease, but the primary is
                        # still proving: no failure against the batch
                        self.hedges.pop(key, None)
                        self.rejected_submits_total += 1
                        self._note_event("hedge-rejected", batch,
                                         prover_type, "invalid proof")
                    else:
                        # an invalid proof from a non-holder must not
                        # evict the live holder's lease or burn the
                        # batch's quarantine budget (unauthenticated
                        # downgrade vector)
                        self.stale_submits_total += 1
                if holds_lease:
                    return {"type": protocol.ERROR,
                            "message": f"invalid proof for batch {batch}"}
                from ..utils.metrics import record_stale_submit

                record_stale_submit()
                return {"type": protocol.ERROR,
                        "message": f"stale lease token for batch "
                                   f"{batch}; proof rejected"}
        elif not holds_lease:
            # without submit-time verification the token is the only gate
            # keeping arbitrary connections out of the proof store
            with self.lock:
                self.stale_submits_total += 1
            from ..utils.metrics import record_stale_submit

            record_stale_submit()
            return {"type": protocol.ERROR,
                    "message": f"stale lease token for batch {batch}"}
        with tracing.trace_context(msg.get("trace_id")
                                   or self.batch_traces.get(batch),
                                   msg.get("span_id")):
            with tracing.span("prover.store_proof", batch=batch,
                              prover_type=prover_type):
                proof = faults.inject("coordinator.store_proof", proof)
                self.rollup.store_proof(batch, prover_type, proof)
        with self.lock:
            warm_at_grant = self.lease_warm.get(key)
            started = self._clear_lease(key)
            hedge = self.hedges.pop(key, None)
            if holds_hedge and hedge is not None:
                # the hedge won the race: its own start time is the
                # proving clock, not the straggler's
                started = hedge["assigned_at"]
                warm_at_grant = hedge.get("warm")
            self._note_event("proof-stored", batch, prover_type,
                             "hedge won" if holds_hedge else None)
        # chain-path X-ray: sampled lifecycles of this batch's txs get
        # their proved mark (never raises — telemetry only)
        try:
            from ..perf.chain_path import CHAIN_PATH

            CHAIN_PATH.batch_proved(batch)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        if started is not None and holds_lease:
            # proving-time metric (reference: set_batch_proving_time,
            # proof_coordinator.rs:286-296) — only meaningful when the
            # submitter is the prover the clock was started for
            from ..utils.metrics import record_batch

            duration = self._now() - started
            # the exemplar ties this observation's bucket to the batch's
            # merged trace in the OpenMetrics exposition
            record_batch(batch, duration,
                         trace_id=self.batch_traces.get(batch))
            prover_id = msg.get("prover_id")
            with self.lock:
                # feed the fleet scheduler: the p99 hedging deadline and
                # this prover's EWMA placement signal.  A batch granted
                # to a prover that reported itself cold is excluded from
                # both — its wall includes AOT compile time, and one
                # such sample would poison the EWMA placement and the
                # p99 hedge deadline for dozens of proofs after
                if warm_at_grant is not False:
                    self.durations.append(duration)
                if prover_id is not None:
                    st = self.prover_stats.setdefault(
                        prover_id, {"completed": 0, "ewma": None,
                                    "last_seen": self._now()})
                    st["completed"] += 1
                    if warm_at_grant is not False:
                        st["ewma"] = duration if st["ewma"] is None else \
                            EWMA_ALPHA * duration \
                            + (1.0 - EWMA_ALPHA) * st["ewma"]
        return {"type": protocol.SUBMIT_ACK, "batch_id": batch}

    def handle_request(self, msg: dict) -> dict:
        with self._inflight_cv:
            self._inflight += 1
        try:
            return self._handle_request(msg)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _handle_request(self, msg: dict) -> dict:
        mtype = msg.get("type")
        if mtype == protocol.INPUT_REQUEST:
            if msg.get("commit_hash") != self.commit_hash:
                return {"type": protocol.VERSION_MISMATCH,
                        "expected": self.commit_hash}
            prover_type = msg.get("prover_type")
            if prover_type not in self._allowed_types():
                return {"type": protocol.TYPE_NOT_NEEDED}
            warm = msg.get("warm")
            batch, token = self.assign(
                prover_type, msg.get("prover_id"),
                warm=warm if isinstance(warm, bool) else None)
            if batch is None:
                return {"type": protocol.TYPE_NOT_NEEDED}
            trace_id = self.trace_for_batch(batch)
            assign_span = None
            with tracing.trace_context(trace_id):
                with tracing.span("prover.assign", batch=batch,
                                  prover_type=prover_type) as sp:
                    program_input = self.rollup.get_prover_input(
                        batch, self.commit_hash)
                    assign_span = sp.span_id if sp else None
            with self.lock:
                self._note_event("assign", batch, prover_type)
            return {"type": protocol.INPUT_RESPONSE, "batch_id": batch,
                    "input": program_input, "format": self.proof_format,
                    "lease_token": token,
                    "trace_id": trace_id, "span_id": assign_span}
        if mtype == protocol.HEARTBEAT:
            return self._handle_heartbeat(msg)
        if mtype == protocol.PROOF_SUBMIT:
            return self._handle_submit(msg)
        return {"type": protocol.ERROR, "message": f"unknown type {mtype}"}

    # ------------------------------------------------------------------
    def stats_json(self) -> dict:
        """Health-endpoint view of the resilience state."""
        with self.lock:
            return {
                "liveAssignments": sum(
                    1 for d in self.assignments.values()
                    if d > self._now()),
                "reassignments": self.reassignments_total,
                "heartbeats": self.heartbeats_total,
                "rejectedSubmits": self.rejected_submits_total,
                "unsolicitedSubmits": self.unsolicited_submits_total,
                "staleSubmits": self.stale_submits_total,
                "quarantined": sorted(self.quarantined),
                "failures": {f"{num}/{ptype}": count
                             for (num, ptype), count
                             in sorted(self.failures.items())},
                "recentEvents": list(self.events),
                "scheduler": self._scheduler_stats_locked(),
                "runtime": self._runtime_stats_locked(),
            }

    def _runtime_stats_locked(self) -> dict:
        """This process's prover-runtime counters (resumes, ladder
        retries, checkpoint traffic) plus what the fleet's heartbeats
        reported: which provers run degraded and which phase each live
        lease is in.  Caller holds self.lock."""
        from ..prover import runtime_errors as rt_mod

        now = self._now()
        stats = rt_mod.runtime_stats()
        stats["poisonReports"] = self.poison_reports_total
        stats["degradedProvers"] = {
            pid: st["degraded"]
            for pid, st in sorted(self.prover_stats.items())
            if st.get("degraded") is not None}
        stats["livePhases"] = [
            {"batch": num, "proverType": ptype, "phase": phase,
             "sincePhaseSeconds": max(0.0, now - since)}
            for (num, ptype), (phase, since)
            in sorted(self.lease_phase.items())
            if self.assignments.get((num, ptype), 0.0) > now
            or ((num, ptype) in self.hedges
                and self.hedges[(num, ptype)]["expires"] > now)]
        return stats

    def _scheduler_stats_locked(self) -> dict:
        """Caller holds self.lock."""
        now = self._now()
        deadline = self._hedge_deadline()
        return {
            "policy": self.scheduler_policy,
            "queueDepth": self.queue_depth,
            "hedgedAssignments": self.hedged_assignments_total,
            "duplicateSubmits": self.duplicate_submits_total,
            "coldDeferrals": self.cold_deferrals_total,
            "hedgeDeadlineSeconds": deadline,
            "liveHedges": [
                {"batch": num, "proverType": ptype,
                 "reason": h.get("reason"),
                 "proverId": h.get("prover_id")}
                for (num, ptype), h in sorted(self.hedges.items())
                if h["expires"] > now],
            "provers": {
                pid: {"completed": st["completed"],
                      "ewmaSeconds": st["ewma"],
                      "liveLeases": self._live_leases_held(pid, now),
                      "idleSeconds": max(0.0, now - st["last_seen"]),
                      "warm": st.get("warm"),
                      "coldDeferrals": st.get("cold_deferrals", 0),
                      "degraded": st.get("degraded")}
                for pid, st in sorted(self.prover_stats.items())},
        }

    # ------------------------------------------------------------------
    def start(self):
        coordinator = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = protocol.recv_msg_file(self.rfile)
                    except (ValueError, ConnectionError):
                        break
                    if msg is None:
                        break
                    try:
                        resp = coordinator.handle_request(msg)
                    except Exception as e:  # noqa: BLE001 — internal
                        # failure (or an injected one): drop the
                        # connection, keep the lease; expiry re-assigns
                        log.warning("coordinator request failed: %s", e)
                        break
                    try:
                        protocol.send_msg(self.connection, resp)
                    except (ConnectionError, OSError):
                        break

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        if self._server is not None:
            return self    # idempotent: Sequencer.start() re-enters here
        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self

    def stop(self, timeout: float = 5.0) -> bool:
        """Stop accepting connections, then wait (bounded) for in-flight
        requests to finish so a proof submit already past the wire lands
        in the rollup store instead of being dropped mid-handler.
        Returns True when the drain completed inside the deadline."""
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            # allow stop -> start cycles (sequencer HA re-homes the
            # prover fleet across demote/promote): a later start()
            # rebinds the SAME port (self.port was pinned at first
            # bind), so prover endpoint lists stay valid
            self._server = None
        deadline = time.monotonic() + timeout
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning("%d coordinator request(s) still in flight "
                                "after %.1fs drain deadline; their leases "
                                "will expire and reassign", self._inflight,
                                timeout)
                    return False
                self._inflight_cv.wait(remaining)
        return True
