"""Rollup store: batches, prover inputs, proofs (parity with the reference's
StoreRollup, crates/l2/storage/src/store.rs).  The in-memory store is the
universal test fake; PersistentRollupStore adds write-through persistence
over the native append-only KV (the reference's SQL backend seat), giving
the committer durable per-batch checkpoints: a killed sequencer reopens
the store and resumes at the right batch (l1_committer.rs:389,529,1242
ensure_checkpoint_for_committed_batch / state regeneration)."""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading

from .leadership import FencedError

# persisted leadership watermark (sequencer HA, docs/SEQUENCER_HA.md):
# the highest fencing epoch this store has observed; write groups
# stamped below it are a deposed leader's zombie writes and are refused
LEADERSHIP_META_KEY = "leadership"


@dataclasses.dataclass
class Batch:
    number: int
    first_block: int
    last_block: int
    state_root: bytes
    commitment: bytes = b""        # commitment tx data hash (L1)
    committed: bool = False
    verified: bool = False
    # VM-circuit coverage the committer derived for this batch
    # ("transfer" | "token" | "generic" | "claimed"); wire verifiers
    # reject tpu proofs whose mode differs — a prover cannot downgrade a
    # circuit-covered batch to the claimed-log form (review finding)
    vm_mode: str = ""


class RollupStore:
    def __init__(self):
        self.batches: dict[int, Batch] = {}
        self.prover_inputs: dict[tuple[int, str], dict] = {}
        #   (batch_number, commit_hash_version) -> ProgramInput json
        self.proofs: dict[tuple[int, str], dict] = {}
        #   (batch_number, prover_type) -> proof
        self.blobs: dict[int, object] = {}
        #   batch_number -> BlobsBundle (the L1 data-availability sidecar)
        self._meta: dict = {}
        #   sequencer checkpoints (deposit cursor, ...)
        self.lock = threading.RLock()

    # ---------------- batches ----------------
    def store_batch(self, batch: Batch):
        with self.lock:
            self.batches[batch.number] = batch

    def get_batch(self, number: int) -> Batch | None:
        return self.batches.get(number)

    def latest_batch_number(self) -> int:
        with self.lock:
            return max(self.batches) if self.batches else 0

    def set_committed(self, number: int, commitment: bytes):
        with self.lock:
            b = self.batches[number]
            b.committed = True
            b.commitment = commitment

    def set_verified(self, number: int):
        with self.lock:
            self.batches[number].verified = True

    def set_settlement(self, number: int, committed: bool | None = None,
                       verified: bool | None = None):
        """Flag-only settlement update (no commitment payload): the state
        updater adopts/rolls back L1 settlement status through this so the
        persistent store's write-through always sees it — mutating
        `batch.committed` in place silently loses the flag on restart."""
        with self.lock:
            b = self.batches[number]
            if committed is not None:
                b.committed = committed
            if verified is not None:
                b.verified = verified

    def delete_batch(self, number: int):
        """Drop a batch and all its artifacts (proofs, prover inputs,
        blobs) — the reorg path's last resort when a dropped commitment
        cannot be re-submitted verbatim and the blocks must be re-batched
        from scratch."""
        with self.lock:
            self.batches.pop(number, None)
            for key in [k for k in self.prover_inputs if k[0] == number]:
                self.prover_inputs.pop(key, None)
            for key in [k for k in self.proofs if k[0] == number]:
                self.proofs.pop(key, None)
            self.blobs.pop(number, None)

    # ---------------- prover inputs ----------------
    def store_blobs_bundle(self, batch_number: int, bundle) -> None:
        with self.lock:
            self.blobs[batch_number] = bundle

    def get_blobs_bundle(self, batch_number: int):
        with self.lock:
            return self.blobs.get(batch_number)

    def store_prover_input(self, batch_number: int, version: str,
                           program_input_json: dict):
        with self.lock:
            self.prover_inputs[(batch_number, version)] = program_input_json

    def get_prover_input(self, batch_number: int, version: str):
        return self.prover_inputs.get((batch_number, version))

    # ---------------- proofs ----------------
    def store_proof(self, batch_number: int, prover_type: str, proof: dict):
        with self.lock:
            key = (batch_number, prover_type)
            if key in self.proofs:
                return  # duplicate submissions are a no-op (ref behavior)
            self.proofs[key] = proof

    def get_proof(self, batch_number: int, prover_type: str):
        return self.proofs.get((batch_number, prover_type))

    def delete_proof(self, batch_number: int, prover_type: str):
        """Invalid proofs are deleted so the batch is re-proven
        (reference: distributed_proving.md:70-72)."""
        with self.lock:
            self.proofs.pop((batch_number, prover_type), None)

    def batch_fully_proven(self, batch_number: int,
                           needed_types: list[str]) -> bool:
        return all((batch_number, t) in self.proofs for t in needed_types)

    # ---------------- sequencer checkpoints ----------------
    def get_meta(self, key: str, default=None):
        return self._meta.get(key, default)

    def set_meta(self, key: str, value):
        with self.lock:
            self._meta[key] = value

    # ---------------- leadership fencing ----------------
    def leadership_epoch(self) -> int:
        """Highest fencing epoch this store has observed (0 = never)."""
        meta = self.get_meta(LEADERSHIP_META_KEY) or {}
        return int(meta.get("epoch", 0))

    def fence(self, epoch: int):
        """Raise the persisted leadership watermark (monotonic; a lower
        epoch never rewinds it).  The promoting leader calls this before
        resuming actors, so any zombie write stamped with an older epoch
        is refused from that point on."""
        with self.lock:
            if epoch > self.leadership_epoch():
                self.set_meta(LEADERSHIP_META_KEY, {"epoch": int(epoch)})

    def _check_epoch(self, epoch: int | None):
        if epoch is None:
            return
        current = self.leadership_epoch()
        if epoch < current:
            raise FencedError(
                f"write group fenced: epoch {epoch} < store watermark "
                f"{current}", epoch=epoch, current=current)

    # ---------------- lifecycle ----------------
    def write_group(self, epoch: int | None = None):
        """Atomic multi-record write group (batch + blobs + input +
        settlement flags as one unit); no journal needed in memory.
        `epoch` is the writer's fencing token (sequencer HA) — a stale
        epoch raises FencedError instead of entering the group."""
        self._check_epoch(epoch)
        return contextlib.nullcontext(self)

    def close(self):
        """Release backing resources; no-op in memory, idempotent."""


class PersistentRollupStore(RollupStore):
    """RollupStore with write-through persistence (native KV backend).

    Layout: one table per kind, JSON values (proofs and prover inputs are
    wire-JSON already; blobs bundles carry hex blobs).  Opening the store
    materializes everything back into the in-memory dicts, so reads stay
    dict-fast and the restart path needs no special-casing."""

    def __init__(self, path: str):
        super().__init__()
        from ..storage.persistent import PersistentBackend

        self.backend = PersistentBackend(path)
        self._t_batches = self.backend.table("rollup_batches")
        self._t_inputs = self.backend.table("rollup_inputs")
        self._t_proofs = self.backend.table("rollup_proofs")
        self._t_blobs = self.backend.table("rollup_blobs")
        self._t_meta = self.backend.table("rollup_meta")
        self._load()

    # -- codecs ------------------------------------------------------------
    @staticmethod
    def _batch_json(b: Batch) -> bytes:
        return json.dumps({
            "number": b.number, "first": b.first_block,
            "last": b.last_block, "root": b.state_root.hex(),
            "commitment": b.commitment.hex(),
            "committed": b.committed, "verified": b.verified,
            "vm_mode": b.vm_mode,
        }).encode()

    @staticmethod
    def _batch_from(raw: bytes) -> Batch:
        o = json.loads(raw)
        return Batch(number=o["number"], first_block=o["first"],
                     last_block=o["last"],
                     state_root=bytes.fromhex(o["root"]),
                     commitment=bytes.fromhex(o["commitment"]),
                     committed=o["committed"], verified=o["verified"],
                     vm_mode=o.get("vm_mode", ""))

    @staticmethod
    def _bundle_json(bundle) -> bytes:
        return json.dumps({
            "blobs": [b.hex() for b in bundle.blobs],
            "commitments": [c.hex() for c in bundle.commitments],
            "proofs": [p.hex() for p in bundle.proofs],
        }).encode()

    @staticmethod
    def _bundle_from(raw: bytes):
        from .blobs import BlobsBundle

        o = json.loads(raw)
        return BlobsBundle(
            blobs=[bytes.fromhex(b) for b in o["blobs"]],
            commitments=[bytes.fromhex(c) for c in o["commitments"]],
            proofs=[bytes.fromhex(p) for p in o["proofs"]])

    def _load(self):
        for key, raw in self._t_batches.items():
            b = self._batch_from(raw)
            self.batches[b.number] = b
        for key, raw in self._t_inputs.items():
            n_s, _, ver = key.decode().partition("/")
            self.prover_inputs[(int(n_s), ver)] = json.loads(raw)
        for key, raw in self._t_proofs.items():
            n_s, _, ptype = key.decode().partition("/")
            self.proofs[(int(n_s), ptype)] = json.loads(raw)
        for key, raw in self._t_blobs.items():
            self.blobs[int(key.decode())] = self._bundle_from(raw)
        for key, raw in self._t_meta.items():
            self._meta[key.decode()] = json.loads(raw)

    # -- write-through overrides ------------------------------------------
    def _put_batch(self, b: Batch):
        self._t_batches[str(b.number).encode()] = self._batch_json(b)
        self.backend.flush()

    def store_batch(self, batch: Batch):
        super().store_batch(batch)
        self._put_batch(batch)

    def set_committed(self, number: int, commitment: bytes):
        super().set_committed(number, commitment)
        self._put_batch(self.batches[number])

    def set_verified(self, number: int):
        super().set_verified(number)
        self._put_batch(self.batches[number])

    def set_settlement(self, number: int, committed: bool | None = None,
                       verified: bool | None = None):
        super().set_settlement(number, committed=committed,
                               verified=verified)
        self._put_batch(self.batches[number])

    def delete_batch(self, number: int):
        with self.lock:
            input_keys = [k for k in self.prover_inputs if k[0] == number]
            proof_keys = [k for k in self.proofs if k[0] == number]
            super().delete_batch(number)
            # all artifacts drop as one journaled unit: a crash mid-delete
            # must not leave a proof whose batch record is gone
            with self.write_group():
                self._t_batches.pop(str(number).encode(), None)
                for n, ver in input_keys:
                    self._t_inputs.pop(f"{n}/{ver}".encode(), None)
                for n, ptype in proof_keys:
                    self._t_proofs.pop(f"{n}/{ptype}".encode(), None)
                self._t_blobs.pop(str(number).encode(), None)
            self.backend.flush()

    def store_prover_input(self, batch_number: int, version: str,
                           program_input_json: dict):
        super().store_prover_input(batch_number, version,
                                   program_input_json)
        key = f"{batch_number}/{version}".encode()
        self._t_inputs[key] = json.dumps(program_input_json).encode()
        self.backend.flush()

    def store_proof(self, batch_number: int, prover_type: str, proof: dict):
        with self.lock:
            existed = (batch_number, prover_type) in self.proofs
            super().store_proof(batch_number, prover_type, proof)
            if not existed:
                key = f"{batch_number}/{prover_type}".encode()
                self._t_proofs[key] = json.dumps(proof).encode()
                self.backend.flush()

    def delete_proof(self, batch_number: int, prover_type: str):
        super().delete_proof(batch_number, prover_type)
        self._t_proofs.pop(f"{batch_number}/{prover_type}".encode(), None)
        self.backend.flush()

    def store_blobs_bundle(self, batch_number: int, bundle) -> None:
        super().store_blobs_bundle(batch_number, bundle)
        self._t_blobs[str(batch_number).encode()] = \
            self._bundle_json(bundle)
        self.backend.flush()

    def set_meta(self, key: str, value):
        super().set_meta(key, value)
        self._t_meta[key.encode()] = json.dumps(value).encode()
        self.backend.flush()

    def write_group(self, epoch: int | None = None):
        """Journaled multi-record commit: the committer's batch-record
        group (store_batch + blobs + prover input + set_committed) lands
        atomically — a crash between the writes reopens to either the
        full record or none of it (startup reconciliation rebuilds the
        latter from L1; see docs/L1_SETTLEMENT_RESILIENCE.md).  A stale
        fencing `epoch` is refused before the journal opens."""
        self._check_epoch(epoch)
        return self.backend.batch()

    def close(self):
        self.backend.close()
