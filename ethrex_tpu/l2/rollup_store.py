"""Rollup store: batches, prover inputs, proofs (parity with the reference's
StoreRollup, crates/l2/storage/src/store.rs — in-memory backend first)."""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class Batch:
    number: int
    first_block: int
    last_block: int
    state_root: bytes
    commitment: bytes = b""        # commitment tx data hash (L1)
    committed: bool = False
    verified: bool = False


class RollupStore:
    def __init__(self):
        self.batches: dict[int, Batch] = {}
        self.prover_inputs: dict[tuple[int, str], dict] = {}
        #   (batch_number, commit_hash_version) -> ProgramInput json
        self.proofs: dict[tuple[int, str], dict] = {}
        #   (batch_number, prover_type) -> proof
        self.blobs: dict[int, object] = {}
        #   batch_number -> BlobsBundle (the L1 data-availability sidecar)
        self.lock = threading.RLock()

    # ---------------- batches ----------------
    def store_batch(self, batch: Batch):
        with self.lock:
            self.batches[batch.number] = batch

    def get_batch(self, number: int) -> Batch | None:
        return self.batches.get(number)

    def latest_batch_number(self) -> int:
        with self.lock:
            return max(self.batches) if self.batches else 0

    def set_committed(self, number: int, commitment: bytes):
        with self.lock:
            b = self.batches[number]
            b.committed = True
            b.commitment = commitment

    def set_verified(self, number: int):
        with self.lock:
            self.batches[number].verified = True

    # ---------------- prover inputs ----------------
    def store_blobs_bundle(self, batch_number: int, bundle) -> None:
        with self.lock:
            self.blobs[batch_number] = bundle

    def get_blobs_bundle(self, batch_number: int):
        with self.lock:
            return self.blobs.get(batch_number)

    def store_prover_input(self, batch_number: int, version: str,
                           program_input_json: dict):
        with self.lock:
            self.prover_inputs[(batch_number, version)] = program_input_json

    def get_prover_input(self, batch_number: int, version: str):
        return self.prover_inputs.get((batch_number, version))

    # ---------------- proofs ----------------
    def store_proof(self, batch_number: int, prover_type: str, proof: dict):
        with self.lock:
            key = (batch_number, prover_type)
            if key in self.proofs:
                return  # duplicate submissions are a no-op (ref behavior)
            self.proofs[key] = proof

    def get_proof(self, batch_number: int, prover_type: str):
        return self.proofs.get((batch_number, prover_type))

    def delete_proof(self, batch_number: int, prover_type: str):
        """Invalid proofs are deleted so the batch is re-proven
        (reference: distributed_proving.md:70-72)."""
        with self.lock:
            self.proofs.pop((batch_number, prover_type), None)

    def batch_fully_proven(self, batch_number: int,
                           needed_types: list[str]) -> bool:
        return all((batch_number, t) in self.proofs for t in needed_types)
