"""Based-rollup follower: fetch committed batches from L1 and import them.

The reference's based mode lets any node follow the canonical L2 chain
from L1 data alone (crates/l2/based/block_fetcher.rs:72): the fetcher
walks the committed batches, pulls each commit's blob sidecar, decodes
the block payload, executes it locally, and checks the resulting state
root against the one committed on L1.  Here the sidecar comes from the
L1 client's DA record (the commit transaction IS the blob carrier;
InMemoryL1 keeps the bundles, an RPC L1 serves them from the chain).
"""

from __future__ import annotations

import threading

from .blobs import BlobsBundle, reconstruct_blocks
from .rollup_store import Batch


class FetchError(Exception):
    pass


class BlockFetcher:
    """Import committed batches from L1 into a local node."""

    def __init__(self, node, l1, rollup=None, unhealthy_after: int = 5):
        self.node = node
        self.l1 = l1
        self.rollup = rollup
        self.next_batch = 1
        self.fatal: FetchError | None = None
        # transient-failure accounting: a follower that silently stops
        # following is a stale hot standby (docs/SEQUENCER_HA.md), so
        # healthy() flips after `unhealthy_after` CONSECUTIVE failures
        self.unhealthy_after = unhealthy_after
        self.fetch_errors = 0
        self.consecutive_failures = 0
        self.last_error: str | None = None
        self.batches_imported = 0
        self._stop = threading.Event()
        self._thread = None

    def fetch_once(self) -> int:
        """Import every not-yet-imported committed batch; returns the
        number of batches imported.  Raises FetchError on a state-root
        divergence (the local execution disagrees with L1) — a fatal
        condition for a follower."""
        imported = 0
        last = self.l1.last_committed_batch()
        while self.next_batch <= last:
            number = self.next_batch
            bundle = self.l1.get_blob_sidecar(number)
            if bundle is None:
                raise FetchError(f"no blob sidecar for batch {number}")
            if isinstance(bundle, dict):
                bundle = BlobsBundle(**bundle)
            if not bundle.verify():
                raise FetchError(f"batch {number}: bad KZG sidecar")
            blocks = reconstruct_blocks(bundle)
            for block in blocks:
                if self.node.store.get_header(block.hash) is None:
                    self.node.chain.add_block(block)
                from ..blockchain.fork_choice import apply_fork_choice

                apply_fork_choice(self.node.store, block.hash,
                                  block.hash, block.hash)
            committed_root = self.l1.get_committed_state_root(number)
            local_root = blocks[-1].header.state_root
            if committed_root is not None \
                    and committed_root != local_root:
                raise FetchError(
                    f"batch {number}: local root "
                    f"0x{local_root.hex()} != committed "
                    f"0x{committed_root.hex()}")
            if self.rollup is not None:
                self.rollup.store_batch(Batch(
                    number=number,
                    first_block=blocks[0].header.number,
                    last_block=blocks[-1].header.number,
                    state_root=local_root, commitment=b"",
                    committed=True))
                self.rollup.store_blobs_bundle(number, bundle)
            self.next_batch += 1
            imported += 1
            self.batches_imported += 1
        self.consecutive_failures = 0
        self.last_error = None
        return imported

    def healthy(self) -> bool:
        """False on a fatal divergence OR when transient fetch failures
        have run uninterrupted past the unhealthy_after threshold — a
        standby this stale must not win a promotion race unchecked."""
        if self.fatal is not None:
            return False
        return self.consecutive_failures < self.unhealthy_after

    def start(self, interval: float = 1.0):
        if self._thread is not None and self._thread.is_alive():
            return  # already fetching

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.fetch_once()
                except FetchError as exc:
                    # Fatal for a follower (state-root divergence / bad DA):
                    # record it so health checks surface the failure instead
                    # of an unhandled daemon-thread traceback, and stop
                    # fetching — the frozen chain must not silently advance.
                    self.fatal = exc
                    self._stop.set()
                    return
                except Exception as exc:
                    # transient L1 errors: retry next tick, but count —
                    # an unbroken run of these flips healthy()
                    self.fetch_errors += 1
                    self.consecutive_failures += 1
                    self.last_error = f"{type(exc).__name__}: {exc}"
                    continue

        # restart-after-stop: a stopped fetcher (promotion demoted back
        # to follower) resumes from next_batch with a fresh stop event
        self._stop = threading.Event()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        """Idempotent: safe to call repeatedly and before start()."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
        self._thread = None
