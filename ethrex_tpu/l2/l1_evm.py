"""EvmL1: the dev L1 whose settlement path runs the OnChainProposer
BYTECODE (l2/proposer_evm.py) through our own EVM.

Drop-in for InMemoryL1 everywhere the sequencer settles: commitBatch /
verifyBatches are real contract transactions — selector dispatch,
storage mappings, revert identifiers, and a STATICCALL into the
registered verifier (a dev precompile hook running the in-process proof
checks, the seat of the reference's on-chain verifier contracts).  The
CommonBridge surface (deposits, withdrawal claims, blob sidecars) stays
on the Python rules from the round-4 port.

Reference: crates/l2/contracts/src/l1/OnChainProposer.sol + the
deployment flow in cmd/ethrex/l2/deployer.rs.
"""

from __future__ import annotations

import json

from ..evm.db import InMemorySource, StateDB
from ..evm.vm import EVM, BlockEnv, Message
from ..primitives.account import Account
from ..primitives.genesis import ChainConfig, Fork
from .l1_client import InMemoryL1, L1Error, make_deposit_tx
from .proposer_evm import (PROPOSER_ADDRESS, SEL_COMMIT, SEL_VERIFY,
                           VERIFIER_ADDRESS, build_runtime, decode_revert)

OWNER = bytes.fromhex("aa" * 20)

# proposer storage slot mirroring the leader-lease fencing epoch
# (slots 0-6 belong to the settlement state machine, proposer_evm.py);
# on a real deployment this is the OnChainProposer's lease cell
LEASE_EPOCH_SLOT = 7


def _word(v) -> bytes:
    if isinstance(v, bytes):
        return v.rjust(32, b"\x00")
    return int(v).to_bytes(32, "big")


class EvmL1(InMemoryL1):
    def __init__(self, needed_prover_types, l2_chain_id=None):
        super().__init__(needed_prover_types, l2_chain_id=l2_chain_id)
        cfg = ChainConfig(chain_id=1)
        cfg.time_forks = {Fork.SHANGHAI: 0, Fork.CANCUN: 0}
        self._config = cfg
        src = InMemorySource(accounts={
            PROPOSER_ADDRESS: Account.new(
                code=build_runtime(),
                storage={3: int.from_bytes(OWNER, "big")}),
            OWNER: Account.new(balance=10**21),
        })
        self.state = StateDB(src)
        self._pending_proofs: dict[int, dict] = {}

    # ---- EVM plumbing ----------------------------------------------------
    def _verifier_precompile(self, data: bytes, gas: int, fork):
        """The registered-verifier seat: (number, stateRoot, messagesRoot,
        commitHash) -> 1 iff every needed prover type's submitted proof
        binds the CONTRACT-stored roots for that batch."""
        from ..guest.execution import ProgramOutput

        ok = b"\x00" * 32
        try:
            number = int.from_bytes(data[0:32], "big")
            root = data[32:64]
            msgs = data[64:96]
            batch_proofs = self._pending_proofs.get(number)
            if batch_proofs is not None:
                good = True
                for t in self.needed:
                    raw = batch_proofs.get(t)
                    if raw is None:
                        good = False
                        break
                    obj = json.loads(raw)
                    out = ProgramOutput.decode(
                        bytes.fromhex(obj["output"][2:]))
                    if out.final_state_root != root or \
                            out.messages_root != msgs:
                        good = False
                        break
                if good:
                    ok = _word(1)
        except (ValueError, KeyError, TypeError):
            pass
        return 100, ok

    def _tx(self, data: bytes, sender: bytes = OWNER) -> bytes:
        env = BlockEnv(number=1, coinbase=b"\x00" * 20, timestamp=1,
                       gas_limit=30_000_000, prev_randao=b"\x00" * 32,
                       base_fee=0)
        evm = EVM(self.state, env, self._config)
        evm.extra_precompiles[VERIFIER_ADDRESS] = self._verifier_precompile
        self.state.begin_tx()
        ok, _gas, out = evm.execute_message(Message(
            caller=sender, to=PROPOSER_ADDRESS,
            code_address=PROPOSER_ADDRESS, value=0, data=data,
            gas=10_000_000, kind="CALL"))
        self.state.finalize_tx()
        if not ok:
            raise L1Error(f"proposer reverted: {decode_revert(out)}")
        return out

    def _slot(self, slot: int) -> int:
        return self.state.get_storage(PROPOSER_ADDRESS, slot)

    # ---- leader lease: epoch mirrored into contract storage -------------
    def acquire_lease(self, node_id: str, ttl: float) -> int | None:
        epoch = super().acquire_lease(node_id, ttl)
        if epoch is not None:
            with self.lock:
                self.state.set_storage(PROPOSER_ADDRESS, LEASE_EPOCH_SLOT,
                                       epoch)
        return epoch

    def lease_epoch_slot(self) -> int:
        """The on-contract view of the fencing epoch (test surface)."""
        with self.lock:
            return self._slot(LEASE_EPOCH_SLOT)

    # ---- OnChainProposer through the bytecode ---------------------------
    def commit_batch(self, number, new_state_root, commitment,
                     privileged_tx_hashes=(),
                     messages_root=b"\x00" * 32, epoch=None) -> bytes:
        with self.lock:
            self._check_epoch(epoch)
            # CommonBridge seat: privileged txs must match the deposit
            # queue (read-only pre-check; python bookkeeping below)
            cursor = self.consumed_deposits
            for h in privileged_tx_hashes:
                if cursor >= len(self.deposits):
                    raise L1Error("privileged tx without matching deposit")
                if self.l2_chain_id is not None:
                    expected = make_deposit_tx(
                        self.l2_chain_id, self.deposits[cursor]).hash
                    if h != expected:
                        raise L1Error(
                            f"privileged tx {h.hex()} does not match "
                            f"deposit {cursor}")
                cursor += 1
            data = (SEL_COMMIT.to_bytes(4, "big") + _word(number)
                    + _word(new_state_root) + _word(messages_root)
                    + _word(commitment))
            self._tx(data)
            self.consumed_deposits = cursor
            self.commitments[number] = (new_state_root, commitment)
            self.message_roots[number] = bytes(messages_root)
            from ..crypto.keccak import keccak256

            return keccak256(b"commit" + number.to_bytes(8, "big")
                             + commitment)

    def verify_batches(self, first, last, proofs, epoch=None) -> bytes:
        with self.lock:
            self._check_epoch(epoch)
            pending: dict[int, dict] = {}
            for t in self.needed:
                batch_proofs = proofs.get(t)
                if not batch_proofs or \
                        len(batch_proofs) != last - first + 1:
                    raise L1Error(f"missing {t} proofs")
                for offset, raw in enumerate(batch_proofs):
                    pending.setdefault(first + offset, {})[t] = raw
            self._pending_proofs = pending
            try:
                data = (SEL_VERIFY.to_bytes(4, "big") + _word(first)
                        + _word(last - first + 1))
                self._tx(data)
            finally:
                self._pending_proofs = {}
            self.verified_up_to = last
            from ..crypto.keccak import keccak256

            return keccak256(b"verify" + first.to_bytes(8, "big")
                             + last.to_bytes(8, "big"))

    def last_committed_batch(self) -> int:
        return self._slot(0)

    def last_verified_batch(self) -> int:
        return self._slot(1)

    def reorg(self, depth: int) -> int:
        # InMemoryL1's snapshot rewind cannot roll back EVM storage;
        # refusing beats silently forking the two views of settlement
        raise L1Error("reorg is not supported on EvmL1")
