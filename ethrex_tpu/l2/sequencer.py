"""L2 sequencer: the actor set from the reference's
crates/l2/sequencer/mod.rs:47 start_l2 — BlockProducer, L1Committer,
ProofCoordinator (own module), L1ProofSender, L1Watcher, StateUpdater —
re-expressed as timer-driven components over the Node + RollupStore +
L1Client.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

log = logging.getLogger("ethrex_tpu.l2.sequencer")

from ..crypto.keccak import keccak256
from ..guest.execution import ProgramInput
from ..guest.witness import generate_witness
from ..node import Node
from ..primitives.transaction import TYPE_PRIVILEGED, Transaction
from ..prover import protocol
from .l1_client import L1Client
from .proof_coordinator import ProofCoordinator
from .rollup_store import Batch, RollupStore


@dataclasses.dataclass
class SequencerConfig:
    block_time: float = 1.0
    commit_interval: float = 2.0
    proof_send_interval: float = 2.0
    watcher_interval: float = 1.0
    needed_prover_types: tuple = (protocol.PROVER_TPU,)
    commit_hash: str = protocol.PROTOCOL_VERSION
    # failure handling (reference: the fatal-subsystem cancellation token
    # pattern, cmd/ethrex/ethrex.rs, + per-actor health endpoints)
    max_actor_failures: int = 10
    max_backoff_factor: int = 32
    # prover resilience (docs/PROVER_RESILIENCE.md): assignment lease
    # length (heartbeats extend it), the hard cap on how long heartbeats
    # can keep one assignment alive (None -> coordinator default of
    # 6 leases; bounds hung provers), and how many failed assignments of
    # a batch to its primary prover type trigger the exec fallback
    prover_lease_timeout: float = 600.0
    prover_max_lease_lifetime: float | None = None
    prover_quarantine_threshold: int = 3


@dataclasses.dataclass
class ActorHealth:
    """Per-actor failure/backoff state, exposed via ethrex_health."""

    name: str
    runs: int = 0
    consecutive_failures: int = 0
    last_error: str | None = None
    last_success: float | None = None

    @property
    def healthy(self) -> bool:
        return self.consecutive_failures == 0

    def to_json(self) -> dict:
        return {
            "healthy": self.healthy,
            "runs": self.runs,
            "consecutiveFailures": self.consecutive_failures,
            "lastError": self.last_error,
            "lastSuccess": self.last_success,
        }


class Sequencer:
    """Wires all L2 actors (reference: start_l2)."""

    # the timer-driven actor set; start() loops over these names and the
    # admin pause/resume surface validates against them (keeping the RPC
    # and the loop keyed to one registry instead of magic strings)
    ACTOR_NAMES = ("produce_block", "commit_next_batch", "send_proofs",
                   "watch_l1", "update_state")

    def __init__(self, node: Node, l1: L1Client,
                 config: SequencerConfig | None = None,
                 rollup: RollupStore | None = None):
        self.node = node
        self.l1 = l1
        self.cfg = config or SequencerConfig()
        self.rollup = rollup if rollup is not None else RollupStore()
        self.coordinator = ProofCoordinator(
            self.rollup, needed_types=list(self.cfg.needed_prover_types),
            commit_hash=self.cfg.commit_hash,
            lease_timeout=self.cfg.prover_lease_timeout,
            quarantine_threshold=self.cfg.prover_quarantine_threshold,
            max_lease_lifetime=self.cfg.prover_max_lease_lifetime)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # checkpoint resume (reference: l1_committer.rs:389 per-batch
        # checkpoints): a persistent rollup store carries the batch chain
        # and the deposit cursor across restarts, so a killed sequencer
        # continues at the right batch instead of re-committing from 1
        # the durable cursor counts only INCLUDED deposits; anything the
        # L1 reports beyond it is re-fetched as pending after a restart,
        # so an in-flight deposit is never lost (a crash between block
        # production and the meta write re-creates the privileged tx,
        # which execution then rejects on its fixed nonce = deposit index)
        self._deposit_cursor = int(self.rollup.get_meta(
            "deposit_cursor_included", 0))
        latest = self.rollup.latest_batch_number()
        self.last_batched_block = (
            self.rollup.get_batch(latest).last_block if latest else 0)
        if self.last_batched_block > self.node.store.latest_number():
            # the chain lost its unflushed tail in a crash while the
            # rollup checkpoints survived: regenerate the missing blocks
            # from the stored batch prover inputs (reference:
            # l1_committer.rs:1620 regenerate_state)
            self._regenerate_chain()
        self.pending_privileged: list[Transaction] = []
        self._lock = threading.RLock()
        self.health: dict[str, ActorHealth] = {}
        self.fatal: tuple[str, str] | None = None
        self.on_fatal = None  # callback(actor, error) for orchestrators
        # admin controls (reference: admin_server.rs — committer
        # start/stop with optional delay, sequencer stop-at-batch)
        self.paused: set[str] = set()
        self._resume_at: dict[str, float] = {}
        self.stop_at_batch: int | None = None

    def _regenerate_chain(self):
        """Re-import committed-batch blocks the chain store lost (crash
        between batch checkpoint and chain flush).  Every committed batch
        carries its full ProgramInput, so the blocks are replayed through
        normal validation and fork choice."""
        from ..blockchain.fork_choice import apply_fork_choice
        from ..guest.execution import ProgramInput

        for number in sorted(self.rollup.batches):
            batch = self.rollup.batches[number]
            if batch.last_block <= self.node.store.latest_number():
                continue
            stored = self.rollup.get_prover_input(number,
                                                  self.cfg.commit_hash)
            if stored is None:
                raise RuntimeError(
                    f"cannot regenerate batch {number}: no stored input")
            pi = ProgramInput.from_json(stored)
            tip = None
            for block in pi.blocks:
                if block.header.number <= self.node.store.latest_number():
                    continue
                self.node.chain.add_block(block)
                tip = block.hash
            if tip is not None:
                apply_fork_choice(self.node.store, tip, tip, tip)
        log.info("regenerated chain state up to block %d from rollup "
                 "checkpoints", self.node.store.latest_number())

    # ------------------------------------------------------------------
    # BlockProducer (reference: block_producer.rs produce_block)
    # ------------------------------------------------------------------
    def produce_block(self):
        from ..primitives.transaction import TYPE_PRIVILEGED

        with self._lock:
            forced = list(self.pending_privileged)
            block = self.node.produce_block(forced_txs=forced)
            included = {tx.hash for tx in block.body.transactions}
            self.pending_privileged = [
                tx for tx in self.pending_privileged
                if tx.hash not in included]
            # checkpoint the durable deposit cursor: a privileged tx's
            # nonce IS its deposit index
            done = [tx.nonce + 1 for tx in block.body.transactions
                    if tx.tx_type == TYPE_PRIVILEGED]
            if done:
                cur = int(self.rollup.get_meta(
                    "deposit_cursor_included", 0))
                if max(done) > cur:
                    self.rollup.set_meta("deposit_cursor_included",
                                         max(done))
            return block

    # ------------------------------------------------------------------
    # L1Watcher (reference: l1_watcher.rs — deposits -> privileged txs)
    # ------------------------------------------------------------------
    def watch_l1(self):
        from .l1_client import make_deposit_tx

        with self._lock:
            deposits = self.l1.get_deposits(self._deposit_cursor)
            for dep in deposits:
                tx = make_deposit_tx(self.node.config.chain_id, dep)
                self.pending_privileged.append(tx)
                self._deposit_cursor += 1

    # ------------------------------------------------------------------
    # L1Committer (reference: l1_committer.rs commit_next_batch_to_l1)
    # ------------------------------------------------------------------
    def commit_next_batch(self) -> Batch | None:
        if self.stop_at_batch is not None and \
                self.rollup.latest_batch_number() + 1 > self.stop_at_batch:
            return None    # admin stop-at: the committer idles here
        head = self.node.store.latest_number()
        first = self.last_batched_block + 1
        if head < first:
            return None
        blocks = [self.node.store.get_canonical_block(n)
                  for n in range(first, head + 1)]
        if any(b is None for b in blocks):
            return None
        number = self.rollup.latest_batch_number() + 1
        coarse_log: list = []
        batch_receipts: list = []
        witness = generate_witness(self.node.chain, blocks,
                                   write_log=coarse_log,
                                   receipts_out=batch_receipts)
        program_input = ProgramInput(blocks=blocks, witness=witness,
                                     config=self.node.config)
        state_root = blocks[-1].header.state_root
        privileged_hashes = [
            tx.hash for b in blocks for tx in b.body.transactions
            if tx.tx_type == TYPE_PRIVILEGED]
        # L2->L1 withdrawal messages (from stored receipts of these blocks)
        from .messages import collect_messages, message_root

        receipts = [self.node.store.get_receipts(b.hash) for b in blocks]
        if any(r is None for r in receipts):
            raise RuntimeError("missing receipts for a batched block")
        msgs_root = message_root(collect_messages(blocks, receipts))
        # real KZG sidecar for data availability (reference:
        # l1_committer.rs generate_blobs_bundle + blobs_bundle.rs)
        from .blobs import generate_blobs_bundle

        bundle = generate_blobs_bundle(blocks)
        commitment = keccak256(
            b"batch" + number.to_bytes(8, "big") + state_root
            + b"".join(b.hash for b in blocks)
            + b"".join(privileged_hashes) + msgs_root
            + b"".join(bundle.versioned_hashes))
        # VM-circuit coverage this batch admits (anti-downgrade metadata
        # for wire verifiers) — classified from the artifacts captured
        # during witness generation (no second execution), and derived
        # BEFORE the L1 call so a classifier error cannot break the
        # L1-first commit ordering below
        vm_mode = ""
        from ..prover import protocol as proto

        if proto.PROVER_TPU in self.cfg.needed_prover_types:
            from ..prover.tpu_backend import vm_mode_from_artifacts

            parent = self.node.store.get_header(
                blocks[0].header.parent_hash)
            vm_mode = vm_mode_from_artifacts(
                blocks, coarse_log, batch_receipts, witness,
                parent.state_root)
        # L1 first: only persist the batch once the commitment is accepted,
        # otherwise a transient L1 failure would desync the batch counter
        self.l1.commit_batch(number, state_root, commitment,
                             privileged_hashes, msgs_root)
        try:
            # publish the DA sidecar alongside the commitment (the commit
            # tx is the blob carrier; based followers re-derive the chain
            # from it — l2/based.py)
            self.l1.publish_blobs(number, bundle)
        except NotImplementedError:
            pass
        batch = Batch(number=number, first_block=first,
                      last_block=head, state_root=state_root,
                      commitment=commitment, vm_mode=vm_mode)
        self.rollup.store_batch(batch)
        self.rollup.store_blobs_bundle(number, bundle)
        self.rollup.store_prover_input(number, self.cfg.commit_hash,
                                       program_input.to_json())
        self.rollup.set_committed(number, commitment)
        self.last_batched_block = head
        from ..utils.metrics import record_batch

        record_batch(number)
        return batch

    # ------------------------------------------------------------------
    # L1ProofSender (reference: l1_proof_sender.rs — consecutive proven
    # batches -> one verifyBatches tx)
    # ------------------------------------------------------------------
    def send_proofs(self) -> tuple[int, int] | None:
        first = self.l1.last_verified_batch() + 1
        last = first - 1
        needed = list(self.cfg.needed_prover_types)

        def slot_type(n: int, t: str) -> str:
            """The prover type that actually fills type t's proof slot for
            batch n: quarantined batches settle on the coordinator's
            fallback backend (graceful degradation — see
            docs/PROVER_RESILIENCE.md)."""
            eff = self.coordinator.effective_needed_types(n, [t])
            return eff[0] if eff else t

        while self.rollup.get_batch(last + 1) is not None \
                and self.rollup.get_batch(last + 1).committed \
                and self.rollup.batch_fully_proven(
                    last + 1, [slot_type(last + 1, t) for t in needed]):
            last += 1
        if last < first:
            return None
        proofs = {}
        for t in needed:
            from ..prover.backend import get_backend

            def check(n: int) -> bool:
                backend = get_backend(slot_type(n, t))
                proof = self.rollup.get_proof(n, slot_type(n, t))
                # anti-downgrade: the committer recorded the VM-circuit
                # coverage this batch admits; a claimed-log proof for a
                # circuit-covered batch is rejected without the witness
                batch = self.rollup.get_batch(n)
                if batch is not None and not backend.check_coverage(
                        proof, batch.vm_mode):
                    return False
                # full audit when the backend supports it: the stored
                # ProverInput lets the proof's write log be replayed
                # against the witness MPT (no re-execution)
                if hasattr(backend, "verify_with_input"):
                    stored = self.rollup.get_prover_input(
                        n, self.cfg.commit_hash)
                    if stored is not None:
                        from ..guest.execution import ProgramInput

                        return backend.verify_with_input(
                            proof, ProgramInput.from_json(stored))
                return backend.verify(proof)

            results = {n: check(n) for n in range(first, last + 1)}
            if not all(results.values()):
                # invalid proof: delete so the fleet re-proves (reference:
                # distributed_proving.md:70-72)
                for n, ok in results.items():
                    if not ok:
                        self.rollup.delete_proof(n, slot_type(n, t))
                return None
            # per-batch proof bytes: the L1 checks each batch's committed
            # output (state root + messages root) against its records
            proofs[t] = [
                get_backend(slot_type(n, t)).to_proof_bytes(
                    self.rollup.get_proof(n, slot_type(n, t)))
                for n in range(first, last + 1)]
        self.l1.verify_batches(first, last, proofs)
        for n in range(first, last + 1):
            self.rollup.set_verified(n)
        return (first, last)

    # ------------------------------------------------------------------
    # StateUpdater (reference: state_updater.rs)
    # ------------------------------------------------------------------
    def update_state(self):
        committed = self.l1.last_committed_batch()
        verified = self.l1.last_verified_batch()
        for n, batch in list(self.rollup.batches.items()):
            if n <= committed and not batch.committed:
                batch.committed = True
            if n <= verified and not batch.verified:
                batch.verified = True

    # ------------------------------------------------------------------
    def start(self):
        self.coordinator.start()

        def loop(interval, fn):
            st = ActorHealth(fn.__name__)
            self.health[st.name] = st

            def run():
                while True:
                    # exponential backoff while an actor keeps failing
                    factor = min(1 << st.consecutive_failures,
                                 self.cfg.max_backoff_factor)
                    if self._stop.wait(interval * factor):
                        return
                    if st.name in self.paused or \
                            self._resume_at.get(st.name, 0) > time.time():
                        continue
                    try:
                        fn()
                        st.runs += 1
                        st.consecutive_failures = 0
                        st.last_success = time.time()
                    except Exception as e:  # noqa: BLE001 — actors survive
                        st.consecutive_failures += 1
                        st.last_error = f"{type(e).__name__}: {e}"
                        log.warning("sequencer actor %s failed (%d/%d): %s",
                                    st.name, st.consecutive_failures,
                                    self.cfg.max_actor_failures,
                                    st.last_error)
                        if st.consecutive_failures >= \
                                self.cfg.max_actor_failures:
                            # fatal subsystem: cancel the whole sequencer
                            # (reference: cancellation token -> non-zero
                            # exit, ethrex.rs:208)
                            self.fatal = (st.name, st.last_error)
                            log.error("sequencer actor %s is fatally "
                                      "failing; stopping all actors",
                                      st.name)
                            self._stop.set()
                            cb = self.on_fatal
                            if cb is not None:
                                cb(st.name, st.last_error)
                            try:
                                self.coordinator.stop()
                            except Exception:  # noqa: BLE001 — not started
                                pass
                            return
            t = threading.Thread(target=run, daemon=True)
            t.start()
            self._threads.append(t)

        intervals = {
            "produce_block": self.cfg.block_time,
            "commit_next_batch": self.cfg.commit_interval,
            "send_proofs": self.cfg.proof_send_interval,
            "watch_l1": self.cfg.watcher_interval,
            "update_state": self.cfg.watcher_interval,
        }
        for name in self.ACTOR_NAMES:
            loop(intervals[name], getattr(self, name))
        return self

    # ------------------------------------------------------------------
    # admin controls (reference: l2/sequencer/admin_server.rs)
    # ------------------------------------------------------------------
    def pause_actor(self, name: str) -> None:
        if name not in self.ACTOR_NAMES:
            raise ValueError(f"unknown actor {name!r}")
        self.paused.add(name)
        self._resume_at.pop(name, None)

    def resume_actor(self, name: str, delay: float = 0.0) -> None:
        if name not in self.ACTOR_NAMES:
            raise ValueError(f"unknown actor {name!r}")
        if delay > 0:
            self._resume_at[name] = time.time() + delay
        else:
            self._resume_at.pop(name, None)
        self.paused.discard(name)

    def stop(self):
        self._stop.set()
        self.coordinator.stop()
