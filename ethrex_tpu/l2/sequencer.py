"""L2 sequencer: the actor set from the reference's
crates/l2/sequencer/mod.rs:47 start_l2 — BlockProducer, L1Committer,
ProofCoordinator (own module), L1ProofSender, L1Watcher, StateUpdater —
re-expressed as timer-driven components over the Node + RollupStore +
L1Client.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
import types

log = logging.getLogger("ethrex_tpu.l2.sequencer")

from ..crypto.keccak import keccak256
from ..guest.execution import ProgramInput
from ..guest.witness import generate_witness
from ..node import Node
from ..primitives.transaction import TYPE_PRIVILEGED, Transaction
from ..prover import protocol
from ..utils import faults, tracing
from ..utils.metrics import observe_actor_iteration
from .eth_client import is_transient
from .l1_client import L1Client
from .leadership import FencedError, LeadershipManager
from .proof_coordinator import ProofCoordinator
from .rollup_store import Batch, RollupStore


class SettlementDivergence(RuntimeError):
    """The local settlement records and the L1 disagree about an
    already-settled batch (same number, different commitment), or a batch
    the L1 holds cannot be reproduced from the canonical chain.
    Deliberately NOT a transient error: continuing would settle the L2 on
    a fork, so the sequencer fails fast with a diagnostic instead."""


@dataclasses.dataclass
class SequencerConfig:
    block_time: float = 1.0
    commit_interval: float = 2.0
    proof_send_interval: float = 2.0
    watcher_interval: float = 1.0
    needed_prover_types: tuple = (protocol.PROVER_TPU,)
    commit_hash: str = protocol.PROTOCOL_VERSION
    # failure handling (reference: the fatal-subsystem cancellation token
    # pattern, cmd/ethrex/ethrex.rs, + per-actor health endpoints).
    # Deterministic errors (L1Error, logic bugs) burn max_actor_failures;
    # transient ones (TransportError/ConnectionError/timeouts — an L1
    # outage) get the much larger max_transient_failures budget plus
    # jittered backoff, so a flaky L1 degrades instead of killing the
    # sequencer (docs/L1_SETTLEMENT_RESILIENCE.md)
    max_actor_failures: int = 10
    max_transient_failures: int = 200
    max_backoff_factor: int = 32
    backoff_jitter: float = 0.25
    # deposits shallower than this many L1 confirmations are not ingested
    # (1 = included in any block; raise for reorg safety)
    l1_confirmation_depth: int = 1
    # prover resilience (docs/PROVER_RESILIENCE.md): assignment lease
    # length (heartbeats extend it), the hard cap on how long heartbeats
    # can keep one assignment alive (None -> coordinator default of
    # 6 leases; bounds hung provers), and how many failed assignments of
    # a batch to its primary prover type trigger the exec fallback
    prover_lease_timeout: float = 600.0
    prover_max_lease_lifetime: float | None = None
    prover_quarantine_threshold: int = 3
    # fleet scheduling (docs/AGGREGATION.md): "fleet" = size-aware
    # placement + p99 hedging + work stealing; "fcfs" pins the original
    # first-come-first-served scan
    scheduler_policy: str = "fleet"
    # recursive proof aggregation (docs/AGGREGATION.md): when enabled,
    # pending runs of >= aggregation_min_batches settle as ONE
    # aggregated proof per prover type (send_proofs defers to the
    # aggregate_proofs actor for those runs and stays the per-batch
    # fallback for everything shorter)
    aggregation_enabled: bool = False
    aggregation_interval: float = 2.0
    aggregation_min_batches: int = 2
    aggregation_max_batches: int = 16
    # sequencer HA (docs/SEQUENCER_HA.md): ha_role None keeps the
    # classic single-sequencer mode (no lease, unfenced writes).
    # "leader" and "follower" pick the starting posture of an HA pair —
    # both run the same candidacy loop against the L1 lease cell; the
    # follower just defers its first bid by one lease ttl so the
    # configured leader wins the uncontested race
    ha_role: str | None = None
    leader_lease: float = 3.0
    ha_node_id: str | None = None


@dataclasses.dataclass
class ActorHealth:
    """Per-actor failure/backoff state, exposed via ethrex_health."""

    name: str
    runs: int = 0
    consecutive_failures: int = 0        # deterministic errors
    consecutive_transient: int = 0       # transport/connection errors
    last_error: str | None = None
    last_error_class: str | None = None  # "transient" | "deterministic"
    last_success: float | None = None
    # loop-iteration latency (failed iterations count too — a slow
    # failure is still a stall)
    timed_runs: int = 0
    last_seconds: float | None = None
    total_seconds: float = 0.0
    max_seconds: float = 0.0

    @property
    def healthy(self) -> bool:
        return self.consecutive_failures == 0 \
            and self.consecutive_transient == 0

    def note_duration(self, seconds: float):
        self.timed_runs += 1
        self.last_seconds = seconds
        self.total_seconds += seconds
        if seconds > self.max_seconds:
            self.max_seconds = seconds

    def to_json(self) -> dict:
        return {
            "healthy": self.healthy,
            "runs": self.runs,
            "consecutiveFailures": self.consecutive_failures,
            "transientFailures": self.consecutive_transient,
            "lastError": self.last_error,
            "lastErrorClass": self.last_error_class,
            "lastSuccess": self.last_success,
            "loop": {
                "lastSeconds": self.last_seconds,
                "avgSeconds": (self.total_seconds / self.timed_runs
                               if self.timed_runs else None),
                "maxSeconds": self.max_seconds if self.timed_runs
                else None,
            },
        }


class Sequencer:
    """Wires all L2 actors (reference: start_l2)."""

    # the timer-driven actor set; start() loops over these names and the
    # admin pause/resume surface validates against them (keeping the RPC
    # and the loop keyed to one registry instead of magic strings)
    ACTOR_NAMES = ("produce_block", "commit_next_batch", "send_proofs",
                   "aggregate_proofs", "watch_l1", "update_state")

    def __init__(self, node: Node, l1: L1Client,
                 config: SequencerConfig | None = None,
                 rollup: RollupStore | None = None):
        self.node = node
        self.l1 = l1
        self.cfg = config or SequencerConfig()
        self.rollup = rollup if rollup is not None else RollupStore()
        self.coordinator = ProofCoordinator(
            self.rollup, needed_types=list(self.cfg.needed_prover_types),
            commit_hash=self.cfg.commit_hash,
            lease_timeout=self.cfg.prover_lease_timeout,
            quarantine_threshold=self.cfg.prover_quarantine_threshold,
            max_lease_lifetime=self.cfg.prover_max_lease_lifetime,
            scheduler_policy=self.cfg.scheduler_policy)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # checkpoint resume (reference: l1_committer.rs:389 per-batch
        # checkpoints): a persistent rollup store carries the batch chain
        # and the deposit cursor across restarts, so a killed sequencer
        # continues at the right batch instead of re-committing from 1
        # the durable cursor counts only INCLUDED deposits; anything the
        # L1 reports beyond it is re-fetched as pending after a restart,
        # so an in-flight deposit is never lost (a crash between block
        # production and the meta write re-creates the privileged tx,
        # which execution then rejects on its fixed nonce = deposit index)
        self._deposit_cursor = int(self.rollup.get_meta(
            "deposit_cursor_included", 0))
        latest = self.rollup.latest_batch_number()
        self.last_batched_block = (
            self.rollup.get_batch(latest).last_block if latest else 0)
        if self.last_batched_block > self.node.store.latest_number():
            # the chain lost its unflushed tail in a crash while the
            # rollup checkpoints survived: regenerate the missing blocks
            # from the stored batch prover inputs (reference:
            # l1_committer.rs:1620 regenerate_state)
            self._regenerate_chain()
        self.pending_privileged: list[Transaction] = []
        self._lock = threading.RLock()
        self.health: dict[str, ActorHealth] = {}
        self.fatal: tuple[str, str] | None = None
        self.on_fatal = None  # callback(actor, error) for orchestrators
        self.started_at: float | None = None  # stall-watchdog baseline
        # admin controls (reference: admin_server.rs — committer
        # start/stop with optional delay, sequencer stop-at-batch)
        self.paused: set[str] = set()
        self._resume_at: dict[str, float] = {}
        self.stop_at_batch: int | None = None
        # L1 settlement resilience (docs/L1_SETTLEMENT_RESILIENCE.md):
        # batches whose commitment an L1 reorg dropped, queued for
        # re-submission, plus the counters ethrex_health exposes
        self._settlement_lock = threading.RLock()
        self._recommit_queue: set[int] = set()
        self.reorgs_total = 0
        self.recommits_total = 0
        self.commits_adopted_total = 0
        self.rebuilt_batches_total = 0
        # the committer's last in-flight commit attempt (number, first
        # block, artifacts): when the L1 accepts a commit but the
        # acknowledgment is lost in-process, the exact artifacts that
        # were settled are still in hand — the rebuild adopts them after
        # checking them against the on-chain record instead of paying a
        # full candidate search while block production races ahead
        self._last_commit_attempt = None
        self._backoff_rng = random.Random(0)
        # startup reconciliation: close the crash window where the L1
        # accepted settlement the local store never recorded, and refuse
        # to run at all on a local/L1 divergence
        self._reconcile_with_l1()
        # the recursive-aggregation stage (docs/AGGREGATION.md) —
        # constructed after reconciliation so a crash-mid-aggregation
        # marker is classified against the L1's recovered verified tip
        from .aggregator import ProofAggregator

        self.aggregator = ProofAggregator(
            self.rollup, self.l1, coordinator=self.coordinator,
            needed_types=list(self.cfg.needed_prover_types),
            commit_hash=self.cfg.commit_hash,
            min_batches=self.cfg.aggregation_min_batches,
            max_batches=self.cfg.aggregation_max_batches,
            epoch_source=self._epoch)
        # sequencer HA (docs/SEQUENCER_HA.md): the leadership manager
        # owns the L1 lease; promotion/demotion park and unpark the
        # actor set through the admin pause surface
        self.leadership: LeadershipManager | None = None
        self.promotions_total = 0
        self.reconciled_at: float | None = time.time()
        if self.cfg.ha_role:
            if self.cfg.ha_role not in ("leader", "follower"):
                raise ValueError(
                    f"ha_role must be 'leader' or 'follower', "
                    f"got {self.cfg.ha_role!r}")
            if not self.l1.supports_leases():
                raise ValueError(
                    "sequencer HA requires an L1 client with a leader-"
                    "lease cell (this one cannot fence a deposed leader)")
            node_id = self.cfg.ha_node_id or \
                f"seq-{self.cfg.ha_role}-{id(self):x}"
            self.leadership = LeadershipManager(
                self.l1, node_id, ttl=self.cfg.leader_lease,
                on_promote=self._promote, on_demote=self._demote,
                candidacy_delay=(0.0 if self.cfg.ha_role == "leader"
                                 else self.cfg.leader_lease))
        # terminal-stop guard (idempotent drain; safe in follower mode
        # where the actor threads were never started)
        self._stopped = False
        self._stop_result = True
        self._stop_guard = threading.Lock()

    def _regenerate_chain(self):
        """Re-import committed-batch blocks the chain store lost (crash
        between batch checkpoint and chain flush).  Every committed batch
        carries its full ProgramInput, so the blocks are replayed through
        normal validation and fork choice."""
        from ..blockchain.fork_choice import apply_fork_choice
        from ..guest.execution import ProgramInput

        for number in sorted(self.rollup.batches):
            batch = self.rollup.batches[number]
            if batch.last_block <= self.node.store.latest_number():
                continue
            stored = self.rollup.get_prover_input(number,
                                                  self.cfg.commit_hash)
            if stored is None:
                raise RuntimeError(
                    f"cannot regenerate batch {number}: no stored input")
            pi = ProgramInput.from_json(stored)
            tip = None
            for block in pi.blocks:
                if block.header.number <= self.node.store.latest_number():
                    continue
                self.node.chain.add_block(block)
                tip = block.hash
            if tip is not None:
                apply_fork_choice(self.node.store, tip, tip, tip)
        log.info("regenerated chain state up to block %d from rollup "
                 "checkpoints", self.node.store.latest_number())

    # ------------------------------------------------------------------
    # startup reconciliation (reference: state_updater.rs settlement
    # reconciliation + l1_committer.rs ensure_checkpoint_for_committed_batch)
    # ------------------------------------------------------------------
    def _reconcile_with_l1(self) -> None:
        """Compare local settlement records against the L1 at boot.

        Three outcomes per batch: (a) L1 is ahead of the local store —
        the commit-crash window; the missing batch record is rebuilt from
        the canonical chain and adopted, instead of re-committing into a
        permanent "out of order" fatal loop.  (b) Local flags lag the L1
        (crash between commit/verify and the flag write) — adopted
        through the store setters.  (c) The two records DIVERGE for the
        same batch number — SettlementDivergence, fail fast."""
        try:
            l1_committed = self.l1.last_committed_batch()
            l1_verified = self.l1.last_verified_batch()
        except NotImplementedError:
            return
        except Exception as e:  # noqa: BLE001 — classify before giving up
            if is_transient(e):
                # L1 unreachable at boot: run anyway; the update_state
                # actor reconciles as soon as it answers again
                log.warning("L1 unreachable during startup "
                            "reconciliation (%s); continuing", e)
                return
            raise
        local = self.rollup.latest_batch_number()
        for n in range(1, min(local, l1_committed) + 1):
            batch = self.rollup.get_batch(n)
            if batch is None or not batch.commitment:
                continue
            onchain = self.l1.get_committed_commitment(n)
            if onchain is not None and onchain != batch.commitment:
                raise SettlementDivergence(
                    f"batch {n}: local commitment "
                    f"{batch.commitment.hex()[:16]} != L1 commitment "
                    f"{onchain.hex()[:16]} — the rollup store and the "
                    f"settlement contract describe different chains; "
                    f"refusing to settle on a fork")
        for n in range(local + 1, l1_committed + 1):
            self._rebuild_batch_from_l1(n)
        for n in range(1, l1_committed + 1):
            self._repair_partial_batch(n)
        for n in sorted(self.rollup.batches):
            b = self.rollup.get_batch(n)
            if n <= l1_committed and not b.committed:
                self.rollup.set_settlement(n, committed=True)
            if n <= l1_verified and not b.verified:
                self.rollup.set_settlement(n, verified=True)

    def _repair_partial_batch(self, number: int) -> None:
        """A narrower crash window: the batch record survived but the
        crash lost its prover input and/or DA bundle (the writes after
        store_batch).  Both are deterministic functions of the canonical
        blocks, so they are recomputed — guarded by the commitment, which
        must reproduce exactly."""
        batch = self.rollup.get_batch(number)
        if batch is None:
            return
        missing_input = self.rollup.get_prover_input(
            number, self.cfg.commit_hash) is None
        missing_blobs = self.rollup.get_blobs_bundle(number) is None
        if not missing_input and not missing_blobs:
            return
        art = self._build_batch_artifacts(number, batch.first_block,
                                          batch.last_block)
        if art is None or (batch.commitment
                           and art.commitment != batch.commitment):
            raise SettlementDivergence(
                f"batch {number} record is missing its "
                f"{'prover input' if missing_input else 'DA bundle'} and "
                f"the canonical chain no longer reproduces its commitment")
        if missing_blobs:
            self.rollup.store_blobs_bundle(number, art.bundle)
        if missing_input:
            self.rollup.store_prover_input(number, self.cfg.commit_hash,
                                           art.program_input.to_json())
        self.rebuilt_batches_total += 1
        log.warning("repaired partial record of batch %d (rebuilt %s)",
                    number,
                    "input+blobs" if missing_input and missing_blobs
                    else "input" if missing_input else "blobs")

    def _rebuild_batch_from_l1(self, number: int) -> None:
        """The verified crash window in commit_next_batch: the L1
        accepted batch `number`, the process died before the rollup store
        heard about it.  The blocks are still canonical, so the whole
        batch record (witness, prover input, DA bundle, commitment) is
        recomputed and checked against what the L1 actually settled."""
        first = self.last_batched_block + 1
        head = self.node.store.latest_number()
        onchain_root = self.l1.get_committed_state_root(number)
        onchain_commitment = self.l1.get_committed_commitment(number)
        if onchain_root is None and onchain_commitment is None:
            raise SettlementDivergence(
                f"L1 has batch {number} committed but exposes neither its "
                f"state root nor its commitment; cannot rebuild the lost "
                f"batch record")
        art = None
        # fast path: the lost acknowledgment happened in THIS process, so
        # the artifacts the L1 just accepted are the committer's last
        # attempt — adopt them if the on-chain record confirms the match
        # (a full candidate search below stays for genuine restarts,
        # where production is not racing the rebuild)
        cached = self._last_commit_attempt
        if (cached is not None and cached[0] == number
                and cached[1] == first
                and (onchain_commitment is None
                     or cached[2].commitment == onchain_commitment)
                and (onchain_root is None
                     or cached[2].state_root == onchain_root)):
            art = cached[2]
        if art is None and onchain_root is not None:
            candidates = [
                b for b in range(first, head + 1)
                if (blk := self.node.store.get_canonical_block(b))
                is not None and blk.header.state_root == onchain_root]
        elif art is None:
            candidates = list(range(first, head + 1))
        else:
            candidates = []
        for last in candidates:
            cand = self._build_batch_artifacts(number, first, last)
            if cand is None:
                continue
            if onchain_commitment is not None \
                    and cand.commitment != onchain_commitment:
                continue
            art = cand
            break
        if art is None:
            raise SettlementDivergence(
                f"L1 has batch {number} committed but no canonical block "
                f"range [{first}..{head}] reproduces it — the chain store "
                f"and the L1 describe different chains (or the chain tail "
                f"was lost beyond recovery)")
        last_block = art.blocks[-1].header.number
        batch = Batch(number=number, first_block=first,
                      last_block=last_block, state_root=art.state_root,
                      commitment=art.commitment, vm_mode=art.vm_mode)
        with self.rollup.write_group(epoch=self._epoch()):
            self.rollup.store_batch(batch)
            self.rollup.store_blobs_bundle(number, art.bundle)
            self.rollup.store_prover_input(number, self.cfg.commit_hash,
                                           art.program_input.to_json())
            self.rollup.set_committed(number, art.commitment)
        self.last_batched_block = last_block
        self.rebuilt_batches_total += 1
        log.warning("rebuilt batch %d (blocks %d..%d) from the canonical "
                    "chain after a commit-crash window", number, first,
                    last_block)

    # ------------------------------------------------------------------
    # sequencer HA: fencing + promotion/demotion (docs/SEQUENCER_HA.md)
    # ------------------------------------------------------------------
    def _epoch(self) -> int | None:
        """The fencing token stamped on externally-visible writes;
        None in single-sequencer (non-HA) mode."""
        leadership = getattr(self, "leadership", None)
        return leadership.epoch if leadership is not None else None

    def _fence(self) -> int | None:
        """Fence checkpoint before an externally-visible write: raises
        FencedError unless this node currently holds the lease (no-op
        without HA).  The returned epoch is captured ONCE per operation
        and stamped on every leg — if the lease moves mid-operation the
        sinks reject the stale token."""
        leadership = getattr(self, "leadership", None)
        if leadership is None:
            faults.inject("seq.fence")
            return None
        return leadership.check()

    def _promote(self):
        """Promotion IS the crash-recovery startup path (Crash-Only
        Software, PAPERS.md): fence the store at the new epoch, refresh
        the committer position from the durable checkpoints the follower
        accumulated while chain-following, run the PR-2 reconciliation
        (journal replay already happened when the store opened), restart
        the proof coordinator so the prover fleet re-homes here, then
        unpark the actors.  At most one uncommitted batch is re-derived
        — everything settled is adopted, never re-committed."""
        epoch = self.leadership.epoch
        if epoch is None:
            raise FencedError("promotion without a lease epoch")
        self.rollup.fence(epoch)
        # the follower's chain advanced via the block fetcher while the
        # actors were parked: recompute the batch cursor before actors
        # resume, or the committer would span an already-settled range
        latest = self.rollup.latest_batch_number()
        self.last_batched_block = (
            self.rollup.get_batch(latest).last_block if latest else 0)
        if self.last_batched_block > self.node.store.latest_number():
            self._regenerate_chain()
        self._deposit_cursor = int(self.rollup.get_meta(
            "deposit_cursor_included", 0))
        self._last_commit_attempt = None
        with self._settlement_lock:
            self._recommit_queue.clear()
        self._reconcile_with_l1()
        self.reconciled_at = time.time()
        # re-home the prover fleet: the coordinator serves assignments
        # from this node now; prover leases and phase checkpoints
        # survive the move (docs/PROVER_RESILIENCE.md), so in-flight
        # proofs resume instead of restarting
        self.coordinator.start()
        for name in self.ACTOR_NAMES:
            self.resume_actor(name)
        self.promotions_total += 1
        log.info("promoted to leader at epoch %d", epoch)

    def _demote(self):
        """Deposed (fenced write, renewal starvation, or clean step-
        down): park every actor and stop serving the prover fleet.  The
        process stays alive as a hot standby — caches warm, chain
        following — and re-enters candidacy through the leadership
        loop."""
        for name in self.ACTOR_NAMES:
            self.pause_actor(name)
        try:
            self.coordinator.stop(timeout=2.0)
        except Exception:  # noqa: BLE001 — may never have started
            pass
        log.warning("demoted to follower; actors parked")

    # ------------------------------------------------------------------
    # BlockProducer (reference: block_producer.rs produce_block)
    # ------------------------------------------------------------------
    def produce_block(self):
        from ..primitives.transaction import TYPE_PRIVILEGED

        with self._lock:
            forced = list(self.pending_privileged)
            block = self.node.produce_block(forced_txs=forced)
            included = {tx.hash for tx in block.body.transactions}
            self.pending_privileged = [
                tx for tx in self.pending_privileged
                if tx.hash not in included]
            # checkpoint the durable deposit cursor: a privileged tx's
            # nonce IS its deposit index
            done = [tx.nonce + 1 for tx in block.body.transactions
                    if tx.tx_type == TYPE_PRIVILEGED]
            if done:
                cur = int(self.rollup.get_meta(
                    "deposit_cursor_included", 0))
                if max(done) > cur:
                    self.rollup.set_meta("deposit_cursor_included",
                                         max(done))
            return block

    # ------------------------------------------------------------------
    # L1Watcher (reference: l1_watcher.rs — deposits -> privileged txs)
    # ------------------------------------------------------------------
    def watch_l1(self):
        from .l1_client import make_deposit_tx

        with self._lock:
            faults.inject("l1.get_deposits")
            deposits = self.l1.get_deposits(self._deposit_cursor)
            depth = self.cfg.l1_confirmation_depth
            head = None
            if depth > 1:
                try:
                    head = self.l1.get_block_number()
                except NotImplementedError:
                    head = None  # L1 without a block surface: ingest all
            for dep in deposits:
                if head is not None and dep.l1_block:
                    if head - dep.l1_block + 1 < depth:
                        # too shallow — a reorg could still drop it; later
                        # deposits are younger still, so stop here to keep
                        # the cursor contiguous
                        break
                tx = make_deposit_tx(self.node.config.chain_id, dep)
                self.pending_privileged.append(tx)
                self._deposit_cursor += 1

    # ------------------------------------------------------------------
    # L1Committer (reference: l1_committer.rs commit_next_batch_to_l1)
    # ------------------------------------------------------------------
    def _build_batch_artifacts(self, number: int, first: int,
                               last: int) -> types.SimpleNamespace | None:
        """Deterministically recompute everything batch `number` over
        blocks [first, last] carries: witness, prover input, DA bundle,
        commitment, vm mode.  Shared by the committer and startup
        reconciliation — the same block range always reproduces the same
        commitment, which is what makes commits idempotent and lost batch
        records rebuildable."""
        blocks = [self.node.store.get_canonical_block(n)
                  for n in range(first, last + 1)]
        if not blocks or any(b is None for b in blocks):
            return None
        coarse_log: list = []
        batch_receipts: list = []
        witness = generate_witness(self.node.chain, blocks,
                                   write_log=coarse_log,
                                   receipts_out=batch_receipts)
        program_input = ProgramInput(blocks=blocks, witness=witness,
                                     config=self.node.config)
        state_root = blocks[-1].header.state_root
        privileged_hashes = [
            tx.hash for b in blocks for tx in b.body.transactions
            if tx.tx_type == TYPE_PRIVILEGED]
        # L2->L1 withdrawal messages (from stored receipts of these blocks)
        from .messages import collect_messages, message_root

        receipts = [self.node.store.get_receipts(b.hash) for b in blocks]
        if any(r is None for r in receipts):
            raise RuntimeError("missing receipts for a batched block")
        msgs_root = message_root(collect_messages(blocks, receipts))
        # real KZG sidecar for data availability (reference:
        # l1_committer.rs generate_blobs_bundle + blobs_bundle.rs)
        from .blobs import generate_blobs_bundle

        bundle = generate_blobs_bundle(blocks)
        commitment = keccak256(
            b"batch" + number.to_bytes(8, "big") + state_root
            + b"".join(b.hash for b in blocks)
            + b"".join(privileged_hashes) + msgs_root
            + b"".join(bundle.versioned_hashes))
        # VM-circuit coverage this batch admits (anti-downgrade metadata
        # for wire verifiers) — classified from the artifacts captured
        # during witness generation (no second execution), and derived
        # BEFORE the L1 call so a classifier error cannot break the
        # L1-first commit ordering
        vm_mode = ""
        from ..prover import protocol as proto

        if proto.PROVER_TPU in self.cfg.needed_prover_types:
            from ..prover.tpu_backend import vm_mode_from_artifacts

            parent = self.node.store.get_header(
                blocks[0].header.parent_hash)
            vm_mode = vm_mode_from_artifacts(
                blocks, coarse_log, batch_receipts, witness,
                parent.state_root)
        return types.SimpleNamespace(
            blocks=blocks, program_input=program_input,
            state_root=state_root, privileged_hashes=privileged_hashes,
            msgs_root=msgs_root, bundle=bundle, commitment=commitment,
            vm_mode=vm_mode)

    def _settle_commit(self, number: int, commitment: bytes,
                       state_root: bytes, privileged_hashes: list,
                       msgs_root: bytes, bundle,
                       epoch: int | None = None) -> None:
        """Idempotent L1 commit: if the L1 already holds batch `number`
        with OUR commitment (a retry after the commit tx landed but the
        acknowledgment was lost), adopt it as success; a different
        commitment is a divergence and fails fast.  The l1.commit fault
        site fires on both legs — before the call (request lost) and
        after it returns (response lost).  `epoch` is the caller's
        fencing token (sequencer HA): the L1 rejects it when stale, so
        a deposed leader's delayed commit can never land."""
        faults.inject("l1.commit")
        if self.l1.last_committed_batch() >= number:
            onchain = self.l1.get_committed_commitment(number)
            if onchain != commitment:
                raise SettlementDivergence(
                    f"batch {number} already settled on L1 with a "
                    f"different commitment "
                    f"(l1={onchain.hex()[:16] if onchain else None} "
                    f"local={commitment.hex()[:16]}); refusing to settle "
                    f"on a fork")
            with self._settlement_lock:
                self.commits_adopted_total += 1
            from ..utils.metrics import record_commit_adopted

            record_commit_adopted()
            log.warning("batch %d already committed on L1 with a matching "
                        "commitment; adopting it as success", number)
        else:
            self.l1.commit_batch(number, state_root, commitment,
                                 privileged_hashes, msgs_root,
                                 epoch=epoch)
            faults.inject("l1.commit")
        try:
            # publish the DA sidecar alongside the commitment (the commit
            # tx is the blob carrier; based followers re-derive the chain
            # from it — l2/based.py); on the adopt path re-publish only
            # if the first attempt died before the sidecar went out
            if self.l1.get_blob_sidecar(number) is None:
                self.l1.publish_blobs(number, bundle)
        except NotImplementedError:
            pass

    def commit_next_batch(self) -> Batch | None:
        # the fencing token for this WHOLE commit is captured once, up
        # front: if leadership moves mid-commit, the L1 and the store
        # reject the stale token on their own legs (zombie protection)
        epoch = self._fence()
        with self._settlement_lock:
            if self._recommit_queue:
                # reorged-out commitments take priority over new batches
                return self._recommit_batch(min(self._recommit_queue))
        number = self.rollup.latest_batch_number() + 1
        if self.stop_at_batch is not None and number > self.stop_at_batch:
            return None    # admin stop-at: the committer idles here
        if self.l1.last_committed_batch() >= number:
            # the L1 already holds the batch we are about to build: a
            # commit succeeded but its acknowledgment was lost before any
            # local persistence.  Building a fresh batch now would span a
            # WIDER block range (production kept going) and diverge —
            # re-derive the settled record from the L1 instead, exactly
            # like startup reconciliation
            self._rebuild_batch_from_l1(number)
            with self._settlement_lock:
                self.commits_adopted_total += 1
            from ..utils.metrics import record_batch, record_commit_adopted

            record_commit_adopted()
            record_batch(number)
            return self.rollup.get_batch(number)
        head = self.node.store.latest_number()
        first = self.last_batched_block + 1
        if head < first:
            return None
        art = self._build_batch_artifacts(number, first, head)
        if art is None:
            return None
        # L1 first: only persist the batch once the commitment is accepted,
        # otherwise a transient L1 failure would desync the batch counter.
        # Remember the attempt first: if the L1 accepts it but the
        # acknowledgment is lost, the rebuild adopts these artifacts
        # instead of re-deriving the settled range from scratch
        self._last_commit_attempt = (number, first, art)
        self._settle_commit(number, art.commitment, art.state_root,
                            art.privileged_hashes, art.msgs_root,
                            art.bundle, epoch=epoch)
        batch = Batch(number=number, first_block=first,
                      last_block=head, state_root=art.state_root,
                      commitment=art.commitment, vm_mode=art.vm_mode)
        # the local batch record is one journaled unit: a crash between
        # these writes reopens to either the full record or none (and the
        # none case is exactly the commit-crash window reconciliation
        # already rebuilds from L1); the group carries the same fencing
        # token as the L1 leg, so a leader deposed inside the commit
        # crash-window cannot write a record the new leader won't own
        with self.rollup.write_group(epoch=epoch):
            self.rollup.store_batch(batch)
            self.rollup.store_blobs_bundle(number, art.bundle)
            self.rollup.store_prover_input(number, self.cfg.commit_hash,
                                           art.program_input.to_json())
            self.rollup.set_committed(number, art.commitment)
        self.last_batched_block = head
        from ..utils.metrics import record_batch

        record_batch(number)
        # chain-path X-ray: the sealed blocks leave the batching stage;
        # sampled lifecycles get their batched mark and join the PR-15
        # batch trace by trace ID.  Telemetry — never fails the commit.
        try:
            from ..perf.chain_path import CHAIN_PATH

            CHAIN_PATH.blocks_batched(
                number, first, head,
                trace_id=self.coordinator.trace_for_batch(number))
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        return batch

    def _recommit_batch(self, number: int) -> Batch | None:
        """Re-submit a batch whose L1 commitment a reorg dropped.  The
        stored record is re-committed VERBATIM (same commitment), so the
        stored proofs stay valid and send_proofs can re-verify without
        re-proving."""
        batch = self.rollup.get_batch(number)
        if batch is None:
            self._recommit_queue.discard(number)
            return None
        bundle = self.rollup.get_blobs_bundle(number)
        blocks = [self.node.store.get_canonical_block(n)
                  for n in range(batch.first_block, batch.last_block + 1)]
        if bundle is None or any(b is None for b in blocks):
            # unusable record (partial persistence + reorg): drop it and
            # every batch above, rewind, and re-batch from scratch
            self._drop_batches_from(number)
            return None
        privileged_hashes = [
            tx.hash for b in blocks for tx in b.body.transactions
            if tx.tx_type == TYPE_PRIVILEGED]
        from .messages import collect_messages, message_root

        receipts = [self.node.store.get_receipts(b.hash) for b in blocks]
        if any(r is None for r in receipts):
            self._drop_batches_from(number)
            return None
        msgs_root = message_root(collect_messages(blocks, receipts))
        self._settle_commit(number, batch.commitment, batch.state_root,
                            privileged_hashes, msgs_root, bundle,
                            epoch=self._epoch())
        self.rollup.set_settlement(number, committed=True)
        with self._settlement_lock:
            self._recommit_queue.discard(number)
            self.recommits_total += 1
        from ..utils.metrics import record_recommit

        record_recommit()
        log.info("re-committed batch %d after an L1 reorg", number)
        return batch

    def _drop_batches_from(self, number: int) -> None:
        """Reorg last resort: delete batch records from `number` up and
        rewind last_batched_block so the normal committer re-batches the
        (still canonical) blocks from scratch."""
        with self._settlement_lock:
            latest = self.rollup.latest_batch_number()
            for n in range(number, latest + 1):
                self.rollup.delete_batch(n)
                self._recommit_queue.discard(n)
            prev = self.rollup.get_batch(number - 1)
            self.last_batched_block = prev.last_block if prev else 0
            log.warning("dropped unusable batch records %d..%d after an "
                        "L1 reorg; rewound last_batched_block to %d",
                        number, latest, self.last_batched_block)

    # ------------------------------------------------------------------
    # L1ProofSender (reference: l1_proof_sender.rs — consecutive proven
    # batches -> one verifyBatches tx)
    # ------------------------------------------------------------------
    def send_proofs(self) -> tuple[int, int] | None:
        first = self.l1.last_verified_batch() + 1
        last = first - 1
        needed = list(self.cfg.needed_prover_types)

        def slot_type(n: int, t: str) -> str:
            """The prover type that actually fills type t's proof slot for
            batch n: quarantined batches settle on the coordinator's
            fallback backend (graceful degradation — see
            docs/PROVER_RESILIENCE.md)."""
            eff = self.coordinator.effective_needed_types(n, [t])
            return eff[0] if eff else t

        while self.rollup.get_batch(last + 1) is not None \
                and self.rollup.get_batch(last + 1).committed \
                and self.rollup.batch_fully_proven(
                    last + 1, [slot_type(last + 1, t) for t in needed]):
            last += 1
        if last < first:
            return None
        if self.cfg.aggregation_enabled \
                and last - first + 1 >= self.cfg.aggregation_min_batches:
            # long enough for the recursion stage: defer to the
            # aggregate_proofs actor (N proofs -> one L1 tx); runs
            # shorter than aggregation_min_batches still settle here
            # per-batch, which also keeps settlement moving if the
            # aggregator keeps failing (its audit deletes bad proofs,
            # shrinking the run below the threshold)
            return None
        proofs = {}
        for t in needed:
            from ..prover.backend import get_backend

            def check(n: int) -> bool:
                backend = get_backend(slot_type(n, t))
                proof = self.rollup.get_proof(n, slot_type(n, t))
                # anti-downgrade: the committer recorded the VM-circuit
                # coverage this batch admits; a claimed-log proof for a
                # circuit-covered batch is rejected without the witness
                batch = self.rollup.get_batch(n)
                if batch is not None and not backend.check_coverage(
                        proof, batch.vm_mode):
                    return False
                # full audit when the backend supports it: the stored
                # ProverInput lets the proof's write log be replayed
                # against the witness MPT (no re-execution)
                if hasattr(backend, "verify_with_input"):
                    stored = self.rollup.get_prover_input(
                        n, self.cfg.commit_hash)
                    if stored is not None:
                        from ..guest.execution import ProgramInput

                        return backend.verify_with_input(
                            proof, ProgramInput.from_json(stored))
                return backend.verify(proof)

            results = {}
            for n in range(first, last + 1):
                # join the batch's proving trace (opened at assignment)
                # so verification shows up in the same lifecycle trace
                with tracing.trace_context(
                        self.coordinator.batch_traces.get(n)):
                    with tracing.span("proof.verify", batch=n,
                                      prover_type=slot_type(n, t)):
                        results[n] = check(n)
            if not all(results.values()):
                # invalid proof: delete so the fleet re-proves (reference:
                # distributed_proving.md:70-72)
                for n, ok in results.items():
                    if not ok:
                        self.rollup.delete_proof(n, slot_type(n, t))
                return None
            # per-batch proof bytes: the L1 checks each batch's committed
            # output (state root + messages root) against its records
            proofs[t] = [
                get_backend(slot_type(n, t)).to_proof_bytes(
                    self.rollup.get_proof(n, slot_type(n, t)))
                for n in range(first, last + 1)]
        epoch = self._fence()
        faults.inject("l1.verify")
        self.l1.verify_batches(first, last, proofs, epoch=epoch)
        faults.inject("l1.verify")
        for n in range(first, last + 1):
            with tracing.trace_context(
                    self.coordinator.batch_traces.get(n)):
                with tracing.span("proof.settle", batch=n):
                    self.rollup.set_verified(n)
        from ..utils.metrics import record_verified_batch

        record_verified_batch(last)
        try:
            from ..perf.chain_path import CHAIN_PATH

            CHAIN_PATH.batches_settled(first, last)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        self._record_lifecycles(first, last)
        return (first, last)

    def _record_lifecycles(self, first: int, last: int) -> None:
        """Post-settlement critical-path attribution: walk each settled
        batch's merged lifecycle trace, feed the
        batch_critical_path_seconds{component} histogram (exemplared
        with the trace ID) and the coordinator's lifecycle timeline.
        Telemetry — never raises into settlement."""
        from ..utils.metrics import observe_critical_path

        try:
            for n in range(first, last + 1):
                tid = self.coordinator.batch_traces.get(n)
                if tid is None:
                    continue
                cp = tracing.critical_path(tracing.TRACER.get_trace(tid))
                if not cp.get("spanCount"):
                    continue
                for component, secs in cp.get("components", {}).items():
                    observe_critical_path(component, secs, trace_id=tid)
                self.coordinator.note_lifecycle(n, {
                    "batch": n,
                    "traceId": tid,
                    "wallSeconds": round(cp.get("wallSeconds") or 0.0, 6),
                    "spanCount": cp.get("spanCount"),
                    "partial": cp.get("partial"),
                    "sources": cp.get("sources"),
                    "components": {k: round(v, 6) for k, v in
                                   cp.get("components", {}).items()},
                })
        except Exception:  # noqa: BLE001 — settlement already succeeded
            log.exception("critical-path attribution failed")

    # ------------------------------------------------------------------
    # ProofAggregator actor (docs/AGGREGATION.md)
    # ------------------------------------------------------------------
    def aggregate_proofs(self) -> tuple[int, int] | None:
        """Settle the next pending run as one aggregated proof; a no-op
        until aggregation is enabled and the run reaches
        aggregation_min_batches (send_proofs remains the fallback)."""
        if not self.cfg.aggregation_enabled:
            return None
        settled = self.aggregator.step()
        if settled is not None:
            # aggregated runs get the same per-batch lifecycle
            # attribution as the per-batch settlement path
            self._record_lifecycles(*settled)
        return settled

    # ------------------------------------------------------------------
    # StateUpdater (reference: state_updater.rs)
    # ------------------------------------------------------------------
    def update_state(self):
        """Reconcile local settlement flags with the L1 — in BOTH
        directions.  Forward: adopt flags the L1 advanced past us (e.g.
        another tooling path verified batches).  Backward: an L1 reorg
        that regressed last_committed/verified drops the affected flags
        through the write-through setters and queues the batches for
        re-commit, so the committer re-settles them verbatim."""
        # fence before touching settlement flags: a deposed leader's
        # state updater must not adopt/rollback flags the new leader owns
        self._fence()
        committed = self.l1.last_committed_batch()
        verified = self.l1.last_verified_batch()
        with self._settlement_lock:
            reorged = False
            for n in sorted(self.rollup.batches, reverse=True):
                batch = self.rollup.get_batch(n)
                if n > committed and batch.committed:
                    # settlement regression: the commit tx reorged out
                    self.rollup.set_settlement(n, committed=False,
                                               verified=False)
                    self._recommit_queue.add(n)
                    reorged = True
                    log.warning("L1 reorg dropped the commitment of batch "
                                "%d; queued for re-commit", n)
            for n in sorted(self.rollup.batches):
                batch = self.rollup.get_batch(n)
                if n <= committed and not batch.committed:
                    onchain = self.l1.get_committed_commitment(n)
                    if onchain is not None and batch.commitment \
                            and onchain != batch.commitment:
                        raise SettlementDivergence(
                            f"batch {n} settled on L1 with a different "
                            f"commitment (l1={onchain.hex()[:16]} "
                            f"local={batch.commitment.hex()[:16]})")
                    self.rollup.set_settlement(n, committed=True)
                if n <= verified and not batch.verified:
                    self.rollup.set_settlement(n, verified=True)
                if n > verified and batch.verified:
                    # the verify tx reorged out (commit may have
                    # survived); send_proofs re-verifies from stored
                    # proofs
                    self.rollup.set_settlement(n, verified=False)
                    reorged = True
                    log.warning("L1 reorg dropped the verification of "
                                "batch %d; will re-verify", n)
            if reorged:
                self.reorgs_total += 1
                from ..utils.metrics import record_l1_reorg

                record_l1_reorg()
        from ..utils.metrics import record_verified_batch

        record_verified_batch(verified)

    # ------------------------------------------------------------------
    def start(self):
        if self.leadership is None:
            self.coordinator.start()
        else:
            # HA mode: actor threads spin up PARKED (follower posture);
            # the coordinator stays down so this node's rollup view
            # cannot hand the prover fleet duplicate work.  Promotion —
            # driven by the leadership manager winning the lease —
            # starts the coordinator and unparks the actors
            for name in self.ACTOR_NAMES:
                self.pause_actor(name)
        self.started_at = time.time()

        def loop(interval, fn):
            st = ActorHealth(fn.__name__)
            self.health[st.name] = st

            def run():
                while True:
                    # exponential backoff while an actor keeps failing —
                    # jittered so a fleet of actors hammered by the same
                    # L1 outage doesn't retry in lockstep
                    steps = min(st.consecutive_failures
                                + st.consecutive_transient, 16)
                    factor = min(1 << steps, self.cfg.max_backoff_factor)
                    delay = interval * factor
                    if factor > 1:
                        delay *= 1 + self._backoff_rng.random() \
                            * self.cfg.backoff_jitter
                    if self._stop.wait(delay):
                        return
                    if st.name in self.paused or \
                            self._resume_at.get(st.name, 0) > time.time():
                        continue
                    t0 = time.perf_counter()
                    try:
                        fn()
                        st.runs += 1
                        st.consecutive_failures = 0
                        st.consecutive_transient = 0
                        st.last_success = time.time()
                    except FencedError as e:
                        # deposed, not failing: a sink refused our stale
                        # epoch.  Demote (park all actors, re-enter
                        # candidacy) without burning any failure budget —
                        # the new leader owns the pipeline now
                        st.last_error = f"FencedError: {e}"
                        st.last_error_class = "fenced"
                        log.warning("sequencer actor %s fenced (deposed "
                                    "leader): %s", st.name, e)
                        if self.leadership is not None:
                            self.leadership.fenced(e)
                    except Exception as e:  # noqa: BLE001 — actors survive
                        # error classification: transient faults (network
                        # flakes, injected drops — an L1 outage) get a far
                        # larger failure budget than deterministic errors,
                        # so an outage degrades instead of killing the
                        # sequencer
                        transient = is_transient(e)
                        if transient:
                            st.consecutive_transient += 1
                            st.last_error_class = "transient"
                            count = st.consecutive_transient
                            budget = self.cfg.max_transient_failures
                            from ..utils.metrics import \
                                record_transient_error

                            record_transient_error()
                        else:
                            st.consecutive_failures += 1
                            st.last_error_class = "deterministic"
                            count = st.consecutive_failures
                            budget = self.cfg.max_actor_failures
                        st.last_error = f"{type(e).__name__}: {e}"
                        log.warning("sequencer actor %s failed "
                                    "[%s %d/%d]: %s",
                                    st.name, st.last_error_class,
                                    count, budget, st.last_error)
                        if count >= budget:
                            # fatal subsystem: cancel the whole sequencer
                            # (reference: cancellation token -> non-zero
                            # exit, ethrex.rs:208)
                            self.fatal = (st.name, st.last_error)
                            log.error("sequencer actor %s is fatally "
                                      "failing; stopping all actors",
                                      st.name)
                            self._stop.set()
                            cb = self.on_fatal
                            if cb is not None:
                                cb(st.name, st.last_error)
                            # flight recorder: capture the dying state
                            # (no-op unless --debug-snapshot-dir is set;
                            # must never raise in the actor loop)
                            try:
                                from ..utils import snapshot as _snapshot

                                _snapshot.on_fatal(st.name, st.last_error,
                                                   node=self.node)
                            except Exception:
                                pass
                            try:
                                self.coordinator.stop()
                            except Exception:  # noqa: BLE001 — not started
                                pass
                            return
                    finally:
                        dt = time.perf_counter() - t0
                        st.note_duration(dt)
                        observe_actor_iteration(st.name, dt)
            t = threading.Thread(target=run, daemon=True)
            t.start()
            self._threads.append(t)

        intervals = {
            "produce_block": self.cfg.block_time,
            "commit_next_batch": self.cfg.commit_interval,
            "send_proofs": self.cfg.proof_send_interval,
            "aggregate_proofs": self.cfg.aggregation_interval,
            "watch_l1": self.cfg.watcher_interval,
            "update_state": self.cfg.watcher_interval,
        }
        for name in self.ACTOR_NAMES:
            loop(intervals[name], getattr(self, name))
        if self.leadership is not None:
            self.leadership.start()
        return self

    # ------------------------------------------------------------------
    # admin controls (reference: l2/sequencer/admin_server.rs)
    # ------------------------------------------------------------------
    def pause_actor(self, name: str) -> None:
        if name not in self.ACTOR_NAMES:
            raise ValueError(f"unknown actor {name!r}")
        self.paused.add(name)
        self._resume_at.pop(name, None)

    def resume_actor(self, name: str, delay: float = 0.0) -> None:
        if name not in self.ACTOR_NAMES:
            raise ValueError(f"unknown actor {name!r}")
        if delay > 0:
            self._resume_at[name] = time.time() + delay
        else:
            self._resume_at.pop(name, None)
        self.paused.discard(name)

    def ready_json(self) -> dict:
        """The ethrex_ready payload: role + gated-on-reconciliation
        readiness, distinct from ethrex_health's liveness.  A follower
        is alive but NOT ready for leader traffic; a promoting node
        turns ready only once reconciliation finished and its actors
        unparked (docs/SEQUENCER_HA.md)."""
        if self.leadership is None:
            return {"ready": self.fatal is None, "role": "leader",
                    "ha": False, "reconciledAt": self.reconciled_at,
                    "promotions": self.promotions_total}
        status = self.leadership.status()
        return {
            "ready": (status["role"] == "leader" and self.fatal is None
                      and self.reconciled_at is not None),
            "role": status["role"],
            "ha": True,
            "reconciledAt": self.reconciled_at,
            "promotions": self.promotions_total,
            "leadership": status,
        }

    def stop(self, timeout: float = 10.0) -> bool:
        """Drain: release the leadership lease (so a standby can win
        immediately instead of waiting out the ttl), signal every actor
        loop, join the actor threads (each finishes its in-flight
        iteration — a mid-commit batch lands or rolls back through its
        write group), then stop the coordinator, which waits for
        in-flight proof submits to land.  Returns True when every actor
        stopped within the deadline.

        Idempotent and follower-safe: repeated invocations (demote →
        shutdown races, the shutdown manager re-running a drain) return
        the first drain's result without re-joining anything, and a
        follower whose actor threads never started drains cleanly."""
        with self._stop_guard:
            if self._stopped:
                return self._stop_result
            self._stopped = True
        if self.leadership is not None:
            self.leadership.stop()
        self._stop.set()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        stragglers = [t for t in self._threads if t.is_alive()]
        if stragglers:
            log.warning("%d sequencer actor(s) still running after %.1fs "
                        "drain deadline", len(stragglers), timeout)
        self.coordinator.stop(
            timeout=max(0.5, deadline - time.monotonic()))
        self._stop_result = not stragglers
        return self._stop_result
