"""L2 batch blobs: EIP-4844 sidecar generation + state reconstruction.

Parity: the reference committer packs the batch payload into blobs and
commits with real KZG (crates/l2/sequencer/l1_committer.rs:1489
generate_blobs_bundle; crates/common/types/blobs_bundle.rs), and rollup
state can be rebuilt from those blobs alone
(crates/l2/utils/state_reconstruct.rs).

Packing: the payload (RLP of the batch's block list) is length-prefixed
and split into 31-byte chunks, one per field element with a zero top byte
— every 32-byte word is then canonically < BLS_MODULUS by construction.
"""

from __future__ import annotations

import dataclasses

from ..crypto import kzg
from ..primitives import rlp
from ..primitives.block import Block

BYTES_PER_ELEMENT = 31  # payload bytes per field element (top byte zero)
PAYLOAD_PER_BLOB = BYTES_PER_ELEMENT * kzg.FIELD_ELEMENTS_PER_BLOB


class BlobError(Exception):
    pass


@dataclasses.dataclass
class BlobsBundle:
    blobs: list[bytes]
    commitments: list[bytes]
    proofs: list[bytes]

    @property
    def versioned_hashes(self) -> list[bytes]:
        return [kzg.commitment_to_versioned_hash(c)
                for c in self.commitments]

    def verify(self, setup=None) -> bool:
        if not (len(self.blobs) == len(self.commitments)
                == len(self.proofs)):
            return False
        return all(
            kzg.verify_blob_kzg_proof(b, c, p, setup)
            for b, c, p in zip(self.blobs, self.commitments, self.proofs))


def pack_payload(payload: bytes) -> list[bytes]:
    """Length-prefixed payload -> list of canonical blobs."""
    framed = len(payload).to_bytes(8, "big") + payload
    blobs = []
    for off in range(0, len(framed), PAYLOAD_PER_BLOB):
        chunk = framed[off:off + PAYLOAD_PER_BLOB]
        blob = bytearray(kzg.BYTES_PER_BLOB)
        for i in range(0, len(chunk), BYTES_PER_ELEMENT):
            el = chunk[i:i + BYTES_PER_ELEMENT]
            fe = i // BYTES_PER_ELEMENT
            blob[fe * 32 + 1:fe * 32 + 1 + len(el)] = el
        blobs.append(bytes(blob))
    return blobs or [bytes(kzg.BYTES_PER_BLOB)]


def unpack_payload(blobs: list[bytes]) -> bytes:
    stream = bytearray()
    for blob in blobs:
        if len(blob) != kzg.BYTES_PER_BLOB:
            raise BlobError("blob must be 131072 bytes")
        for fe in range(kzg.FIELD_ELEMENTS_PER_BLOB):
            word = blob[fe * 32:(fe + 1) * 32]
            if word[0] != 0:
                raise BlobError("non-canonical packed element")
            stream += word[1:]
    if len(stream) < 8:
        raise BlobError("truncated payload")
    size = int.from_bytes(stream[:8], "big")
    if size > len(stream) - 8:
        raise BlobError("payload length prefix exceeds blob data")
    return bytes(stream[8:8 + size])


def blocks_to_payload(blocks: list[Block]) -> bytes:
    return rlp.encode([b.encode() for b in blocks])


def payload_to_blocks(payload: bytes) -> list[Block]:
    items = rlp.decode(payload)
    if not isinstance(items, list):
        raise BlobError("payload is not an RLP list")
    return [Block.decode(bytes(item)) for item in items]


def generate_blobs_bundle(blocks: list[Block], setup=None) -> BlobsBundle:
    """The committer's sidecar: blocks -> blobs -> KZG commitments/proofs."""
    blobs = pack_payload(blocks_to_payload(blocks))
    commitments, proofs = [], []
    for blob in blobs:
        c = kzg.blob_to_kzg_commitment(blob, setup)
        commitments.append(c)
        proofs.append(kzg.compute_blob_kzg_proof(blob, c, setup))
    return BlobsBundle(blobs=blobs, commitments=commitments, proofs=proofs)


def reconstruct_blocks(bundle: BlobsBundle, setup=None) -> list[Block]:
    """State reconstruction entry: verify the sidecar, then decode the
    batch's blocks back out of the blob payload."""
    if not bundle.verify(setup):
        raise BlobError("blobs bundle failed KZG verification")
    return payload_to_blocks(unpack_payload(bundle.blobs))
