"""L1 settlement client interface + in-memory simulator.

The interface mirrors what the sequencer needs from the OnChainProposer /
CommonBridge contracts (reference: crates/l2/contracts/src/l1/*.sol and the
EthClient call sites in l1_committer.rs / l1_proof_sender.rs / l1_watcher.rs).
`InMemoryL1` enforces the same state-machine rules (sequential commitment,
commit-before-verify, verification requires all configured prover types) so
the full pipeline runs hermetically; an HTTP EthClient against a real L1
implements the same interface in the deployment rounds.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..crypto.keccak import keccak256
from .leadership import FencedError, LeaseState


class L1Error(Exception):
    pass


@dataclasses.dataclass
class Deposit:
    l1_tx_hash: bytes
    recipient: bytes
    amount: int
    data: bytes = b""
    gas_limit: int = 200_000
    index: int = 0
    l1_block: int = 0   # L1 block of inclusion (0 = unknown/legacy)


# the aliased L1-bridge sender for privileged txs: deposits must NOT spend
# the recipient's nonce (their next real tx would fail) — the mint executes
# from this alias, whose nonce counts processed deposits
L1_BRIDGE_ALIAS = bytes.fromhex("1111000000000000000000000000000000001111")


def make_deposit_tx(chain_id: int, deposit: Deposit):
    """Deterministic privileged tx for an L1 deposit — shared by the L2
    watcher and the L1 commitment check, so the L1 can recompute and verify
    exactly which privileged txs a batch may contain."""
    from ..primitives.transaction import TYPE_PRIVILEGED, Transaction

    return Transaction(
        tx_type=TYPE_PRIVILEGED, chain_id=chain_id, nonce=deposit.index,
        from_addr=L1_BRIDGE_ALIAS, to=deposit.recipient,
        value=deposit.amount, gas_limit=deposit.gas_limit,
        data=deposit.data,
    )


class L1Client:
    def commit_batch(self, number: int, new_state_root: bytes,
                     commitment: bytes,
                     privileged_tx_hashes: list[bytes] = (),
                     messages_root: bytes = b"\x00" * 32,
                     epoch: int | None = None) -> bytes:
        raise NotImplementedError

    def verify_batches(self, first: int, last: int,
                       proofs: dict[str, bytes],
                       epoch: int | None = None) -> bytes:
        raise NotImplementedError

    def verify_batches_aggregated(self, first: int, last: int,
                                  aggregates: dict[str, bytes],
                                  epoch: int | None = None) -> bytes:
        """Settle a contiguous batch range with ONE aggregated proof per
        prover type instead of one full proof per batch (the recursion
        path, docs/AGGREGATION.md): `aggregates` maps prover type to a
        single wire payload that still binds every batch's committed
        output, so L1 calldata amortizes N batches into one tx."""
        raise NotImplementedError

    def last_committed_batch(self) -> int:
        raise NotImplementedError

    def last_verified_batch(self) -> int:
        raise NotImplementedError

    def get_deposits(self, since_index: int) -> list[Deposit]:
        raise NotImplementedError

    # DA surface for based followers (the commit tx carries the sidecar)
    def publish_blobs(self, number: int, bundle) -> None:
        raise NotImplementedError

    def get_blob_sidecar(self, number: int):
        return None

    def get_committed_state_root(self, number: int) -> bytes | None:
        return None

    def get_committed_commitment(self, number: int) -> bytes | None:
        """The on-chain commitment word for a settled batch (None when
        unknown) — the idempotent committer and startup reconciliation
        compare it against the locally recomputed commitment."""
        return None

    def get_block_number(self) -> int:
        """Current L1 head block number (confirmation-depth anchor)."""
        raise NotImplementedError

    # ---- leader lease cell (sequencer HA, docs/SEQUENCER_HA.md) ----
    # A compare-and-swap cell holding (holder, epoch, expiry).  Every
    # successful acquire mints a strictly increasing epoch — the fencing
    # token that commit/verify transactions carry; the L1 rejects any
    # write fenced below the highest epoch it has granted.
    def supports_leases(self) -> bool:
        """Whether this client exposes the leader-lease cell; HA mode
        refuses to start against an L1 that cannot fence."""
        return False

    def acquire_lease(self, node_id: str, ttl: float) -> int | None:
        """CAS acquire: returns the new epoch, or None while another
        holder's lease is still live."""
        raise L1Error("this L1 client does not support leader leases")

    def renew_lease(self, node_id: str, epoch: int, ttl: float) -> bool:
        """Extend the holder's own live lease; False once the cell has
        moved on (expired + re-acquired, or released)."""
        raise L1Error("this L1 client does not support leader leases")

    def release_lease(self, node_id: str, epoch: int) -> bool:
        """Voluntary release (clean shutdown): expires the lease now so
        a standby can win without waiting out the ttl."""
        raise L1Error("this L1 client does not support leader leases")

    def get_lease(self) -> LeaseState | None:
        """Read-side view of the lease cell (None = never acquired)."""
        raise L1Error("this L1 client does not support leader leases")


class InMemoryL1(L1Client):
    """OnChainProposer/CommonBridge semantics without an actual chain.

    Carries a minimal L1 block model: every state-changing transaction
    (commit / verify / deposit / claim) is sealed into its own L1 block,
    and a per-block snapshot history backs `reorg(depth)` — the chaos
    battery's handle for dropping the newest commitments/deposits the way
    a real L1 reorg does.  `advance_blocks` mines empty blocks so tests
    can mature a deposit past the watcher's confirmation depth."""

    # per-block snapshots retained for reorg(); older history is trimmed
    MAX_HISTORY = 512

    def __init__(self, needed_prover_types: list[str],
                 l2_chain_id: int | None = None):
        self.needed = list(needed_prover_types)
        self.l2_chain_id = l2_chain_id
        self.commitments: dict[int, tuple[bytes, bytes]] = {}
        self.message_roots: dict[int, bytes] = {}
        self.blob_sidecars: dict[int, object] = {}
        self.claimed: set[bytes] = set()
        self.verified_up_to = 0
        self.deposits: list[Deposit] = []
        self.consumed_deposits = 0
        self.lock = threading.RLock()
        self.block_number = 0
        self.reorgs_total = 0
        # aggregated-settlement accounting (observability only — not part
        # of the reorg snapshot state): how many verify txs were
        # aggregated and how many per-batch proofs they amortized away
        self.aggregated_settlements = 0
        self.proofs_settled_aggregated = 0
        # leader lease cell (sequencer HA).  Deliberately OUTSIDE the
        # reorg snapshot history: fencing epochs must stay monotonic even
        # across an L1 reorg — rewinding the cell could re-mint an epoch
        # and hand two holders the same fencing token.
        self._lease: dict | None = None
        self._lease_epoch = 0          # highest epoch ever granted
        self._lease_clock = time.time  # injectable for deterministic tests
        self.fenced_writes_total = 0
        self._history: list[tuple[int, dict]] = [(0, self._snapshot())]

    # ---- L1 block model ----
    def _snapshot(self) -> dict:
        return {
            "commitments": dict(self.commitments),
            "message_roots": dict(self.message_roots),
            "blob_sidecars": dict(self.blob_sidecars),
            "claimed": set(self.claimed),
            "verified_up_to": self.verified_up_to,
            "deposits": list(self.deposits),
            "consumed_deposits": self.consumed_deposits,
        }

    def _restore(self, snap: dict) -> None:
        self.commitments = dict(snap["commitments"])
        self.message_roots = dict(snap["message_roots"])
        self.blob_sidecars = dict(snap["blob_sidecars"])
        self.claimed = set(snap["claimed"])
        self.verified_up_to = snap["verified_up_to"]
        self.deposits = list(snap["deposits"])
        self.consumed_deposits = snap["consumed_deposits"]

    def _mine(self) -> int:
        """Seal the current mutation into a new L1 block (lock held)."""
        self.block_number += 1
        self._history.append((self.block_number, self._snapshot()))
        if len(self._history) > self.MAX_HISTORY:
            self._history.pop(0)
        return self.block_number

    def advance_blocks(self, n: int = 1) -> int:
        """Mine n empty L1 blocks (confirmations pass without activity)."""
        with self.lock:
            for _ in range(n):
                self._mine()
            return self.block_number

    def get_block_number(self) -> int:
        with self.lock:
            return self.block_number

    def reorg(self, depth: int) -> int:
        """Drop the newest `depth` L1 blocks and everything they carried
        (commitments, verifications, deposits, claims); returns the new
        head.  Test surface for the sequencer's reorg handling."""
        with self.lock:
            if depth <= 0:
                raise ValueError("reorg depth must be positive")
            if depth > self.block_number:
                raise L1Error(
                    f"reorg depth {depth} exceeds chain height "
                    f"{self.block_number}")
            new_head = self.block_number - depth
            snap = None
            for blk, s in reversed(self._history):
                if blk <= new_head:
                    snap = s
                    break
            if snap is None:
                raise L1Error(
                    f"reorg to block {new_head} is beyond the retained "
                    f"snapshot history")
            self._restore(snap)
            self._history = [(b, s) for b, s in self._history
                             if b <= new_head]
            self.block_number = new_head
            self.reorgs_total += 1
            return new_head

    # ---- leader lease cell ----
    def supports_leases(self) -> bool:
        return True

    def acquire_lease(self, node_id: str, ttl: float) -> int | None:
        with self.lock:
            now = self._lease_clock()
            lease = self._lease
            if lease is not None and lease["holder"] != node_id \
                    and lease["expires"] > now:
                return None    # CAS lost: another holder is still live
            self._lease_epoch += 1
            self._lease = {"holder": node_id, "epoch": self._lease_epoch,
                           "expires": now + ttl}
            self._mine()
            return self._lease_epoch

    def renew_lease(self, node_id: str, epoch: int, ttl: float) -> bool:
        with self.lock:
            lease = self._lease
            if lease is None or lease["holder"] != node_id \
                    or lease["epoch"] != epoch:
                return False   # the cell moved on: holder is deposed
            lease["expires"] = self._lease_clock() + ttl
            return True

    def release_lease(self, node_id: str, epoch: int) -> bool:
        with self.lock:
            lease = self._lease
            if lease is None or lease["holder"] != node_id \
                    or lease["epoch"] != epoch:
                return False
            lease["expires"] = self._lease_clock()
            self._mine()
            return True

    def get_lease(self) -> LeaseState | None:
        with self.lock:
            if self._lease is None:
                return None
            return LeaseState(holder=self._lease["holder"],
                              epoch=self._lease["epoch"],
                              expires=self._lease["expires"])

    def expire_lease(self) -> None:
        """Chaos/test surface: force the current lease to expire NOW —
        the holder crashed and its renewals stopped, without waiting
        out the wall-clock ttl."""
        with self.lock:
            if self._lease is not None:
                self._lease["expires"] = self._lease_clock()

    def _check_epoch(self, epoch: int | None):
        """Fencing discipline (lock held): a write stamped with an epoch
        below the highest ever granted is a deposed leader's zombie write
        — reject it.  epoch=None is the non-HA single-sequencer path."""
        if epoch is None:
            return
        if epoch < self._lease_epoch:
            self.fenced_writes_total += 1
            raise FencedError(
                f"write fenced: epoch {epoch} < current lease epoch "
                f"{self._lease_epoch}", epoch=epoch,
                current=self._lease_epoch)

    # ---- OnChainProposer ----
    def commit_batch(self, number, new_state_root, commitment,
                     privileged_tx_hashes=(),
                     messages_root=b"\x00" * 32, epoch=None) -> bytes:
        with self.lock:
            self._check_epoch(epoch)
            if number != len(self.commitments) + 1:
                raise L1Error(
                    f"batch {number} out of order "
                    f"(expected {len(self.commitments) + 1})")
            # privileged txs must correspond 1:1, in order, to the bridge's
            # next unconsumed deposits (reference: OnChainProposer checks
            # the privileged tx digest against CommonBridge's queue)
            cursor = self.consumed_deposits
            for h in privileged_tx_hashes:
                if cursor >= len(self.deposits):
                    raise L1Error("privileged tx without matching deposit")
                if self.l2_chain_id is not None:
                    expected = make_deposit_tx(
                        self.l2_chain_id, self.deposits[cursor]).hash
                    if h != expected:
                        raise L1Error(
                            f"privileged tx {h.hex()} does not match "
                            f"deposit {cursor}")
                cursor += 1
            self.consumed_deposits = cursor
            self.commitments[number] = (new_state_root, commitment)
            self.message_roots[number] = bytes(messages_root)
            self._mine()
            return keccak256(b"commit" + number.to_bytes(8, "big")
                             + commitment)

    def publish_blobs(self, number: int, bundle) -> None:
        # the sidecar rides the commit tx (no block of its own); amend the
        # commit block's snapshot so a reorg keeps blob and commitment
        # consistent
        with self.lock:
            self.blob_sidecars[number] = bundle
            if self._history:
                self._history[-1][1]["blob_sidecars"][number] = bundle

    def get_blob_sidecar(self, number: int):
        with self.lock:
            return self.blob_sidecars.get(number)

    def get_committed_state_root(self, number: int) -> bytes | None:
        with self.lock:
            rec = self.commitments.get(number)
            return rec[0] if rec else None

    def get_committed_commitment(self, number: int) -> bytes | None:
        with self.lock:
            rec = self.commitments.get(number)
            return rec[1] if rec else None

    def verify_batches(self, first, last, proofs, epoch=None) -> bytes:
        """proofs: {prover_type: [proof_bytes for each batch first..last]}.
        Each proof's committed ProgramOutput must bind the batch's stored
        state root and messages root (a fabricated commit-time messages
        root would otherwise let phantom withdrawals be claimed)."""
        import json as _json

        from ..guest.execution import ProgramOutput

        with self.lock:
            self._check_epoch(epoch)
            if first != self.verified_up_to + 1:
                raise L1Error("verification must be contiguous")
            if last > len(self.commitments):
                raise L1Error("cannot verify uncommitted batches")
            for t in self.needed:
                batch_proofs = proofs.get(t)
                if not batch_proofs or len(batch_proofs) != last - first + 1:
                    raise L1Error(f"missing {t} proofs")
                for offset, raw in enumerate(batch_proofs):
                    number = first + offset
                    try:
                        obj = _json.loads(raw)
                        out = ProgramOutput.decode(
                            bytes.fromhex(obj["output"][2:]))
                    except (ValueError, KeyError, TypeError):
                        raise L1Error(f"unparseable {t} proof")
                    state_root, _ = self.commitments[number]
                    if out.final_state_root != state_root:
                        raise L1Error(
                            f"proof state root mismatch for batch {number}")
                    if out.messages_root != self.message_roots[number]:
                        raise L1Error(
                            f"proof messages root mismatch for batch "
                            f"{number}")
            self.verified_up_to = last
            self._mine()
            return keccak256(b"verify" + first.to_bytes(8, "big")
                             + last.to_bytes(8, "big"))

    def verify_batches_aggregated(self, first, last, aggregates,
                                  epoch=None) -> bytes:
        """aggregates: {prover_type: payload_bytes} — ONE wire payload per
        type for the whole range.  The payload carries a per-batch
        "proofs" list whose entries each commit a ProgramOutput; every
        entry must bind its batch's stored state root and messages root
        exactly like the per-batch path, and a STARK-backed payload must
        carry exactly one "outer" recursion proof for the range (the
        sequencer-side aggregator fully verified it before submitting,
        mirroring how send_proofs audits before verify_batches)."""
        import json as _json

        from ..guest.execution import ProgramOutput

        with self.lock:
            self._check_epoch(epoch)
            if first != self.verified_up_to + 1:
                raise L1Error("verification must be contiguous")
            if last > len(self.commitments):
                raise L1Error("cannot verify uncommitted batches")
            count = last - first + 1
            for t in self.needed:
                raw = aggregates.get(t)
                if not raw:
                    raise L1Error(f"missing {t} aggregate")
                try:
                    obj = _json.loads(raw)
                    if obj.get("format") != "aggregate":
                        raise ValueError("not an aggregate payload")
                    batch_proofs = obj["proofs"]
                except (ValueError, KeyError, TypeError):
                    raise L1Error(f"unparseable {t} aggregate")
                if not isinstance(batch_proofs, list) \
                        or len(batch_proofs) != count:
                    raise L1Error(
                        f"{t} aggregate does not cover batches "
                        f"{first}..{last}")
                if any(isinstance(p, dict) and p.get("proof") is not None
                       for p in batch_proofs) \
                        and not isinstance(obj.get("outer"), dict):
                    raise L1Error(
                        f"{t} aggregate carries STARK inners but no "
                        f"outer recursion proof")
                for offset, entry in enumerate(batch_proofs):
                    number = first + offset
                    try:
                        out = ProgramOutput.decode(
                            bytes.fromhex(entry["output"][2:]))
                    except (ValueError, KeyError, TypeError):
                        raise L1Error(
                            f"unparseable {t} aggregate entry for "
                            f"batch {number}")
                    state_root, _ = self.commitments[number]
                    if out.final_state_root != state_root:
                        raise L1Error(
                            f"proof state root mismatch for batch {number}")
                    if out.messages_root != self.message_roots[number]:
                        raise L1Error(
                            f"proof messages root mismatch for batch "
                            f"{number}")
            self.verified_up_to = last
            self.aggregated_settlements += 1
            self.proofs_settled_aggregated += count
            self._mine()
            return keccak256(b"verify-agg" + first.to_bytes(8, "big")
                             + last.to_bytes(8, "big"))

    def last_committed_batch(self) -> int:
        return len(self.commitments)

    def last_verified_batch(self) -> int:
        return self.verified_up_to

    # ---- CommonBridge: withdrawals ----
    def claim_withdrawal(self, batch_number: int, leaf: bytes, index: int,
                         proof: list[bytes]) -> bytes:
        """Claim an L2->L1 message once its batch is VERIFIED; Merkle proof
        against the batch's message root; double-claims rejected."""
        from .messages import verify_message_proof

        with self.lock:
            if batch_number > self.verified_up_to:
                raise L1Error("batch not verified yet")
            root = self.message_roots.get(batch_number)
            if not root or root == b"\x00" * 32:
                raise L1Error("batch has no messages")
            if leaf in self.claimed:
                raise L1Error("message already claimed")
            if not verify_message_proof(root, leaf, index, proof):
                raise L1Error("invalid message proof")
            self.claimed.add(leaf)
            self._mine()
            return keccak256(b"claim" + leaf)

    # ---- CommonBridge: deposits ----
    def deposit(self, recipient: bytes, amount: int, data: bytes = b"",
                gas_limit: int = 200_000):
        """L1-side user action (tests drive this)."""
        with self.lock:
            idx = len(self.deposits)
            d = Deposit(
                l1_tx_hash=keccak256(b"deposit" + idx.to_bytes(8, "big")
                                     + recipient),
                recipient=recipient, amount=amount, data=data,
                gas_limit=gas_limit, index=idx,
                l1_block=self.block_number + 1)
            self.deposits.append(d)
            self._mine()
            return d

    def get_deposits(self, since_index: int) -> list[Deposit]:
        with self.lock:
            return self.deposits[since_index:]


class PersistentInMemoryL1(InMemoryL1):
    """Dev L1 with its contract state JSON-persisted in the datadir, so a
    kill -9'd `ethrex-tpu l2` stack resumes against the same simulated L1
    (a real deployment points --l1.url at an actual chain instead)."""

    def __init__(self, path: str, needed_prover_types: list[str],
                 l2_chain_id: int | None = None):
        super().__init__(needed_prover_types, l2_chain_id)
        self.path = path
        self._loading = True
        try:
            import json as _json
            import os as _os

            if _os.path.exists(path):
                with open(path) as f:
                    o = _json.load(f)
                self.commitments = {
                    int(k): (bytes.fromhex(v[0]), bytes.fromhex(v[1]))
                    for k, v in o["commitments"].items()}
                self.message_roots = {
                    int(k): bytes.fromhex(v)
                    for k, v in o["message_roots"].items()}
                self.claimed = {bytes.fromhex(h) for h in o["claimed"]}
                self.verified_up_to = o["verified_up_to"]
                self.consumed_deposits = o["consumed_deposits"]
                self.block_number = o.get("block_number", 0)
                # the lease cell persists: fencing epochs stay monotonic
                # across dev-L1 restarts (expiry is wall-clock time)
                lease = o.get("lease")
                self._lease = dict(lease) if lease else None
                self._lease_epoch = o.get("lease_epoch", 0)
                self.deposits = [
                    Deposit(l1_tx_hash=bytes.fromhex(d["h"]),
                            recipient=bytes.fromhex(d["r"]),
                            amount=d["a"], data=bytes.fromhex(d["d"]),
                            gas_limit=d["g"], index=d["i"],
                            l1_block=d.get("b", 0))
                    for d in o["deposits"]]
                from .blobs import BlobsBundle

                self.blob_sidecars = {
                    int(k): BlobsBundle(
                        blobs=[bytes.fromhex(x) for x in v["blobs"]],
                        commitments=[bytes.fromhex(x)
                                     for x in v["commitments"]],
                        proofs=[bytes.fromhex(x) for x in v["proofs"]])
                    for k, v in o["blobs"].items()}
        finally:
            self._loading = False
        # reorg history does not persist across restarts: re-baseline the
        # snapshot history at the reloaded state (a reorg can only rewind
        # to blocks observed by this process)
        self._history = [(self.block_number, self._snapshot())]

    def _save(self):
        if getattr(self, "_loading", False):
            return
        import json as _json

        o = {
            "commitments": {str(k): [v[0].hex(), v[1].hex()]
                            for k, v in self.commitments.items()},
            "message_roots": {str(k): v.hex()
                              for k, v in self.message_roots.items()},
            "claimed": [h.hex() for h in self.claimed],
            "verified_up_to": self.verified_up_to,
            "consumed_deposits": self.consumed_deposits,
            "block_number": self.block_number,
            "lease": self._lease,
            "lease_epoch": self._lease_epoch,
            "deposits": [{"h": d.l1_tx_hash.hex(), "r": d.recipient.hex(),
                          "a": d.amount, "d": d.data.hex(),
                          "g": d.gas_limit, "i": d.index, "b": d.l1_block}
                         for d in self.deposits],
            "blobs": {str(k): {"blobs": [x.hex() for x in b.blobs],
                               "commitments": [x.hex()
                                               for x in b.commitments],
                               "proofs": [x.hex() for x in b.proofs]}
                      for k, b in self.blob_sidecars.items()},
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(o, f)
        import os as _os

        _os.replace(tmp, self.path)

    def commit_batch(self, *a, **kw):
        out = super().commit_batch(*a, **kw)
        with self.lock:
            self._save()
        return out

    def publish_blobs(self, number: int, bundle) -> None:
        super().publish_blobs(number, bundle)
        with self.lock:
            self._save()

    def verify_batches(self, *a, **kw):
        out = super().verify_batches(*a, **kw)
        with self.lock:
            self._save()
        return out

    def verify_batches_aggregated(self, *a, **kw):
        out = super().verify_batches_aggregated(*a, **kw)
        with self.lock:
            self._save()
        return out

    def claim_withdrawal(self, *a, **kw):
        out = super().claim_withdrawal(*a, **kw)
        with self.lock:
            self._save()
        return out

    def deposit(self, *a, **kw):
        out = super().deposit(*a, **kw)
        with self.lock:
            self._save()
        return out

    def advance_blocks(self, n: int = 1) -> int:
        out = super().advance_blocks(n)
        with self.lock:
            self._save()
        return out

    def reorg(self, depth: int) -> int:
        out = super().reorg(depth)
        with self.lock:
            self._save()
        return out

    def acquire_lease(self, node_id: str, ttl: float) -> int | None:
        out = super().acquire_lease(node_id, ttl)
        with self.lock:
            self._save()
        return out

    def renew_lease(self, node_id: str, epoch: int, ttl: float) -> bool:
        out = super().renew_lease(node_id, epoch, ttl)
        with self.lock:
            self._save()
        return out

    def release_lease(self, node_id: str, epoch: int) -> bool:
        out = super().release_lease(node_id, epoch)
        with self.lock:
            self._save()
        return out
