"""L1 settlement client interface + in-memory simulator.

The interface mirrors what the sequencer needs from the OnChainProposer /
CommonBridge contracts (reference: crates/l2/contracts/src/l1/*.sol and the
EthClient call sites in l1_committer.rs / l1_proof_sender.rs / l1_watcher.rs).
`InMemoryL1` enforces the same state-machine rules (sequential commitment,
commit-before-verify, verification requires all configured prover types) so
the full pipeline runs hermetically; an HTTP EthClient against a real L1
implements the same interface in the deployment rounds.
"""

from __future__ import annotations

import dataclasses
import threading

from ..crypto.keccak import keccak256


class L1Error(Exception):
    pass


@dataclasses.dataclass
class Deposit:
    l1_tx_hash: bytes
    recipient: bytes
    amount: int
    data: bytes = b""
    gas_limit: int = 200_000
    index: int = 0


# the aliased L1-bridge sender for privileged txs: deposits must NOT spend
# the recipient's nonce (their next real tx would fail) — the mint executes
# from this alias, whose nonce counts processed deposits
L1_BRIDGE_ALIAS = bytes.fromhex("1111000000000000000000000000000000001111")


def make_deposit_tx(chain_id: int, deposit: Deposit):
    """Deterministic privileged tx for an L1 deposit — shared by the L2
    watcher and the L1 commitment check, so the L1 can recompute and verify
    exactly which privileged txs a batch may contain."""
    from ..primitives.transaction import TYPE_PRIVILEGED, Transaction

    return Transaction(
        tx_type=TYPE_PRIVILEGED, chain_id=chain_id, nonce=deposit.index,
        from_addr=L1_BRIDGE_ALIAS, to=deposit.recipient,
        value=deposit.amount, gas_limit=deposit.gas_limit,
        data=deposit.data,
    )


class L1Client:
    def commit_batch(self, number: int, new_state_root: bytes,
                     commitment: bytes,
                     privileged_tx_hashes: list[bytes] = (),
                     messages_root: bytes = b"\x00" * 32) -> bytes:
        raise NotImplementedError

    def verify_batches(self, first: int, last: int,
                       proofs: dict[str, bytes]) -> bytes:
        raise NotImplementedError

    def last_committed_batch(self) -> int:
        raise NotImplementedError

    def last_verified_batch(self) -> int:
        raise NotImplementedError

    def get_deposits(self, since_index: int) -> list[Deposit]:
        raise NotImplementedError

    # DA surface for based followers (the commit tx carries the sidecar)
    def publish_blobs(self, number: int, bundle) -> None:
        raise NotImplementedError

    def get_blob_sidecar(self, number: int):
        return None

    def get_committed_state_root(self, number: int) -> bytes | None:
        return None


class InMemoryL1(L1Client):
    """OnChainProposer/CommonBridge semantics without an actual chain."""

    def __init__(self, needed_prover_types: list[str],
                 l2_chain_id: int | None = None):
        self.needed = list(needed_prover_types)
        self.l2_chain_id = l2_chain_id
        self.commitments: dict[int, tuple[bytes, bytes]] = {}
        self.message_roots: dict[int, bytes] = {}
        self.blob_sidecars: dict[int, object] = {}
        self.claimed: set[bytes] = set()
        self.verified_up_to = 0
        self.deposits: list[Deposit] = []
        self.consumed_deposits = 0
        self.lock = threading.RLock()

    # ---- OnChainProposer ----
    def commit_batch(self, number, new_state_root, commitment,
                     privileged_tx_hashes=(),
                     messages_root=b"\x00" * 32) -> bytes:
        with self.lock:
            if number != len(self.commitments) + 1:
                raise L1Error(
                    f"batch {number} out of order "
                    f"(expected {len(self.commitments) + 1})")
            # privileged txs must correspond 1:1, in order, to the bridge's
            # next unconsumed deposits (reference: OnChainProposer checks
            # the privileged tx digest against CommonBridge's queue)
            cursor = self.consumed_deposits
            for h in privileged_tx_hashes:
                if cursor >= len(self.deposits):
                    raise L1Error("privileged tx without matching deposit")
                if self.l2_chain_id is not None:
                    expected = make_deposit_tx(
                        self.l2_chain_id, self.deposits[cursor]).hash
                    if h != expected:
                        raise L1Error(
                            f"privileged tx {h.hex()} does not match "
                            f"deposit {cursor}")
                cursor += 1
            self.consumed_deposits = cursor
            self.commitments[number] = (new_state_root, commitment)
            self.message_roots[number] = bytes(messages_root)
            return keccak256(b"commit" + number.to_bytes(8, "big")
                             + commitment)

    def publish_blobs(self, number: int, bundle) -> None:
        with self.lock:
            self.blob_sidecars[number] = bundle

    def get_blob_sidecar(self, number: int):
        with self.lock:
            return self.blob_sidecars.get(number)

    def get_committed_state_root(self, number: int) -> bytes | None:
        with self.lock:
            rec = self.commitments.get(number)
            return rec[0] if rec else None

    def verify_batches(self, first, last, proofs) -> bytes:
        """proofs: {prover_type: [proof_bytes for each batch first..last]}.
        Each proof's committed ProgramOutput must bind the batch's stored
        state root and messages root (a fabricated commit-time messages
        root would otherwise let phantom withdrawals be claimed)."""
        import json as _json

        from ..guest.execution import ProgramOutput

        with self.lock:
            if first != self.verified_up_to + 1:
                raise L1Error("verification must be contiguous")
            if last > len(self.commitments):
                raise L1Error("cannot verify uncommitted batches")
            for t in self.needed:
                batch_proofs = proofs.get(t)
                if not batch_proofs or len(batch_proofs) != last - first + 1:
                    raise L1Error(f"missing {t} proofs")
                for offset, raw in enumerate(batch_proofs):
                    number = first + offset
                    try:
                        obj = _json.loads(raw)
                        out = ProgramOutput.decode(
                            bytes.fromhex(obj["output"][2:]))
                    except (ValueError, KeyError, TypeError):
                        raise L1Error(f"unparseable {t} proof")
                    state_root, _ = self.commitments[number]
                    if out.final_state_root != state_root:
                        raise L1Error(
                            f"proof state root mismatch for batch {number}")
                    if out.messages_root != self.message_roots[number]:
                        raise L1Error(
                            f"proof messages root mismatch for batch "
                            f"{number}")
            self.verified_up_to = last
            return keccak256(b"verify" + first.to_bytes(8, "big")
                             + last.to_bytes(8, "big"))

    def last_committed_batch(self) -> int:
        return len(self.commitments)

    def last_verified_batch(self) -> int:
        return self.verified_up_to

    # ---- CommonBridge: withdrawals ----
    def claim_withdrawal(self, batch_number: int, leaf: bytes, index: int,
                         proof: list[bytes]) -> bytes:
        """Claim an L2->L1 message once its batch is VERIFIED; Merkle proof
        against the batch's message root; double-claims rejected."""
        from .messages import verify_message_proof

        with self.lock:
            if batch_number > self.verified_up_to:
                raise L1Error("batch not verified yet")
            root = self.message_roots.get(batch_number)
            if not root or root == b"\x00" * 32:
                raise L1Error("batch has no messages")
            if leaf in self.claimed:
                raise L1Error("message already claimed")
            if not verify_message_proof(root, leaf, index, proof):
                raise L1Error("invalid message proof")
            self.claimed.add(leaf)
            return keccak256(b"claim" + leaf)

    # ---- CommonBridge: deposits ----
    def deposit(self, recipient: bytes, amount: int, data: bytes = b"",
                gas_limit: int = 200_000):
        """L1-side user action (tests drive this)."""
        with self.lock:
            idx = len(self.deposits)
            d = Deposit(
                l1_tx_hash=keccak256(b"deposit" + idx.to_bytes(8, "big")
                                     + recipient),
                recipient=recipient, amount=amount, data=data,
                gas_limit=gas_limit, index=idx)
            self.deposits.append(d)
            return d

    def get_deposits(self, since_index: int) -> list[Deposit]:
        with self.lock:
            return self.deposits[since_index:]


class PersistentInMemoryL1(InMemoryL1):
    """Dev L1 with its contract state JSON-persisted in the datadir, so a
    kill -9'd `ethrex-tpu l2` stack resumes against the same simulated L1
    (a real deployment points --l1.url at an actual chain instead)."""

    def __init__(self, path: str, needed_prover_types: list[str],
                 l2_chain_id: int | None = None):
        super().__init__(needed_prover_types, l2_chain_id)
        self.path = path
        self._loading = True
        try:
            import json as _json
            import os as _os

            if _os.path.exists(path):
                with open(path) as f:
                    o = _json.load(f)
                self.commitments = {
                    int(k): (bytes.fromhex(v[0]), bytes.fromhex(v[1]))
                    for k, v in o["commitments"].items()}
                self.message_roots = {
                    int(k): bytes.fromhex(v)
                    for k, v in o["message_roots"].items()}
                self.claimed = {bytes.fromhex(h) for h in o["claimed"]}
                self.verified_up_to = o["verified_up_to"]
                self.consumed_deposits = o["consumed_deposits"]
                self.deposits = [
                    Deposit(l1_tx_hash=bytes.fromhex(d["h"]),
                            recipient=bytes.fromhex(d["r"]),
                            amount=d["a"], data=bytes.fromhex(d["d"]),
                            gas_limit=d["g"], index=d["i"])
                    for d in o["deposits"]]
                from .blobs import BlobsBundle

                self.blob_sidecars = {
                    int(k): BlobsBundle(
                        blobs=[bytes.fromhex(x) for x in v["blobs"]],
                        commitments=[bytes.fromhex(x)
                                     for x in v["commitments"]],
                        proofs=[bytes.fromhex(x) for x in v["proofs"]])
                    for k, v in o["blobs"].items()}
        finally:
            self._loading = False

    def _save(self):
        if getattr(self, "_loading", False):
            return
        import json as _json

        o = {
            "commitments": {str(k): [v[0].hex(), v[1].hex()]
                            for k, v in self.commitments.items()},
            "message_roots": {str(k): v.hex()
                              for k, v in self.message_roots.items()},
            "claimed": [h.hex() for h in self.claimed],
            "verified_up_to": self.verified_up_to,
            "consumed_deposits": self.consumed_deposits,
            "deposits": [{"h": d.l1_tx_hash.hex(), "r": d.recipient.hex(),
                          "a": d.amount, "d": d.data.hex(),
                          "g": d.gas_limit, "i": d.index}
                         for d in self.deposits],
            "blobs": {str(k): {"blobs": [x.hex() for x in b.blobs],
                               "commitments": [x.hex()
                                               for x in b.commitments],
                               "proofs": [x.hex() for x in b.proofs]}
                      for k, b in self.blob_sidecars.items()},
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(o, f)
        import os as _os

        _os.replace(tmp, self.path)

    def commit_batch(self, *a, **kw):
        out = super().commit_batch(*a, **kw)
        with self.lock:
            self._save()
        return out

    def publish_blobs(self, number: int, bundle) -> None:
        super().publish_blobs(number, bundle)
        with self.lock:
            self._save()

    def verify_batches(self, *a, **kw):
        out = super().verify_batches(*a, **kw)
        with self.lock:
            self._save()
        return out

    def claim_withdrawal(self, *a, **kw):
        out = super().claim_withdrawal(*a, **kw)
        with self.lock:
            self._save()
        return out

    def deposit(self, *a, **kw):
        out = super().deposit(*a, **kw)
        with self.lock:
            self._save()
        return out
