"""OnChainProposer + CommonBridge settlement state machine — a
rule-for-rule behavioral port of the reference's L1 contracts
(/root/reference/crates/l2/contracts/src/l1/OnChainProposer.sol:226-687,
CommonBridge.sol:135-687), re-expressed in Python with the SAME revert
conditions under the SAME identifiers so every guard is testable
case-by-case (tests/test_proposer_rules.py).

This is the semantic core the in-process dev L1 (l2/l1_client.InMemoryL1
and the RPC-deployable rule engine in l2/l1_contract.py) enforces; a
future round compiles the real .sol artifacts, but the STATE MACHINE —
commit succession, versioned-hash binding of privileged txs, the
expiry-forces-inclusion rule, verify-time queue consumption, withdrawal
claims against verified batches, pause/revert flows — is what settlement
correctness rests on, and it lives here in one auditable place.

Conventions mirrored from the contracts:
  * versioned hash = bytes2(count) || low-30-bytes(keccak(hash_0..count))
    (CommonBridge.getPendingTransactionsVersionedHash:341-360);
  * privileged tx hash = keccak(chain_id32 || from20 || to20 || id32 ||
    value32 || gas_limit32 || keccak(data)32) (_sendToL2:253-270);
  * withdrawal leaf = keccak(l2_bridge20 || msg_hash32 || id32) proven
    into the batch's published withdrawal-log Merkle root
    (_verifyMessageProof:640-655);
  * commitments of verified batches are pruned (n-1 on verify).
"""

from __future__ import annotations

import dataclasses

from ..crypto.keccak import keccak256

ETH_TOKEN = b"\x00" * 20
ADDRESS_ALIASING = 0xEE110000000000000000000000000000000011FF


class Revert(Exception):
    """A contract-rule violation; `ident` matches the reference's custom
    error / require message identity."""

    def __init__(self, ident: str):
        super().__init__(ident)
        self.ident = ident


def alias_sender(addr: bytes, is_contract: bool) -> bytes:
    """L1->L2 address aliasing for contract callers (CommonBridge
    _getSenderAlias:239-251; EOAs and EIP-7702 delegates pass through)."""
    if not is_contract:
        return addr
    return ((int.from_bytes(addr, "big") + ADDRESS_ALIASING)
            % (1 << 160)).to_bytes(20, "big")


def versioned_hash(count: int, hashes: list[bytes]) -> bytes:
    """bytes2(count) | uint240(keccak(concat(hashes[:count])))."""
    digest = keccak256(b"".join(hashes[:count]))
    return count.to_bytes(2, "big") + digest[2:]


def privileged_tx_hash(chain_id: int, from_addr: bytes, to: bytes,
                       tx_id: int, value: int, gas_limit: int,
                       data: bytes) -> bytes:
    return keccak256(
        chain_id.to_bytes(32, "big") + from_addr + to
        + tx_id.to_bytes(32, "big") + value.to_bytes(32, "big")
        + gas_limit.to_bytes(32, "big") + keccak256(data))


def withdrawal_leaf(l2_bridge: bytes, msg_hash: bytes,
                    message_id: int) -> bytes:
    return keccak256(l2_bridge + msg_hash + message_id.to_bytes(32, "big"))


def merkle_verify(proof: list[bytes], root: bytes, leaf: bytes) -> bool:
    """OpenZeppelin MerkleProof.verify: sorted-pair hashing."""
    node = leaf
    for sib in proof:
        a, b = (node, sib) if node <= sib else (sib, node)
        node = keccak256(a + b)
    return node == root


@dataclasses.dataclass
class BatchCommitment:
    new_state_root: bytes
    blob_versioned_hash: bytes
    privileged_rolling_hash: bytes
    withdrawals_root: bytes
    last_block_hash: bytes
    non_privileged_count: int
    commit_hash: bytes


class CommonBridgeRules:
    """The bridge's queue/claim state (CommonBridge.sol)."""

    def __init__(self, chain_id: int, l2_bridge: bytes,
                 l2_gas_limit: int = 21_000 * 5,
                 privileged_wait: int = 60 * 60 * 24 * 15):
        self.chain_id = chain_id
        self.l2_bridge = l2_bridge
        self.l2_gas_limit = l2_gas_limit
        self.privileged_wait = privileged_wait
        self.pending_tx_hashes: list[bytes] = []
        self.pending_index = 0
        self.tx_deadline: dict[bytes, int] = {}
        self.transaction_id = 0
        self.deposits_pool = 0          # ETH locked (deposits mapping)
        self.withdrawal_roots: dict[int, bytes] = {}
        self.claimed_ids: set[int] = set()
        self.proposer = None            # set by wire-up
        self.paused = False

    # -- L1 -> L2 ----------------------------------------------------------
    def send_to_l2(self, sender: bytes, to: bytes, value: int,
                   gas_limit: int, data: bytes, now: int,
                   is_contract: bool = False) -> bytes:
        if self.paused:
            raise Revert("EnforcedPause")
        if gas_limit > self.l2_gas_limit:
            raise Revert("CommonBridge: gasLimit exceeds l2GasLimit")
        from_addr = alias_sender(sender, is_contract)
        h = privileged_tx_hash(self.chain_id, from_addr, to,
                               self.transaction_id, value, gas_limit, data)
        self.pending_tx_hashes.append(h)
        self.tx_deadline[h] = now + self.privileged_wait
        self.transaction_id += 1
        return h

    def deposit(self, sender: bytes, l2_recipient: bytes, value: int,
                now: int, is_contract: bool = False) -> bytes:
        self.deposits_pool += value
        return self.send_to_l2(sender, l2_recipient, value,
                               self.l2_gas_limit, b"", now,
                               is_contract=is_contract)

    # -- queue views / consumption ----------------------------------------
    def _pending_len(self) -> int:
        return len(self.pending_tx_hashes) - self.pending_index

    def pending_versioned_hash(self, count: int) -> bytes:
        if count == 0:
            raise Revert("CommonBridge: number is zero (get)")
        if count > self._pending_len():
            raise Revert("CommonBridge: number is greater than the length "
                         "of pendingTxHashes (get)")
        window = self.pending_tx_hashes[
            self.pending_index:self.pending_index + count]
        return versioned_hash(count, window)

    def remove_pending(self, count: int, caller_is_proposer: bool) -> None:
        if not caller_is_proposer:
            raise Revert("onlyOnChainProposer")
        if count > self._pending_len():
            raise Revert("CommonBridge: number is greater than the length "
                         "of pendingTxHashes (remove)")
        self.pending_index += count

    def has_expired_privileged(self, now: int) -> bool:
        if self._pending_len() == 0:
            return False
        head = self.pending_tx_hashes[self.pending_index]
        return now > self.tx_deadline[head]

    # -- withdrawals -------------------------------------------------------
    def publish_withdrawals(self, batch: int, root: bytes,
                            caller_is_proposer: bool) -> None:
        if not caller_is_proposer:
            raise Revert("onlyOnChainProposer")
        if self.withdrawal_roots.get(batch):
            raise Revert("CommonBridge: withdrawal logs already published")
        self.withdrawal_roots[batch] = root

    def claim_withdrawal(self, claimer: bytes, amount: int, batch: int,
                         message_id: int, proof: list[bytes]) -> None:
        if self.paused:
            raise Revert("EnforcedPause")
        if self.deposits_pool < amount:
            raise Revert("CommonBridge: trying to withdraw more tokens/ETH "
                         "than were deposited")
        msg_hash = keccak256(ETH_TOKEN + ETH_TOKEN + claimer
                             + amount.to_bytes(32, "big"))
        root = self.withdrawal_roots.get(batch)
        if not root:
            raise Revert("CommonBridge: the batch that emitted the "
                         "withdrawal logs was not committed")
        if self.proposer is None or batch > self.proposer.last_verified:
            raise Revert("CommonBridge: the batch that emitted the "
                         "withdrawal logs was not verified")
        if message_id in self.claimed_ids:
            raise Revert("CommonBridge: the withdrawal was already claimed")
        leaf = withdrawal_leaf(self.l2_bridge, msg_hash, message_id)
        if not merkle_verify(proof, root, leaf):
            raise Revert("CommonBridge: Invalid proof")
        # effects only after every check: Solidity reverts roll state back,
        # Python does not, so a failed claim must not consume the id
        self.claimed_ids.add(message_id)
        self.deposits_pool -= amount


class OnChainProposerRules:
    """The proposer's commit/verify/revert state (OnChainProposer.sol)."""

    def __init__(self, bridge: CommonBridgeRules, owner: bytes,
                 needed_proof_types: list[str], validium: bool = False):
        self.bridge = bridge
        bridge.proposer = self
        self.owner = owner
        self.needed = list(needed_proof_types)
        self.validium = validium
        self.paused = False
        self.last_committed = 0
        self.last_verified = 0
        self.commitments: dict[int, BatchCommitment] = {}
        # verificationKeys[commit_hash][prover_type]
        self.verification_keys: dict[bytes, dict[str, bytes]] = {}
        # the verifier seam: type -> fn(vk, public_inputs, proof) -> bool
        self.verifiers: dict[str, object] = {}

    # -- admin -------------------------------------------------------------
    def _only_owner(self, caller: bytes) -> None:
        if caller != self.owner:
            raise Revert("OwnableUnauthorizedAccount")

    def _when_not_paused(self) -> None:
        if self.paused:
            raise Revert("EnforcedPause")

    def pause(self, caller: bytes) -> None:
        self._only_owner(caller)
        self.paused = True

    def unpause(self, caller: bytes) -> None:
        self._only_owner(caller)
        self.paused = False

    def set_verification_key(self, caller: bytes, commit_hash: bytes,
                             prover_type: str, vk: bytes) -> None:
        self._only_owner(caller)
        if commit_hash == b"\x00" * 32:
            raise Revert("CommitHashIsZero")
        self.verification_keys.setdefault(commit_hash, {})[prover_type] = vk

    # -- commit ------------------------------------------------------------
    def commit_batch(self, caller: bytes, batch_number: int,
                     new_state_root: bytes, withdrawals_root: bytes,
                     privileged_rolling_hash: bytes, last_block_hash: bytes,
                     non_privileged_count: int, commit_hash: bytes,
                     blob_versioned_hash: bytes = b"") -> None:
        self._only_owner(caller)
        self._when_not_paused()
        if batch_number != self.last_committed + 1:
            raise Revert("BatchNumberNotSuccessor")
        if batch_number in self.commitments:
            raise Revert("BatchAlreadyCommitted")
        if last_block_hash == b"\x00" * 32 or not last_block_hash:
            raise Revert("LastBlockHashIsZero")
        if privileged_rolling_hash and \
                privileged_rolling_hash != b"\x00" * 32:
            count = int.from_bytes(privileged_rolling_hash[:2], "big")
            if self.bridge.pending_versioned_hash(count) != \
                    privileged_rolling_hash:
                raise Revert("InvalidPrivilegedTransactionLogs")
        publish_root = bool(withdrawals_root
                            and withdrawals_root != b"\x00" * 32)
        if publish_root and self.bridge.withdrawal_roots.get(batch_number):
            # the publish-time guard, checked here but the publication
            # itself is deferred until all commit checks pass (a Solidity
            # revert would undo it; Python must not publish early)
            raise Revert("CommonBridge: withdrawal logs already published")
        if self.validium:
            if blob_versioned_hash:
                raise Revert("ValidiumBlobPublished")
        else:
            if not blob_versioned_hash:
                raise Revert("RollupBlobNotPublished")
        if not commit_hash or commit_hash == b"\x00" * 32:
            raise Revert("CommitHashIsZero")
        keys = self.verification_keys.get(commit_hash, {})
        for t in self.needed:
            if not keys.get(t):
                raise Revert("MissingVerificationKeyForCommit")
        if publish_root:
            self.bridge.publish_withdrawals(batch_number, withdrawals_root,
                                            caller_is_proposer=True)
        self.commitments[batch_number] = BatchCommitment(
            new_state_root=new_state_root,
            blob_versioned_hash=blob_versioned_hash,
            privileged_rolling_hash=privileged_rolling_hash or b"",
            withdrawals_root=withdrawals_root or b"",
            last_block_hash=last_block_hash,
            non_privileged_count=non_privileged_count,
            commit_hash=commit_hash)
        self.last_committed = batch_number

    # -- verify ------------------------------------------------------------
    def public_inputs(self, batch_number: int) -> bytes:
        """The statement the proofs bind (commitment reconstruction,
        _getPublicInputsFromCommitment): previous root || new root ||
        withdrawals root || privileged rolling hash || last block hash ||
        blob versioned hash."""
        cur = self.commitments[batch_number]
        prev = self.commitments.get(batch_number - 1)
        prev_root = prev.new_state_root if prev else b"\x00" * 32
        return (prev_root + cur.new_state_root
                + (cur.withdrawals_root or b"\x00" * 32)
                + (cur.privileged_rolling_hash or b"\x00" * 32)
                + cur.last_block_hash
                + (cur.blob_versioned_hash or b"\x00" * 32).ljust(32, b"\x00"))

    def verify_batches(self, caller: bytes, first_batch: int,
                       proofs: dict[str, list[bytes]], now: int = 0) -> None:
        """proofs: prover_type -> per-batch proof bytes list."""
        self._only_owner(caller)
        self._when_not_paused()
        counts = {len(v) for v in proofs.values()} or {0}
        if counts == {0}:
            raise Revert("EmptyBatchArray")
        if len(counts) != 1:
            raise Revert("BatchArrayLengthMismatch")
        n = counts.pop()
        # all-or-nothing like the contract: a revert anywhere in the loop
        # (including after remove_pending consumed queue entries) must leave
        # proposer + bridge state untouched, so snapshot and restore
        snap = (self.last_verified, dict(self.commitments),
                self.bridge.pending_index)
        try:
            for i in range(n):
                self._verify_one(first_batch + i,
                                 {t: v[i] for t, v in proofs.items()}, now)
        except Revert:
            (self.last_verified, self.commitments,
             self.bridge.pending_index) = snap
            raise

    def _verify_one(self, batch_number: int, proofs: dict[str, bytes],
                    now: int) -> None:
        if batch_number != self.last_verified + 1:
            raise Revert("BatchNotSequential")
        cur = self.commitments.get(batch_number)
        if cur is None:
            raise Revert("BatchNotCommitted")
        count = int.from_bytes((cur.privileged_rolling_hash or b"\x00" * 2)
                               [:2], "big")
        if count > 0:
            self.bridge.remove_pending(count, caller_is_proposer=True)
        if self.bridge.has_expired_privileged(now) and \
                cur.non_privileged_count != 0:
            raise Revert("ExpiredPrivilegedTransactionDeadline")
        pub = self.public_inputs(batch_number)
        for t in self.needed:
            vk = self.verification_keys.get(cur.commit_hash, {}).get(t)
            verifier = self.verifiers.get(t)
            ok = False
            if verifier is not None:
                try:
                    ok = bool(verifier(vk, pub, proofs.get(t, b"")))
                except Exception:
                    ok = False
            if not ok:
                raise Revert(f"Invalid{t.capitalize()}Proof")
        self.last_verified = batch_number
        self.commitments.pop(batch_number - 1, None)

    # -- revert (pause-gated rollback of uncommitted work) -----------------
    def revert_batch(self, caller: bytes, batch_number: int) -> None:
        self._only_owner(caller)
        if not self.paused:
            raise Revert("ExpectedPause")
        if batch_number <= self.last_verified:
            raise Revert("CannotRevertVerifiedBatch")
        if batch_number > self.last_committed:
            raise Revert("NoBatchesToRevert")
        for i in range(batch_number, self.last_committed + 1):
            self.commitments.pop(i, None)
        self.last_committed = batch_number - 1
