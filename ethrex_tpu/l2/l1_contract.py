"""On-chain L1 settlement seam: a hand-assembled bridge/proposer contract
plus an L1Client that drives it over HTTP JSON-RPC.

Parity target: the reference's OnChainProposer/CommonBridge Solidity
contracts (crates/l2/contracts/src/l1/) and the committer's real L1 tx
path.  No Solidity toolchain ships in this image, so the contract is
built by the tiny assembler below — it enforces the ORDERING rules
on-chain (contiguous commits, contiguous verified ranges never exceeding
the committed head) and records commitments + deposits; proof content
verification stays on the sequencer side exactly like InMemoryL1
(the reference delegates that to per-zkVM verifier contracts).

Contract ABI (custom one-byte dispatch; all words 32 bytes big-endian):
  0x01 commitBatch(n, commitment)     tx; reverts unless n == last+1
  0x02 verifyBatches(first, last)     tx; contiguous + committed
  0x03 deposit(recipient20)           payable tx; queues a deposit
  0x04 getDeposit(i)                  view -> (recipient32, value32)
  0x05 lastCommitted()                view -> n
  0x06 lastVerified()                 view -> n
  0x07 depositCount()                 view -> n
  0x08 commitment(n)                  view -> bytes32
"""

from __future__ import annotations

import threading

from ..crypto.keccak import keccak256
from .eth_client import EthClient, RpcError, TransportError
from .l1_client import Deposit, L1Client, L1Error, make_deposit_tx

# deposit record slots live at 2^128 + 2i (+1), far above the commitment
# range 0x1000 + n — no reachable batch number can collide
DEPOSIT_BASE = 1 << 128

# ---------------------------------------------------------------------------
# mini assembler
# ---------------------------------------------------------------------------

OPS = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "LT": 0x10,
    "GT": 0x11, "EQ": 0x14, "ISZERO": 0x15, "AND": 0x16, "SHR": 0x1C,
    "CALLVALUE": 0x34, "CALLDATALOAD": 0x35, "CODECOPY": 0x39,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52, "SLOAD": 0x54,
    "SSTORE": 0x55, "JUMP": 0x56, "JUMPI": 0x57, "JUMPDEST": 0x5B,
    "PUSH0": 0x5F, "DUP1": 0x80, "DUP2": 0x81, "DUP3": 0x82,
    "SWAP1": 0x90, "SWAP2": 0x91, "LOG1": 0xA1, "RETURN": 0xF3,
    "REVERT": 0xFD,
}


def assemble(program) -> bytes:
    """Two-pass assembler: items are mnemonics, ("PUSH", int),
    ("PUSHL", label), or ("LABEL", name).  Labels use fixed PUSH2."""
    # pass 1: layout
    size = 0
    labels = {}
    for item in program:
        if isinstance(item, str):
            size += 1
        elif item[0] == "PUSH":
            v = item[1]
            size += 1 + max(1, (v.bit_length() + 7) // 8) if v else 1
        elif item[0] == "PUSHL":
            size += 3
        elif item[0] == "LABEL":
            labels[item[1]] = size
            size += 1  # JUMPDEST
    # pass 2: emit
    out = bytearray()
    for item in program:
        if isinstance(item, str):
            out.append(OPS[item])
        elif item[0] == "PUSH":
            v = item[1]
            if v == 0:
                out.append(OPS["PUSH0"])
            else:
                raw = v.to_bytes((v.bit_length() + 7) // 8, "big")
                out.append(0x5F + len(raw))
                out += raw
        elif item[0] == "PUSHL":
            out.append(0x61)  # PUSH2
            out += labels[item[1]].to_bytes(2, "big")
        elif item[0] == "LABEL":
            out.append(OPS["JUMPDEST"])
    return bytes(out)


def _dispatch(selector: int, label: str):
    return ["DUP1", ("PUSH", selector), "EQ", ("PUSHL", label), "JUMPI"]


def _view_return():
    return [("PUSH", 0), "MSTORE", ("PUSH", 32), ("PUSH", 0), "RETURN"]


def bridge_runtime() -> bytes:
    prog = [("PUSH", 0), "CALLDATALOAD", ("PUSH", 248), "SHR"]
    for sel, label in ((1, "commit"), (2, "verify"), (3, "deposit"),
                       (4, "getdep"), (5, "view0"), (6, "view1"),
                       (7, "view2"), (8, "getcommit")):
        prog += _dispatch(sel, label)
    prog += [("PUSHL", "fail"), "JUMP"]

    prog += [("LABEL", "commit"), "POP",
             ("PUSH", 1), "CALLDATALOAD",                    # n
             "DUP1", ("PUSH", 0), "SLOAD", ("PUSH", 1), "ADD",
             "EQ", "ISZERO", ("PUSHL", "fail"), "JUMPI",     # n == last+1
             "DUP1", ("PUSH", 0), "SSTORE",                  # last = n
             ("PUSH", 33), "CALLDATALOAD", "SWAP1",
             ("PUSH", 0x1000), "ADD", "SSTORE",              # slot 0x1000+n
             ("PUSH", 1), "CALLDATALOAD", ("PUSH", 0), "MSTORE",
             ("PUSH", 1), ("PUSH", 32), ("PUSH", 0), "LOG1",
             "STOP"]

    prog += [("LABEL", "verify"), "POP",
             ("PUSH", 1), "CALLDATALOAD",                    # first
             "DUP1", ("PUSH", 1), "SLOAD", ("PUSH", 1), "ADD",
             "EQ", "ISZERO", ("PUSHL", "fail"), "JUMPI",
             ("PUSH", 33), "CALLDATALOAD",                   # first last
             "DUP2", "DUP2", "LT", ("PUSHL", "fail"), "JUMPI",  # last<first
             "DUP1", ("PUSH", 0), "SLOAD", "LT",             # committed<last
             ("PUSHL", "fail"), "JUMPI",
             "SWAP1", "POP", ("PUSH", 1), "SSTORE",          # verified=last
             ("PUSH", 33), "CALLDATALOAD", ("PUSH", 0), "MSTORE",
             ("PUSH", 2), ("PUSH", 32), ("PUSH", 0), "LOG1",
             "STOP"]

    prog += [("LABEL", "deposit"), "POP",
             ("PUSH", 2), "SLOAD",                           # i
             "DUP1", "DUP1", "ADD", ("PUSH", DEPOSIT_BASE), "ADD",  # i slot
             ("PUSH", 1), "CALLDATALOAD", ("PUSH", 96), "SHR",
             "SWAP1", "SSTORE",                              # [recipient]
             "DUP1", "DUP1", "ADD", ("PUSH", DEPOSIT_BASE + 1), "ADD",
             "CALLVALUE", "SWAP1", "SSTORE",                 # [value]
             ("PUSH", 1), "ADD", ("PUSH", 2), "SSTORE",      # count = i+1
             ("PUSH", 3), ("PUSH", 0), ("PUSH", 0), "LOG1",
             "STOP"]

    prog += [("LABEL", "getdep"), "POP",
             ("PUSH", 1), "CALLDATALOAD",                    # i
             "DUP1", "DUP1", "ADD", ("PUSH", DEPOSIT_BASE), "ADD", "SLOAD",
             ("PUSH", 0), "MSTORE",
             "DUP1", "ADD", ("PUSH", DEPOSIT_BASE + 1), "ADD", "SLOAD",
             ("PUSH", 32), "MSTORE",
             ("PUSH", 64), ("PUSH", 0), "RETURN"]

    prog += [("LABEL", "view0"), "POP", ("PUSH", 0), "SLOAD"] \
        + _view_return()
    prog += [("LABEL", "view1"), "POP", ("PUSH", 1), "SLOAD"] \
        + _view_return()
    prog += [("LABEL", "view2"), "POP", ("PUSH", 2), "SLOAD"] \
        + _view_return()
    prog += [("LABEL", "getcommit"), "POP",
             ("PUSH", 1), "CALLDATALOAD", ("PUSH", 0x1000), "ADD",
             "SLOAD"] + _view_return()
    prog += [("LABEL", "fail"), ("PUSH", 0), ("PUSH", 0), "REVERT"]
    return assemble(prog)


def bridge_initcode() -> bytes:
    runtime = bridge_runtime()
    # PUSH2 len, PUSH1 ofs, PUSH0, CODECOPY, PUSH2 len, PUSH0, RETURN
    prefix_len = 3 + 2 + 1 + 1 + 3 + 1 + 1
    return (bytes([0x61]) + len(runtime).to_bytes(2, "big")
            + bytes([0x60, prefix_len, 0x5F, 0x39])
            + bytes([0x61]) + len(runtime).to_bytes(2, "big")
            + bytes([0x5F, 0xF3]) + runtime)


def _word(v: int) -> bytes:
    return v.to_bytes(32, "big")


# ---------------------------------------------------------------------------
# the RPC-backed L1 client
# ---------------------------------------------------------------------------

class RpcL1Client(L1Client):
    """L1Client over a real JSON-RPC endpoint + the bridge contract.

    The proof-content checks (needed prover types, ProgramOutput binding
    to the batch's state/messages roots) run client-side against a local
    record validated against the ON-CHAIN commitment word, mirroring
    InMemoryL1's rules; ordering rules are enforced by the contract and
    surface as reverted transactions."""

    def __init__(self, client: EthClient, contract: bytes, secret: int,
                 needed_prover_types: list[str],
                 l2_chain_id: int | None = None):
        self.client = client
        self.contract = contract
        self.secret = secret
        self.needed = list(needed_prover_types)
        self.l2_chain_id = l2_chain_id
        self.records: dict[int, tuple[bytes, bytes, bytes]] = {}
        #   number -> (state_root, commitment, messages_root)
        self.consumed_deposits = 0
        self.lock = threading.RLock()

    @classmethod
    def deploy(cls, client: EthClient, secret: int,
               needed_prover_types: list[str],
               l2_chain_id: int | None = None) -> "RpcL1Client":
        rec = client.send_tx_bump_gas_exponential_backoff(
            secret, to=None, data=bridge_initcode(), gas_limit=2_000_000)
        if int(rec.get("status", "0x0"), 16) != 1:
            raise L1Error("bridge deployment reverted")
        addr = bytes.fromhex(rec["contractAddress"][2:])
        return cls(client, addr, secret, needed_prover_types, l2_chain_id)

    # ---- tx path ----
    def _tx(self, data: bytes, value: int = 0) -> dict:
        try:
            rec = self.client.send_tx_bump_gas_exponential_backoff(
                self.secret, to=self.contract, data=data, value=value)
        except (RpcError, TransportError) as e:
            raise L1Error(f"L1 tx failed: {e}")
        if int(rec.get("status", "0x0"), 16) != 1:
            raise L1Error("L1 tx reverted")
        return rec

    def _view(self, data: bytes) -> bytes:
        try:
            return self.client.eth_call(self.contract, data)
        except (RpcError, TransportError) as e:
            raise L1Error(f"L1 view call failed: {e}")

    # ---- leader lease ----
    def supports_leases(self) -> bool:
        """The dev contract bytecode carries no lease cell; sequencer HA
        against a real L1 needs an OnChainProposer with the lease slot
        (docs/SEQUENCER_HA.md) — until then `--ha-role` refuses this
        client rather than running unfenced."""
        return False

    # ---- OnChainProposer ----
    def commit_batch(self, number, new_state_root, commitment,
                     privileged_tx_hashes=(),
                     messages_root=b"\x00" * 32, epoch=None) -> bytes:
        with self.lock:
            # privileged txs must match the bridge's deposit queue 1:1
            # (client-side mirror of OnChainProposer's digest check)
            deposits = self.get_deposits(self.consumed_deposits)
            cursor = 0
            for h in privileged_tx_hashes:
                if cursor >= len(deposits):
                    raise L1Error("privileged tx without matching deposit")
                if self.l2_chain_id is not None:
                    expected = make_deposit_tx(self.l2_chain_id,
                                               deposits[cursor]).hash
                    if h != expected:
                        raise L1Error("privileged tx does not match "
                                      f"deposit {deposits[cursor].index}")
                cursor += 1
            already = self.last_committed_batch() >= number and \
                self._view(b"\x08" + _word(number))[-32:] == commitment
            if not already:
                try:
                    self._tx(b"\x01" + _word(number) + commitment)
                except L1Error:
                    # the tx may have landed even though the client saw a
                    # failure (timeout after acceptance): reconcile with
                    # the chain before declaring the commit failed
                    if not (self.last_committed_batch() >= number
                            and self._view(b"\x08" + _word(number))[-32:]
                            == commitment):
                        raise
            self.consumed_deposits += cursor
            self.records[number] = (bytes(new_state_root),
                                    bytes(commitment), bytes(messages_root))
            return keccak256(b"commit" + number.to_bytes(8, "big")
                             + commitment)

    def verify_batches(self, first, last, proofs, epoch=None) -> bytes:
        import json as _json

        from ..guest.execution import ProgramOutput

        with self.lock:
            for t in self.needed:
                batch_proofs = proofs.get(t)
                if not batch_proofs or \
                        len(batch_proofs) != last - first + 1:
                    raise L1Error(f"missing {t} proofs")
                for offset, raw in enumerate(batch_proofs):
                    number = first + offset
                    rec = self.records.get(number)
                    if rec is None:
                        raise L1Error(f"unknown batch {number}")
                    state_root, commitment, messages_root = rec
                    onchain = self._view(b"\x08" + _word(number))
                    if onchain[-32:] != commitment:
                        raise L1Error(
                            f"on-chain commitment mismatch for {number}")
                    try:
                        obj = _json.loads(raw)
                        out = ProgramOutput.decode(
                            bytes.fromhex(obj["output"][2:]))
                    except (ValueError, KeyError, TypeError):
                        raise L1Error(f"unparseable {t} proof")
                    if out.final_state_root != state_root:
                        raise L1Error(
                            f"proof state root mismatch for {number}")
                    if out.messages_root != messages_root:
                        raise L1Error(
                            f"proof messages root mismatch for {number}")
            self._tx(b"\x02" + _word(first) + _word(last))
            return keccak256(b"verify" + first.to_bytes(8, "big")
                             + last.to_bytes(8, "big"))

    def last_committed_batch(self) -> int:
        return int.from_bytes(self._view(b"\x05"), "big")

    def last_verified_batch(self) -> int:
        return int.from_bytes(self._view(b"\x06"), "big")

    def get_committed_commitment(self, number: int) -> bytes | None:
        if self.last_committed_batch() < number:
            return None
        return self._view(b"\x08" + _word(number))[-32:]

    def get_committed_state_root(self, number: int) -> bytes | None:
        with self.lock:
            rec = self.records.get(number)
            return rec[0] if rec else None

    def get_block_number(self) -> int:
        # raw transport errors propagate: the sequencer's actor loop
        # classifies them as transient (unlike deterministic L1Error)
        return self.client.block_number()

    # ---- CommonBridge ----
    def deposit(self, recipient: bytes, amount: int) -> None:
        self._tx(b"\x03" + recipient, value=amount)

    def deposit_count(self) -> int:
        return int.from_bytes(self._view(b"\x07"), "big")

    def get_deposits(self, since_index: int) -> list[Deposit]:
        count = self.deposit_count()
        out = []
        for i in range(since_index, count):
            raw = self._view(b"\x04" + _word(i))
            recipient = raw[12:32]
            amount = int.from_bytes(raw[32:64], "big")
            out.append(Deposit(l1_tx_hash=keccak256(b"dep" + _word(i)),
                               recipient=recipient, amount=amount,
                               index=i))
        return out
