"""OnChainProposer as EVM BYTECODE, settled through our own EVM.

The round-4 port (l2/proposer_rules.py) re-expressed the reference's
Solidity state machine in Python; this module closes the remaining gap
(VERDICT #8): the commit/verify state machine is hand-assembled to EVM
bytecode (l2/evm_asm.py — no solc in the toolchain) and the dev L1
executes it with the SAME interpreter that runs L2 blocks, so settlement
exercises real contract code: selector dispatch, storage mappings via
KECCAK256, revert identifiers, only-owner/pause guards, the
batch-succession and sequential-verify rules, and a STATICCALL into a
registered verifier (the on-chain verifier seat — here a dev precompile
hook that runs the in-process proof checks).

Reference seat: crates/l2/contracts/src/l1/OnChainProposer.sol:226-687
(commitBatch/verifyBatches guards) + cmd/ethrex/l2/deployer.rs.

Storage layout:
    slot 0  lastCommittedBatch          slot 3  owner
    slot 1  lastVerifiedBatch           map 4   batch -> state root
    slot 2  paused                      map 5   batch -> messages root
                                        map 6   batch -> commit hash
"""

from __future__ import annotations

from ..crypto.keccak import keccak256
from .evm_asm import assemble

VERIFIER_ADDRESS = bytes.fromhex("00000000000000000000000000000000000000f1")
PROPOSER_ADDRESS = bytes.fromhex("000000000000000000000000000000000000c0de")


def selector(sig: str) -> int:
    return int.from_bytes(keccak256(sig.encode())[:4], "big")

SEL_COMMIT = selector("commitBatch(uint256,bytes32,bytes32,bytes32)")
SEL_VERIFY = selector("verifyBatches(uint256,uint256)")
SEL_LAST_COMMITTED = selector("lastCommittedBatch()")
SEL_LAST_VERIFIED = selector("lastVerifiedBatch()")
SEL_BATCH_ROOT = selector("batchStateRoot(uint256)")
SEL_PAUSE = selector("pause()")
SEL_UNPAUSE = selector("unpause()")


def _rv(ident: str) -> list:
    """revert with the padded ascii identifier (one 32-byte word)."""
    return [("PUSH", int.from_bytes(ident.encode(), "big")),
            ("PUSH", 0), "MSTORE", ("PUSH", 32), ("PUSH", 0), "REVERT"]


def _only_owner(tag: str) -> list:
    return ["CALLER", ("PUSH", 3), "SLOAD", "EQ", ("PUSHL", tag), "JUMPI",
            *_rv("OwnableUnauthorizedAccount"), ("LABEL", tag)]


def _not_paused(tag: str) -> list:
    return [("PUSH", 2), "SLOAD", "ISZERO", ("PUSHL", tag), "JUMPI",
            *_rv("EnforcedPause"), ("LABEL", tag)]


def _map_hash(slot: int, scratch: int = 0x80) -> list:
    """keccak(key || slot) with the key already at mem[scratch]."""
    return [("PUSH", slot), ("PUSH", scratch + 32), "MSTORE",
            ("PUSH", 64), ("PUSH", scratch), "KECCAK256"]


def build_runtime() -> bytes:
    a: list = []
    # ---- dispatch --------------------------------------------------------
    a += [("PUSH", 0), "CALLDATALOAD", ("PUSH", 224), "SHR"]
    for sel, tag in ((SEL_COMMIT, "fn_commit"), (SEL_VERIFY, "fn_verify"),
                     (SEL_LAST_COMMITTED, "fn_lc"),
                     (SEL_LAST_VERIFIED, "fn_lv"),
                     (SEL_BATCH_ROOT, "fn_root"),
                     (SEL_PAUSE, "fn_pause"), (SEL_UNPAUSE, "fn_unpause")):
        a += ["DUP1", ("PUSH", sel), "EQ", ("PUSHL", tag), "JUMPI"]
    a += _rv("UnknownSelector")

    # ---- commitBatch(number, newStateRoot, messagesRoot, commitHash) ----
    a += [("LABEL", "fn_commit")]
    a += _only_owner("cm_own")
    a += _not_paused("cm_pse")
    a += [("PUSH", 4), "CALLDATALOAD"]                       # [n]
    a += ["DUP1", ("PUSH", 0), "SLOAD", ("PUSH", 1), "ADD", "EQ",
          ("PUSHL", "cm_seq"), "JUMPI",
          *_rv("BatchNumberNotSuccessor"), ("LABEL", "cm_seq")]
    a += [("PUSH", 100), "CALLDATALOAD", "ISZERO", "ISZERO",
          ("PUSHL", "cm_chz"), "JUMPI",
          *_rv("CommitHashIsZero"), ("LABEL", "cm_chz")]
    # roots[n] / msgs[n] / commits[n]
    a += ["DUP1", ("PUSH", 0x80), "MSTORE"]                  # scratch key
    for slot, arg in ((4, 36), (5, 68), (6, 100)):
        a += _map_hash(slot)                                 # [n, h]
        a += [("PUSH", arg), "CALLDATALOAD", "SWAP1", "SSTORE"]
    a += [("PUSH", 0), "SSTORE", "STOP"]                     # lastCommitted

    # ---- verifyBatches(first, count) ------------------------------------
    a += [("LABEL", "fn_verify")]
    a += _only_owner("vf_own")
    a += _not_paused("vf_pse")
    a += [("PUSH", 4), "CALLDATALOAD"]                       # [f]
    a += ["DUP1", ("PUSH", 1), "SLOAD", ("PUSH", 1), "ADD", "EQ",
          ("PUSHL", "vf_seq"), "JUMPI",
          *_rv("BatchNotSequential"), ("LABEL", "vf_seq")]
    a += [("PUSH", 36), "CALLDATALOAD"]                      # [f, c]
    a += ["DUP1", "ISZERO", "ISZERO", ("PUSHL", "vf_ne"), "JUMPI",
          *_rv("EmptyBatchArray"), ("LABEL", "vf_ne")]
    a += ["DUP2", "ADD", ("PUSH", 1), "SWAP1", "SUB"]        # [f, last]
    a += ["DUP1", ("PUSH", 0), "SLOAD", "LT", "ISZERO",
          ("PUSHL", "vf_cm"), "JUMPI",
          *_rv("BatchNotCommitted"), ("LABEL", "vf_cm")]
    a += ["DUP2"]                                            # [f, last, i]
    a += [("LABEL", "vf_loop")]
    a += ["DUP2", "DUP2", "GT", ("PUSHL", "vf_done"), "JUMPI"]
    # calldata for the verifier: [i, root, msgs, commit] at 0..128
    a += ["DUP1", ("PUSH", 0), "MSTORE"]
    a += ["DUP1", ("PUSH", 0x80), "MSTORE"]
    for slot, off in ((4, 32), (5, 64), (6, 96)):
        a += _map_hash(slot) + ["SLOAD", ("PUSH", off), "MSTORE"]
    a += [("PUSH", 32), ("PUSH", 0xC0), ("PUSH", 128), ("PUSH", 0),
          ("PUSH", int.from_bytes(VERIFIER_ADDRESS, "big")), "GAS",
          "STATICCALL"]
    a += [("PUSH", 0xC0), "MLOAD", ("PUSH", 1), "EQ", "AND",
          ("PUSHL", "vf_next"), "JUMPI",
          *_rv("InvalidProof"), ("LABEL", "vf_next")]
    a += [("PUSH", 1), "ADD", ("PUSHL", "vf_loop"), "JUMP"]
    a += [("LABEL", "vf_done"), "POP", ("PUSH", 1), "SSTORE", "STOP"]

    # ---- getters / admin ------------------------------------------------
    for tag, slot in (("fn_lc", 0), ("fn_lv", 1)):
        a += [("LABEL", tag), ("PUSH", slot), "SLOAD", ("PUSH", 0),
              "MSTORE", ("PUSH", 32), ("PUSH", 0), "RETURN"]
    a += [("LABEL", "fn_root"), ("PUSH", 4), "CALLDATALOAD",
          ("PUSH", 0x80), "MSTORE", *_map_hash(4), "SLOAD",
          ("PUSH", 0), "MSTORE", ("PUSH", 32), ("PUSH", 0), "RETURN"]
    a += [("LABEL", "fn_pause"), *_only_owner("ps_own"),
          ("PUSH", 1), ("PUSH", 2), "SSTORE", "STOP"]
    a += [("LABEL", "fn_unpause"), *_only_owner("up_own"),
          ("PUSH", 0), ("PUSH", 2), "SSTORE", "STOP"]
    return assemble(a)


def decode_revert(output: bytes) -> str:
    return output.lstrip(b"\x00").decode("ascii", "replace")
