"""Recursive proof aggregation stage: N settled-ready batch proofs ->
one aggregated proof -> one L1 verify tx (docs/AGGREGATION.md).

The sequencer's per-batch path (`send_proofs`) posts one full proof per
batch per prover type.  `ProofAggregator.step()` instead collects the
next run of verified-but-unsettled batches from the `RollupStore`
(committed, fully proven, above the L1's `last_verified_batch`), audits
each proof exactly like the per-batch path, then:

  * STARK-carrying proofs (the tpu backend's FORMAT_STARK output) are
    folded cross-batch: every batch's inner STARKs feed ONE outer
    FriVerifyAir recursion proof via `stark.aggregate.aggregate_groups`,
    and the per-batch payloads ship with their FRI Merkle path data
    stripped — the dominant share of proof bytes.  The aggregate is
    re-verified host-side (`verify_aggregated`) before submission,
    mirroring how `send_proofs` audits before `verify_batches`.
  * proofs with no STARK body (the exec backend) degrade to an output
    bundle: the same one-payload, one-tx settlement shape without a
    recursion proof.

Settlement goes through `L1Client.verify_batches_aggregated`, which
binds every batch's committed output (state root + messages root) just
like the per-batch entry point but charges ONE L1 tx for the range —
the N->1 cost amortization ROADMAP item 4 names.  Per-batch settlement
stays available as the fallback: the sequencer only defers to this
stage when the pending run reaches `min_batches`.

Crash safety: `step()` drops an `aggregation_inflight` marker in the
rollup store's meta table before touching the L1 and clears it after
the local verified flags land.  Recovery needs no replay logic — the
range always starts at `l1.last_verified_batch() + 1` and the L1
rejects non-contiguous verification, so double-settling is structurally
impossible; startup reconciliation (`Sequencer._reconcile_with_l1`)
adopts verified flags the crash window lost, and the marker is just
observability for how the crash resolved.
"""

from __future__ import annotations

import json
import logging
import threading

from ..prover import protocol
from ..utils import faults, tracing
from .l1_client import L1Client
from .rollup_store import RollupStore

log = logging.getLogger("ethrex_tpu.l2.aggregator")

INFLIGHT_META_KEY = "aggregation_inflight"


class AggregatorError(ValueError):
    pass


def slim_entry(proof: dict) -> dict:
    """The outputs-only settlement entry of one batch proof: everything
    `verify_batches_aggregated` binds (the committed ProgramOutput) and
    nothing it does not.  Used for proofs with no STARK body and by the
    aligned path, whose full proofs were already verified off-chain."""
    return {"backend": proof.get("backend"),
            "format": proof.get("format"),
            "output": proof["output"], "proof": None}


def bundle_payload(entries: list[dict], first: int, last: int) -> dict:
    """A degenerate (recursion-free) aggregate payload: one settlement
    object covering `first..last` out of outputs-only entries."""
    return {"format": "aggregate", "first": first, "last": last,
            "proofs": entries, "outer": None}


class ProofAggregator:
    """Collects, recursively aggregates, and settles batch proof runs.

    Drive with `step()` (the sequencer's `aggregate_proofs` actor does);
    every call settles at most one contiguous range.  Thread-safe with
    respect to its own stats; the rollup/L1 stores carry their own
    locks."""

    def __init__(self, rollup: RollupStore, l1: L1Client,
                 coordinator=None,
                 needed_types: list[str] | None = None,
                 commit_hash: str = protocol.PROTOCOL_VERSION,
                 min_batches: int = 2, max_batches: int = 16,
                 params=None, outer_params=None,
                 audit_aggregate: bool = True, epoch_source=None):
        self.rollup = rollup
        self.l1 = l1
        self.coordinator = coordinator
        # sequencer HA: callable returning the leader's fencing epoch
        # (None = unfenced single-sequencer mode); stamped on the
        # aggregated settlement tx so a deposed leader cannot settle
        self.epoch_source = epoch_source or (lambda: None)
        self.needed = list(needed_types or [protocol.PROVER_TPU])
        self.commit_hash = commit_hash
        self.min_batches = max(1, min_batches)
        self.max_batches = max(self.min_batches, max_batches)
        self.params = params
        self.outer_params = outer_params
        self.audit_aggregate = audit_aggregate
        self.lock = threading.RLock()
        self.aggregations_total = 0
        self.batches_aggregated_total = 0
        self.last_range: tuple[int, int] | None = None
        self.last_error: str | None = None
        self.recovered: str | None = None
        self._recover_inflight()

    # ------------------------------------------------------------------
    def _recover_inflight(self):
        """Classify a crash-mid-aggregation marker left by a previous
        run.  Either way the marker is cleared and normal stepping
        resumes — the L1-anchored range start makes redo/skip automatic;
        this only records WHICH side of the L1 call the crash fell on."""
        marker = self.rollup.get_meta(INFLIGHT_META_KEY)
        if not marker:
            return
        try:
            settled = self.l1.last_verified_batch() >= int(marker["last"])
        except Exception:  # noqa: BLE001 — L1 unreachable: leave marker
            return
        self.recovered = "settled-before-crash" if settled \
            else "lost-before-settlement"
        log.warning("recovered aggregation marker for batches %s..%s: %s",
                    marker.get("first"), marker.get("last"),
                    self.recovered)
        self.rollup.set_meta(INFLIGHT_META_KEY, None)

    def _slot_type(self, n: int, t: str) -> str:
        """Quarantine substitution, same rule as send_proofs."""
        if self.coordinator is None:
            return t
        eff = self.coordinator.effective_needed_types(n, [t])
        return eff[0] if eff else t

    # ------------------------------------------------------------------
    def _collect(self) -> tuple[int, int] | None:
        """The next contiguous committed + fully-proven run above the
        L1's verified tip, capped at max_batches."""
        first = self.l1.last_verified_batch() + 1
        last = first - 1
        while last - first + 1 < self.max_batches:
            batch = self.rollup.get_batch(last + 1)
            if batch is None or not batch.committed:
                break
            types = [self._slot_type(last + 1, t) for t in self.needed]
            if not self.rollup.batch_fully_proven(last + 1, types):
                break
            last += 1
        if last - first + 1 < self.min_batches:
            return None
        return first, last

    def _audit(self, first: int, last: int) -> bool:
        """Per-proof audit, identical in depth to send_proofs' check:
        coverage anti-downgrade + full verify (witness replay when the
        backend supports it).  Invalid proofs are deleted so the fleet
        re-proves them."""
        from ..guest.execution import ProgramInput
        from ..prover.backend import get_backend

        ok_all = True
        for t in self.needed:
            for n in range(first, last + 1):
                st = self._slot_type(n, t)
                backend = get_backend(st)
                proof = self.rollup.get_proof(n, st)
                batch = self.rollup.get_batch(n)
                ok = proof is not None
                if ok and batch is not None and not backend.check_coverage(
                        proof, batch.vm_mode):
                    ok = False
                if ok:
                    stored = self.rollup.get_prover_input(
                        n, self.commit_hash)
                    if hasattr(backend, "verify_with_input") \
                            and stored is not None:
                        ok = backend.verify_with_input(
                            proof, ProgramInput.from_json(stored))
                    else:
                        ok = backend.verify(proof)
                if not ok:
                    self.rollup.delete_proof(n, st)
                    self.last_error = f"invalid {st} proof for batch {n}"
                    log.warning("aggregation audit failed: %s",
                                self.last_error)
                    ok_all = False
        return ok_all

    # ------------------------------------------------------------------
    def _build_payload(self, t: str, first: int, last: int) -> dict:
        """One aggregate payload for prover type t over first..last."""
        from ..prover.backend import get_backend
        from ..stark import aggregate as agg_mod

        entries: list[tuple[str, dict]] = []
        for n in range(first, last + 1):
            st = self._slot_type(n, t)
            proof = self.rollup.get_proof(n, st)
            if proof is None:
                raise AggregatorError(f"no {st} proof for batch {n}")
            entries.append((st, proof))
        if not any(isinstance(p.get("proof"), dict) for _, p in entries):
            # exec fleet (or any proof-less backend): outputs bundle
            return bundle_payload([slim_entry(p) for _, p in entries],
                                  first, last)
        groups: list[tuple[list, list]] = []
        for st, p in entries:
            if not isinstance(p.get("proof"), dict):
                groups.append(([], []))
                continue
            backend = get_backend(st)
            if not hasattr(backend, "stark_components"):
                raise AggregatorError(
                    f"backend {st} carries STARK proofs but exposes no "
                    f"components for recursion")
            groups.append(backend.stark_components(p))
        params = self.params if self.params is not None \
            else _default_params()
        agg, slices = agg_mod.aggregate_groups(groups, params,
                                               self.outer_params)
        if self.audit_aggregate:
            flat_airs = [a for airs, _ in groups for a in airs]
            agg_mod.verify_aggregated(flat_airs, agg, params,
                                      self.outer_params)
        out_entries = []
        for (st, p), (start, stop) in zip(entries, slices):
            if isinstance(p.get("proof"), dict):
                out_entries.append(
                    _reassemble(p, agg.inners[start:stop]))
            else:
                out_entries.append(slim_entry(p))
        return {"format": "aggregate", "first": first, "last": last,
                "proofs": out_entries, "outer": agg.outer,
                "max_depth": agg.max_depth,
                "seg_periods": agg.seg_periods}

    # ------------------------------------------------------------------
    def step(self) -> tuple[int, int] | None:
        """Aggregate and settle the next pending run; returns the settled
        (first, last) range or None when there is nothing (yet) to do."""
        from ..utils.metrics import record_aggregation, \
            record_verified_batch

        work = self._collect()
        if work is None:
            return None
        first, last = work
        if not self._audit(first, last):
            return None
        with tracing.span("aggregate.prove", first=first, last=last):
            # two-leg fault site: before = recursion work lost mid-build,
            # after = proof built but the settlement leg lost
            faults.inject("aggregate.prove")
            payloads = {t: self._build_payload(t, first, last)
                        for t in self.needed}
            faults.inject("aggregate.prove")
        wire = {t: json.dumps(p, separators=(",", ":")).encode()
                for t, p in payloads.items()}
        # the marker brackets the settlement call: a crash inside this
        # window is classified (settled vs lost) on the next startup
        self.rollup.set_meta(INFLIGHT_META_KEY,
                             {"first": first, "last": last})
        self.l1.verify_batches_aggregated(first, last, wire,
                                          epoch=self.epoch_source())
        count = last - first + 1
        for n in range(first, last + 1):
            trace = self.coordinator.batch_traces.get(n) \
                if self.coordinator is not None else None
            with tracing.trace_context(trace):
                with tracing.span("proof.settle_aggregated", batch=n):
                    self.rollup.set_verified(n)
        self.rollup.set_meta(INFLIGHT_META_KEY, None)
        with self.lock:
            self.aggregations_total += 1
            self.batches_aggregated_total += count
            self.last_range = (first, last)
            self.last_error = None
        record_aggregation(count, last)
        record_verified_batch(last)
        try:
            from ..perf.chain_path import CHAIN_PATH

            CHAIN_PATH.batches_settled(first, last)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        log.info("aggregated batches %d..%d into one settlement "
                 "(%d proofs -> 1 L1 tx)", first, last, count)
        return first, last

    # ------------------------------------------------------------------
    def stats_json(self) -> dict:
        """Health-endpoint view (ethrex_health l2.aggregation)."""
        with self.lock:
            return {
                "aggregations": self.aggregations_total,
                "batchesAggregated": self.batches_aggregated_total,
                "lastRange": list(self.last_range)
                if self.last_range else None,
                "minBatches": self.min_batches,
                "maxBatches": self.max_batches,
                "lastError": self.last_error,
                "recoveredInflight": self.recovered,
                "inflight": self.rollup.get_meta(INFLIGHT_META_KEY),
            }


def _default_params():
    from ..stark.prover import StarkParams

    return StarkParams()


def _reassemble(proof: dict, inners: list[dict]) -> dict:
    """Substitute path-stripped inner proofs back into a tpu batch
    proof's dict layout (inner order matches TpuBackend._reconstruct:
    state, binding, vm?, tok?, bytecode...)."""
    out = dict(proof)
    out["state_proof"] = inners[0]
    out["proof"] = inners[1]
    cursor = 2
    if proof.get("vm") is not None and "vm_proof" in proof:
        out["vm_proof"] = inners[cursor]
        cursor += 1
    if "tok_proof" in proof:
        out["tok_proof"] = inners[cursor]
        cursor += 1
    if "bc_proofs" in proof:
        out["bc_proofs"] = inners[cursor:cursor
                                  + len(proof["bc_proofs"])]
    return out
